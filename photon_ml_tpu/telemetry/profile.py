"""Executable-level roofline profiler: sampled HONEST timing per compiled
executable, bound-class attribution, and HBM high-watermarks.

The fourth observability layer (span -> phase -> fleet -> executable):
PR 1/3 measure wall time and HBM occupancy, PR 4 (telemetry.xla) accounts
compiles and static cost, but nothing attributed *device time* to an
individual executable — and PERF_NOTES documents why the obvious attempt
lies: ``block_until_ready()`` is a NO-OP through the device tunnel, so a
naive ``time.monotonic()`` bracket around a dispatch measures only the
async enqueue ("2386 TFLOP/s"). The only true synchronization is a
device->host fetch, and the only sanctioned fetch is
:func:`telemetry.device.sync_fetch`.

So this module hooks every ``instrumented_jit`` dispatch (the
``xla.set_dispatch_profiler`` hook, armed at ``telemetry`` import) and:

- counts every dispatch per ``(name, signature)`` dispatch key — the same
  key the executable registry uses, so shardings stay distinct entries
  and merge per NAME for reporting;
- every Nth dispatch per entry (``PHOTON_PROFILE_SAMPLE_EVERY``, default
  :data:`DEFAULT_SAMPLE_EVERY`; the FIRST dispatch of every entry is
  always sampled so short runs still profile), takes one honest
  measurement: clock the dispatch, then fetch one output leaf through
  ``sync_fetch`` so the clock stops only when the device is actually
  done. Sampling keeps steady-state overhead under the 2% budget
  (asserted in tests via the ``profile.overhead_seconds`` counter);
- subtracts nested sampled dispatches (tracing an outer executable can
  dispatch inner ones) via a thread-local measurement stack, yielding
  per-executable EXCLUSIVE seconds;
- derives, against :func:`telemetry.xla.device_peaks`: MFU, arithmetic
  intensity (FLOPs / byte), and a roofline **bound class** —
  MXU-bound / VPU-bound / HBM-bound / dispatch-bound (see
  :func:`bound_class`);
- cross-checks the timing honesty itself: a measured rate above the
  resolved device peak is physically impossible, so it flags
  ``timing_suspect`` instead of reporting a fake number (the PERF_NOTES
  trap, machine-detected);
- samples per-device HBM high-watermarks (``memory.
  record_device_watermarks``) on the same cadence, attributed to the
  open span's phase;
- optionally arms a ``jax.profiler`` capture window around the Kth
  dispatch (:func:`configure_xprof`; ``cli train --xprof-dir``),
  CPU-guarded so the capture machinery cannot wedge test runs.

Everything is published as ``profile.exec.<name>.<field>`` metrics so run
reports rebuilt from a metrics JSONL can render the Hot-executables table
offline, mirroring the ``xla.exec.*`` convention (names may contain dots;
field names never do).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from time import monotonic as _monotonic
from typing import Any, Callable, Optional

from photon_ml_tpu.telemetry import device, memory, metrics, trace, xla

__all__ = [
    "ProfileEntry",
    "ProfileRegistry",
    "PROFILE_REGISTRY",
    "DEFAULT_SAMPLE_EVERY",
    "BOUND_UNKNOWN",
    "BOUND_MXU",
    "BOUND_VPU",
    "BOUND_HBM",
    "BOUND_DISPATCH",
    "BOUND_CLASS_NAMES",
    "bound_class",
    "bound_class_name",
    "profile_dispatch",
    "install",
    "publish_metrics",
    "merged_profiles",
    "exclusive_seconds_by_name",
    "set_sample_every",
    "set_clock",
    "configure_xprof",
    "stop_xprof",
    "set_xprof_hooks",
    "reset",
]

logger = logging.getLogger("photon_ml_tpu.telemetry.profile")

#: Sample one honest (fetch-synchronized) timing every this many
#: dispatches of one (name, signature) entry. 1/64 sampling bounds the
#: worst case — a sampled dispatch that costs as much again in sync —
#: at ~1.6%, inside the 2% overhead budget the tests assert.
DEFAULT_SAMPLE_EVERY = 64

#: Roofline bound classes (numeric codes so they survive a metrics
#: round trip as gauges; 0 must stay "unknown" — absence of evidence).
BOUND_UNKNOWN = 0
BOUND_MXU = 1
BOUND_VPU = 2
BOUND_HBM = 3
BOUND_DISPATCH = 4

BOUND_CLASS_NAMES = {
    BOUND_UNKNOWN: "unknown",
    BOUND_MXU: "MXU-bound",
    BOUND_VPU: "VPU-bound",
    BOUND_HBM: "HBM-bound",
    BOUND_DISPATCH: "dispatch-bound",
}

#: An executable whose roofline-predicted time is under this fraction of
#: its MEASURED time is dominated by dispatch/launch overhead, not by the
#: device — "make the kernel faster" would be the wrong fix.
DISPATCH_BOUND_RATIO = 0.1

#: Compute-side executables below this MFU are classed VPU-bound: the
#: MXU is idle and throughput tracks the vector unit (masking, scatter,
#: elementwise) — the paper's "VPU-mask-bound" claim, as a threshold.
VPU_MFU_THRESHOLD = 0.05

# test/override hooks (cleared by reset(); plain attribute swaps, same
# discipline as xla._analysis_provider: torn reads see old-or-new, both
# valid)
_clock: Callable[[], float] = _monotonic
_sample_every: Optional[int] = None
_sample_every_env_cache: Optional[int] = None


def set_clock(clock: Optional[Callable[[], float]]) -> None:
    """Override the sampler's clock (forged-clock honesty tests). ``None``
    restores ``time.monotonic``. The ``sync_fetch`` crossing keeps its own
    real clock either way — only the per-dispatch measurement is forged."""
    global _clock
    _clock = _monotonic if clock is None else clock


def set_sample_every(n: Optional[int]) -> None:
    """Override the sampling period (tests / unusual runs). ``None``
    restores the ``PHOTON_PROFILE_SAMPLE_EVERY`` env / default chain."""
    global _sample_every
    _sample_every = None if n is None else max(1, int(n))


def _resolve_sample_every() -> int:
    if _sample_every is not None:
        return _sample_every
    global _sample_every_env_cache
    if _sample_every_env_cache is None:
        n = DEFAULT_SAMPLE_EVERY
        raw = os.environ.get("PHOTON_PROFILE_SAMPLE_EVERY")
        if raw:
            try:
                n = max(1, int(raw))
            except ValueError:
                logger.warning(
                    "ignoring malformed PHOTON_PROFILE_SAMPLE_EVERY=%r", raw
                )
        _sample_every_env_cache = n
    return _sample_every_env_cache


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ProfileEntry:
    """Profiled state of one (name, signature) dispatch-key entry.

    ``sampled_seconds`` are honest (fetch-synchronized) inclusive wall
    seconds over the SAMPLED dispatches only; ``est_exclusive_seconds``
    extrapolates to all dispatches. ``flops`` / ``bytes_accessed`` are the
    per-dispatch cost-analysis estimates copied from the executable
    record; ``None`` means the backend offers none ("unknown"), never
    zero."""

    name: str
    signature: tuple
    dispatches: int = 0
    sampled: int = 0
    sampled_seconds: float = 0.0
    sampled_exclusive_seconds: float = 0.0
    fetch_seconds: float = 0.0
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None

    @property
    def est_exclusive_seconds(self) -> float:
        if self.sampled <= 0:
            return 0.0
        return (
            self.sampled_exclusive_seconds / self.sampled * self.dispatches
        )

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["signature"] = list(self.signature)
        d["est_exclusive_seconds"] = self.est_exclusive_seconds
        return d


class ProfileRegistry:
    """Process-global per-executable profile store, keyed like the
    executable registry by ``(name, signature)`` — distinct shardings of
    one name stay distinct entries and merge per name for reporting."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[tuple[str, tuple], ProfileEntry] = {}
        self._suspect_warned: set[str] = set()
        self.total_dispatches = 0

    def count_dispatch(
        self, name: str, signature: tuple, every: int
    ) -> bool:
        """Account one dispatch; True when it is this entry's Nth (the
        sampling decision is a deterministic per-entry counter, so tests
        and replays sample identically)."""
        with self._lock:
            key = (name, signature)
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = ProfileEntry(name, signature)
            e.dispatches += 1
            self.total_dispatches += 1
            return (e.dispatches - 1) % every == 0

    def record_sample(
        self,
        name: str,
        signature: tuple,
        seconds: float,
        exclusive_seconds: float,
        fetch_seconds: float,
        flops: Optional[float],
        bytes_accessed: Optional[float],
    ) -> None:
        with self._lock:
            key = (name, signature)
            e = self._entries.get(key)
            if e is None:  # reset() raced the dispatch; re-attach
                e = self._entries[key] = ProfileEntry(
                    name, signature, dispatches=1
                )
            e.sampled += 1
            e.sampled_seconds += seconds
            e.sampled_exclusive_seconds += exclusive_seconds
            e.fetch_seconds += fetch_seconds
            if flops is not None:
                e.flops = flops
            if bytes_accessed is not None:
                e.bytes_accessed = bytes_accessed

    def entries(self, name: Optional[str] = None) -> list[ProfileEntry]:
        with self._lock:
            out = list(self._entries.values())
        if name is not None:
            out = [e for e in out if e.name == name]
        return out

    def first_suspect_warning(self, name: str) -> bool:
        """True exactly once per name — the warn-once latch."""
        with self._lock:
            if name in self._suspect_warned:
                return False
            self._suspect_warned.add(name)
            return True

    def snapshot(self) -> list[dict[str, Any]]:
        """JSON-safe entry list, most estimated-exclusive-time first."""
        return [
            e.to_dict()
            for e in sorted(
                self.entries(),
                key=lambda e: e.est_exclusive_seconds,
                reverse=True,
            )
        ]

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._suspect_warned.clear()
            self.total_dispatches = 0


#: Process-global profile registry.
PROFILE_REGISTRY = ProfileRegistry()


# ---------------------------------------------------------------------------
# derived roofline numbers
# ---------------------------------------------------------------------------


def bound_class(
    mean_dispatch_seconds: Optional[float],
    flops: Optional[float],
    bytes_accessed: Optional[float],
    peak_flops: Optional[float],
    peak_bw: Optional[float],
    mfu: Optional[float],
) -> int:
    """Roofline bound class for one executable.

    - ``dispatch-bound``: the roofline-predicted device time (max of the
      compute and memory legs) is under :data:`DISPATCH_BOUND_RATIO` of
      the measured time — launch/dispatch overhead dominates.
    - ``HBM-bound``: arithmetic intensity below the device balance point
      (``peak_flops / peak_bw``) — the memory leg of the roofline binds.
    - ``MXU-bound`` vs ``VPU-bound``: compute-side split on
      :data:`VPU_MFU_THRESHOLD` MFU — a compute-limited executable that
      barely touches the MXU is living on the vector unit.
    - ``unknown`` whenever the cost analysis or the peaks are missing —
      absence of evidence is never a class."""
    if (
        mean_dispatch_seconds is None
        or mean_dispatch_seconds <= 0
        or flops is None
        or bytes_accessed is None
        or not bytes_accessed
        or peak_flops is None
        or peak_bw is None
        or not peak_flops
        or not peak_bw
    ):
        return BOUND_UNKNOWN
    roofline_seconds = max(flops / peak_flops, bytes_accessed / peak_bw)
    if roofline_seconds < DISPATCH_BOUND_RATIO * mean_dispatch_seconds:
        return BOUND_DISPATCH
    if flops / bytes_accessed < peak_flops / peak_bw:
        return BOUND_HBM
    if mfu is not None and mfu < VPU_MFU_THRESHOLD:
        return BOUND_VPU
    return BOUND_MXU


def bound_class_name(code: Any) -> str:
    try:
        return BOUND_CLASS_NAMES[int(code)]
    except (KeyError, TypeError, ValueError):
        return "unknown"


def merged_profiles(
    names: Optional[Any] = None,
) -> dict[str, dict[str, Any]]:
    """Per-NAME merge of the profile entries (shardings collapse here)
    with the derived roofline numbers computed against the resolved
    device peaks. Keys of each value: dispatches, sampled,
    sampled_seconds, est_exclusive_seconds, mean_dispatch_seconds,
    flops_per_dispatch, bytes_per_dispatch, mfu, intensity, bound_code,
    timing_suspect. Derived fields are ``None`` when unknown."""
    peak_flops, peak_bw = xla.device_peaks()
    by_name: dict[str, list[ProfileEntry]] = {}
    for e in PROFILE_REGISTRY.entries():
        if names is not None and e.name not in names:
            continue
        by_name.setdefault(e.name, []).append(e)
    out: dict[str, dict[str, Any]] = {}
    for name, entries in by_name.items():
        dispatches = sum(e.dispatches for e in entries)
        sampled = sum(e.sampled for e in entries)
        sampled_seconds = sum(e.sampled_seconds for e in entries)
        est_exclusive = sum(e.est_exclusive_seconds for e in entries)
        mean = sampled_seconds / sampled if sampled else None
        # per-dispatch cost, weighted by each entry's sample count so a
        # rarely-run sharding does not skew the merged intensity
        fl_known = [e for e in entries if e.flops is not None and e.sampled]
        by_known = [
            e for e in entries
            if e.bytes_accessed is not None and e.sampled
        ]
        flops = None
        if fl_known:
            w = sum(e.sampled for e in fl_known)
            flops = sum(e.flops * e.sampled for e in fl_known) / w
        nbytes = None
        if by_known:
            w = sum(e.sampled for e in by_known)
            nbytes = (
                sum(e.bytes_accessed * e.sampled for e in by_known) / w
            )
        mfu = intensity = None
        suspect = False
        if flops is not None and nbytes:
            intensity = flops / nbytes
        if mean is not None and mean > 0:
            if flops is not None and peak_flops:
                mfu = flops / mean / peak_flops
                suspect = suspect or flops / mean > peak_flops
            if nbytes is not None and peak_bw:
                suspect = suspect or nbytes / mean > peak_bw
        elif sampled and mean == 0 and (peak_flops or peak_bw):
            # zero measured seconds with work attributed: the clock is
            # lying outright (the PERF_NOTES tunnel trap's limit case)
            suspect = flops is not None or nbytes is not None
        out[name] = {
            "dispatches": dispatches,
            "sampled": sampled,
            "sampled_seconds": sampled_seconds,
            "est_exclusive_seconds": est_exclusive,
            "mean_dispatch_seconds": mean,
            "flops_per_dispatch": flops,
            "bytes_per_dispatch": nbytes,
            "mfu": mfu,
            "intensity": intensity,
            "bound_code": bound_class(
                mean, flops, nbytes, peak_flops, peak_bw, mfu
            ),
            "timing_suspect": suspect,
        }
    return out


def exclusive_seconds_by_name() -> dict[str, float]:
    """``{name: estimated exclusive seconds}`` — the heartbeat's hot_exec
    input. Pure registry read: registers no metrics (absence stays
    unknown)."""
    out: dict[str, float] = {}
    for e in PROFILE_REGISTRY.entries():
        out[e.name] = out.get(e.name, 0.0) + e.est_exclusive_seconds
    return out


def publish_metrics(names: Optional[Any] = None) -> None:
    """Publish ``profile.exec.<name>.<field>`` gauges for every profiled
    name (or just ``names``) so offline report loads can rebuild the
    Hot-executables table from a metrics JSONL. Runs at report build and
    metrics flush — NOT per sample, keeping the dispatch path cheap."""
    for name, m in merged_profiles(names).items():
        prefix = f"profile.exec.{name}"
        metrics.gauge(f"{prefix}.dispatches").set(m["dispatches"])
        metrics.gauge(f"{prefix}.sampled").set(m["sampled"])
        metrics.gauge(f"{prefix}.sampled_seconds").set(m["sampled_seconds"])
        metrics.gauge(f"{prefix}.est_exclusive_seconds").set(
            m["est_exclusive_seconds"]
        )
        if m["mean_dispatch_seconds"] is not None:
            metrics.gauge(f"{prefix}.mean_dispatch_seconds").set(
                m["mean_dispatch_seconds"]
            )
        if m["mfu"] is not None:
            metrics.gauge(f"{prefix}.mfu").set(m["mfu"])
        if m["intensity"] is not None:
            metrics.gauge(f"{prefix}.intensity").set(m["intensity"])
        metrics.gauge(f"{prefix}.bound_code").set(m["bound_code"])
        if m["timing_suspect"]:
            metrics.gauge(f"{prefix}.timing_suspect").set(1)
            metrics.counter("profile.timing_suspect_total").inc()
            if PROFILE_REGISTRY.first_suspect_warning(name):
                logger.warning(
                    "timing suspect: executable '%s' measures above the "
                    "resolved device peak — the clock is not seeing the "
                    "device (PERF_NOTES: only a device->host fetch truly "
                    "syncs); treat its rates as fake until the "
                    "measurement path is fixed",
                    name,
                )


# ---------------------------------------------------------------------------
# the dispatch sampler (the xla.set_dispatch_profiler hook)
# ---------------------------------------------------------------------------


class _Frame:
    """One in-flight sampled measurement on the thread-local stack."""

    __slots__ = ("child_seconds",)

    def __init__(self):
        self.child_seconds = 0.0


_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _first_array_leaf(out: Any) -> Optional[Any]:
    """The first array-shaped output leaf — the fetch target that makes
    the measurement honest. None for array-free outputs (nothing to
    synchronize on; the timing is then best-effort)."""
    import jax

    for leaf in jax.tree.leaves(out):
        if (
            getattr(leaf, "shape", None) is not None
            and getattr(leaf, "dtype", None) is not None
        ):
            return leaf
    return None


def profile_dispatch(rec, target, args, kwargs):
    """Route one ``instrumented_jit`` dispatch: count it, and every Nth
    per entry take one honest timing — clock the dispatch, then fetch one
    output leaf through the sanctioned ``sync_fetch`` crossing so the
    clock stops only when the device is actually done (L013 enforces
    that this function and everything it reaches never syncs another
    way). Target exceptions propagate unmodified — the AOT
    TypeError/ValueError fallback in ``xla`` depends on seeing them."""
    sampled = PROFILE_REGISTRY.count_dispatch(
        rec.name, rec.signature, _resolve_sample_every()
    )
    if _xprof_config is not None:
        _xprof_tick()
    if not sampled:
        return target(*args, **kwargs)
    clock = _clock
    stack = _stack()
    frame = _Frame()
    stack.append(frame)
    t0 = clock()
    try:
        out = target(*args, **kwargs)
    except BaseException:
        # no sample: a dispatch that never produced a result has no
        # honest duration (xla may retry it through plain jit next)
        stack.pop()
        raise
    t_exec = clock()
    fetch_seconds = 0.0
    leaf = _first_array_leaf(out)
    if leaf is not None:
        try:
            device.sync_fetch(leaf, label=f"profile:{rec.name}")
        except Exception:  # noqa: BLE001 — never fail a dispatch over
            # accounting; the sample is still recorded, just unsynced
            metrics.counter("profile.fetch_errors").inc()
        fetch_seconds = clock() - t_exec
    dt = clock() - t0
    stack.pop()
    exclusive = dt - frame.child_seconds
    if exclusive < 0.0:
        exclusive = 0.0
    if stack:
        stack[-1].child_seconds += dt
    PROFILE_REGISTRY.record_sample(
        rec.name,
        rec.signature,
        dt,
        exclusive,
        fetch_seconds,
        rec.flops,
        rec.bytes_accessed,
    )
    t_book = clock()
    # HBM high-watermark on the sampling cadence, attributed to the open
    # span's phase (cheap: one memory_stats() probe per local device).
    # Derived gauges (MFU, bound class, ...) are NOT published here —
    # publish_metrics() runs at report/flush time, off the hot path.
    span = trace.current_span()
    memory.record_device_watermarks(
        phase=None if span is None else span.name
    )
    metrics.counter("profile.sampled").inc()
    # overhead = everything a non-profiled run would not have paid: the
    # synchronizing fetch plus the bookkeeping after it — the <2% budget
    metrics.counter("profile.overhead_seconds").inc(
        fetch_seconds + (clock() - t_book)
    )
    return out


def install() -> None:
    """Arm the sampler on every ``instrumented_jit`` dispatch
    (idempotent; done at ``telemetry`` import and re-done by
    :func:`reset` so test isolation never leaves profiling disarmed)."""
    xla.set_dispatch_profiler(profile_dispatch)


# ---------------------------------------------------------------------------
# optional jax.profiler capture window
# ---------------------------------------------------------------------------

_xprof_lock = threading.Lock()
_xprof_config: Optional[dict[str, Any]] = None
_xprof_active = False
_xprof_start_hook: Optional[Callable[[str], None]] = None
_xprof_stop_hook: Optional[Callable[[], None]] = None


def set_xprof_hooks(
    start: Optional[Callable[[str], None]],
    stop: Optional[Callable[[], None]],
) -> None:
    """Inject the capture start/stop (tests). ``None`` restores the real
    ``jax.profiler.start_trace`` / ``stop_trace``."""
    global _xprof_start_hook, _xprof_stop_hook
    _xprof_start_hook = start
    _xprof_stop_hook = stop


def _default_backend() -> str:
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 — no jax, no capture
        return "unknown"


def configure_xprof(
    out_dir: str,
    arm_at: int = 20,
    capture: int = 8,
    force: bool = False,
) -> bool:
    """Arm a ``jax.profiler`` capture window: start when the global
    profiled dispatch count reaches ``arm_at`` (past warmup/compile —
    "around the Kth CD iteration"), stop ``capture`` dispatches later.

    CPU-guarded: on a CPU backend the capture is skipped (returns False,
    logged) unless ``force=True`` or ``PHOTON_XPROF_FORCE=1`` — the
    capture machinery has wedged CPU-only CI runs and a CPU trace answers
    no roofline question anyway. A window still open at :func:`reset`
    (run teardown) is stopped there."""
    backend = _default_backend()
    if (
        backend == "cpu"
        and not force
        and os.environ.get("PHOTON_XPROF_FORCE") != "1"
    ):
        logger.info(
            "xprof capture skipped on the cpu backend (force=True or "
            "PHOTON_XPROF_FORCE=1 to override)"
        )
        return False
    global _xprof_config
    with _xprof_lock:
        _xprof_config = {
            "dir": out_dir,
            "arm_at": max(int(arm_at), 0),
            "stop_at": max(int(arm_at), 0) + max(int(capture), 1),
        }
    logger.info(
        "xprof capture armed: dir=%s dispatches [%d, %d)",
        out_dir,
        _xprof_config["arm_at"],
        _xprof_config["stop_at"],
    )
    metrics.gauge("profile.xprof_armed").set(1)
    return True


def _xprof_start(out_dir: str) -> None:
    if _xprof_start_hook is not None:
        _xprof_start_hook(out_dir)
        return
    import jax

    jax.profiler.start_trace(out_dir)


def _xprof_stop() -> None:
    if _xprof_stop_hook is not None:
        _xprof_stop_hook()
        return
    import jax

    jax.profiler.stop_trace()


def _xprof_tick() -> None:
    """Advance the capture window from the dispatch stream (cheap: the
    caller already checked a config exists). Capture failures log and
    disarm — profiling must never take the run down."""
    global _xprof_config, _xprof_active
    with _xprof_lock:
        cfg = _xprof_config
        if cfg is None:
            return
        n = PROFILE_REGISTRY.total_dispatches
        start = not _xprof_active and n >= cfg["arm_at"]
        stop = _xprof_active and n >= cfg["stop_at"]
    if start:
        try:
            _xprof_start(cfg["dir"])
        except Exception:  # noqa: BLE001
            logger.warning(
                "xprof capture failed to start; disarmed", exc_info=True
            )
            with _xprof_lock:
                _xprof_config = None
            return
        with _xprof_lock:
            _xprof_active = True
        trace.add_event("xprof_start", dir=cfg["dir"])
        logger.info("xprof capture started -> %s", cfg["dir"])
    elif stop:
        stop_xprof()


def stop_xprof() -> None:
    """Stop an open capture window and disarm (idempotent)."""
    global _xprof_config, _xprof_active
    with _xprof_lock:
        was_active = _xprof_active
        _xprof_active = False
        cfg = _xprof_config
        _xprof_config = None
    if not was_active:
        return
    try:
        _xprof_stop()
    except Exception:  # noqa: BLE001
        logger.warning("xprof capture failed to stop", exc_info=True)
        return
    trace.add_event(
        "xprof_stop", dir=None if cfg is None else cfg.get("dir")
    )
    logger.info("xprof capture stopped")


def reset() -> None:
    """Restore import-time defaults (test isolation): stop any capture,
    clear the registry and the clock/sampling overrides — and RE-ARM the
    sampler, so a reset never silently disarms profiling."""
    global _sample_every, _sample_every_env_cache, _clock
    stop_xprof()
    set_xprof_hooks(None, None)
    PROFILE_REGISTRY.reset()
    _sample_every = None
    _sample_every_env_cache = None
    _clock = _monotonic
    install()
