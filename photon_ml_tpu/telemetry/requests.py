"""Request-scoped tracing: the fifth observability layer.

Four layers already exist — per-process spans (trace.py), hardware
telemetry (device/memory/xla), fleet aggregation (fleet_report.py), and
the executable profiler (profile.py) — and none of them can see ONE user
request that fans out from the serving router to N member processes and
folds back. This module closes that gap:

- **context**: the router mints a :class:`TraceContext` per request and
  propagates it over the fan-out HTTP hop in the ``X-Photon-Trace``
  header; members parse it and tag their work with the inbound ids, so
  one request's spans join across ``trace.proc-<i>.jsonl`` streams by
  ``trace_id`` (``FleetReport.request_traces``).
- **ring**: EVERY request records a compact :class:`RequestRecord`
  (phase durations + serving attrs) into a lock-disciplined in-memory
  ring. Overflow evicts oldest-first and is drop-counted
  (``telemetry.trace_dropped``) — bounded memory, honest accounting.
- **tail sampling**: full traces are persisted (as ``request:*`` spans
  through the process tracer, so they land in the span JSONL) only for
  requests that are slow (above a rolling p99 of recent latencies),
  degraded, errored, or explicitly sampled — steady-state overhead stays
  ring-only.
- **flight recorder**: the ring's last N seconds dump atomically
  (tmp-then-rename, :func:`flight_dump`) on SIGTERM/drain, and a
  supervisor that detects a hard-killed member can synthesize the same
  artifact from the bounded TAIL of the member's span JSONL
  (:func:`harvest_flight` — a torn last line never fails the read).
  ``cli report --fleet`` renders the result as a lost member's "last
  words".

This module sits on serving HOT PATHS (the L013 sync-walk seeds
``RequestTracer.finish`` / ``RequestTracer.flight_dump``): pure stdlib,
no numpy, no jax — a device sync inside trace bookkeeping would wedge
the event loop.
"""

from __future__ import annotations

import collections
import datetime
import itertools
import json
import os
import threading
import time
import uuid
from typing import Any, Optional

from photon_ml_tpu import faults
from photon_ml_tpu.telemetry import identity, metrics, trace

__all__ = [
    "TRACE_HEADER",
    "TraceContext",
    "RequestRecord",
    "RequestTracer",
    "REQUESTS",
    "make_context",
    "parse_header",
    "begin",
    "finish",
    "configure",
    "records",
    "trace_time",
    "flight_path",
    "flight_dump",
    "harvest_flight",
    "read_flight",
    "tail_records",
    "reset",
]

#: the propagation header: ``<trace_id>/<request_id>[;s=1]``
TRACE_HEADER = "X-Photon-Trace"

DEFAULT_RING_LIMIT = 4096
#: rolling-latency window the slow threshold (p99) is computed over
_LATENCY_WINDOW = 512
#: below this many observed latencies nothing counts as "slow" — an
#: empty p99 would persist every early request
_MIN_SAMPLES = 100
#: recompute the cached p99 threshold every N finishes (sorting the
#: window per request would dominate the very overhead being bounded)
_THRESHOLD_EVERY = 32
#: sentinel distinguishing "leave as-is" from an explicit None
_UNSET = object()

_FP_FLIGHT_DUMP = faults.register_point(
    "telemetry.flight_dump",
    description=(
        "the crash-safe flight-recorder dump (tmp-then-rename) fired on "
        "SIGTERM/drain — an exit rule is the process dying mid-dump; the "
        "fleet report must never adopt the torn .tmp it leaves behind"
    ),
)

# process-unique id base: one uuid per process + a counter beats a uuid
# per request on the hot path
_ID_BASE = uuid.uuid4().hex[:12]
_ID_SEQ = itertools.count(1)


class TraceContext:
    """One request's propagated identity: ``trace_id`` names the whole
    fan-out tree, ``request_id`` the hop that minted it, ``sampled``
    forces full-trace persistence on every process that sees it."""

    __slots__ = ("trace_id", "request_id", "sampled")

    def __init__(self, trace_id: str, request_id: str, sampled: bool = False):
        self.trace_id = trace_id
        self.request_id = request_id
        self.sampled = bool(sampled)

    def to_header(self) -> str:
        value = f"{self.trace_id}/{self.request_id}"
        return value + ";s=1" if self.sampled else value

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.to_header()!r})"


def make_context(sampled: bool = False) -> TraceContext:
    """Mint a fresh context (the router does this once per request)."""
    seq = next(_ID_SEQ)
    return TraceContext(
        trace_id=f"{_ID_BASE}{seq:08x}",
        request_id=f"{seq:06x}",
        sampled=sampled,
    )


def parse_header(value: Optional[str]) -> Optional[TraceContext]:
    """Parse an inbound ``X-Photon-Trace`` value; None for absent or
    malformed (a bad header must never fail the request it rode in on).
    """
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split(";")
    ids = parts[0].split("/")
    if len(ids) != 2 or not ids[0] or not ids[1]:
        return None
    sampled = any(p.strip() == "s=1" for p in parts[1:])
    return TraceContext(ids[0], ids[1], sampled=sampled)


def trace_time(t_monotonic: Optional[float] = None) -> float:
    """A ``time.monotonic()`` stamp on the process tracer's timebase
    (so batcher enqueue stamps and span timestamps line up)."""
    now_mono = time.monotonic()
    if t_monotonic is None:
        t_monotonic = now_mono
    return trace.TRACER.now() - (now_mono - t_monotonic)


class RequestRecord:
    """One request's compact ring entry: start/duration, named phase
    durations, serving attributes, terminal status."""

    __slots__ = (
        "ctx", "name", "role", "t_start", "t_end", "dur_ms", "attrs",
        "phases", "status", "error",
    )

    def __init__(
        self,
        ctx: TraceContext,
        name: str,
        role: str,
        t_start: float,
        attrs: dict[str, Any],
    ):
        self.ctx = ctx
        self.name = name
        self.role = role
        self.t_start = t_start
        self.t_end: Optional[float] = None
        self.dur_ms: Optional[float] = None
        self.attrs = attrs
        #: (phase name, start ts on the tracer timebase, duration ms)
        self.phases: list[tuple[str, float, float]] = []
        self.status = "ok"
        self.error: Optional[str] = None

    def phase(self, name: str, ms: float, ts: Optional[float] = None) -> None:
        """Record one named phase duration; ``ts`` (tracer timebase)
        defaults to "it just ended"."""
        if ts is None:
            ts = trace.TRACER.now() - ms / 1000.0
        self.phases.append((str(name), float(ts), float(ms)))

    def set_attr(self, **attrs: Any) -> "RequestRecord":
        self.attrs.update(attrs)
        return self

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "type": "request",
            "trace_id": self.ctx.trace_id,
            "request_id": self.ctx.request_id,
            "name": self.name,
            "role": self.role,
            "ts": round(self.t_start, 6),
            "dur_ms": None if self.dur_ms is None else round(self.dur_ms, 3),
            "status": self.status,
            "attrs": self.attrs,
            "phases": [
                {"name": n, "ts": round(ts, 6), "ms": round(ms, 3)}
                for n, ts, ms in self.phases
            ],
        }
        if self.error:
            out["error"] = self.error
        return out


class RequestTracer:
    """The per-process request ring + tail sampler + flight recorder.

    Lock discipline: the ring and latency window mutate only under
    ``_lock``; metric emission and span persistence happen OUTSIDE the
    lock (they take their own locks)."""

    def __init__(self, ring_limit: int = DEFAULT_RING_LIMIT):
        self._lock = threading.Lock()
        self._default_ring_limit = int(ring_limit)
        self._ring_limit = int(ring_limit)
        self._ring: collections.deque[RequestRecord] = collections.deque()
        self.dropped = 0
        self.enabled = True
        self._latencies: collections.deque[float] = collections.deque(
            maxlen=_LATENCY_WINDOW
        )
        self._since_threshold = 0
        #: explicit override; None = derive the rolling p99
        self._fixed_threshold_ms: Optional[float] = None
        self._rolling_threshold_ms: Optional[float] = None

    # -- configuration -------------------------------------------------------

    def configure(
        self,
        ring_limit: Optional[int] = None,
        enabled: Optional[bool] = None,
        slow_threshold_ms: Any = _UNSET,
    ) -> None:
        """Adjust the ring cap, enable/disable recording entirely (the
        bench's untraced arm), or pin the slow threshold (``None``
        restores the rolling p99)."""
        with self._lock:
            if ring_limit is not None:
                self._ring_limit = int(ring_limit)
            if slow_threshold_ms is not _UNSET:
                self._fixed_threshold_ms = (
                    None if slow_threshold_ms is None
                    else float(slow_threshold_ms)
                )
        if enabled is not None:
            self.enabled = bool(enabled)

    @property
    def slow_threshold_ms(self) -> Optional[float]:
        """The active slow-request threshold (fixed override, else the
        rolling p99; None while the window is still filling)."""
        if self._fixed_threshold_ms is not None:
            return self._fixed_threshold_ms
        return self._rolling_threshold_ms

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._latencies.clear()
            self.dropped = 0
            self._ring_limit = self._default_ring_limit
            self._since_threshold = 0
            self._fixed_threshold_ms = None
            self._rolling_threshold_ms = None
        self.enabled = True

    # -- record lifecycle ----------------------------------------------------

    def begin(
        self,
        name: str,
        ctx: Optional[TraceContext] = None,
        role: str = "member",
        t_start: Optional[float] = None,
        **attrs: Any,
    ) -> Optional[RequestRecord]:
        """Open a record (None when recording is disabled — callers
        guard with ``if rec is not None``). ``ctx=None`` mints a local,
        unsampled context."""
        if not self.enabled:
            return None
        if ctx is None:
            ctx = make_context()
        if t_start is None:
            t_start = trace.TRACER.now()
        return RequestRecord(ctx, str(name), str(role), t_start, dict(attrs))

    def finish(
        self,
        rec: Optional[RequestRecord],
        status: str = "ok",
        error: Optional[str] = None,
    ) -> Optional[RequestRecord]:
        """Close a record into the ring, update drop/latency accounting,
        and persist the full trace when tail sampling says so."""
        if rec is None or not self.enabled:
            return rec
        rec.t_end = trace.TRACER.now()
        rec.dur_ms = max(0.0, (rec.t_end - rec.t_start) * 1000.0)
        rec.status = str(status)
        rec.error = error
        dropped = 0
        with self._lock:
            self._ring.append(rec)
            while len(self._ring) > self._ring_limit:
                self._ring.popleft()  # oldest-evicted
                dropped += 1
            self.dropped += dropped
            self._latencies.append(rec.dur_ms)
            self._since_threshold += 1
            if (
                self._since_threshold >= _THRESHOLD_EVERY
                and len(self._latencies) >= _MIN_SAMPLES
            ):
                self._since_threshold = 0
                window = sorted(self._latencies)
                self._rolling_threshold_ms = window[
                    int(0.99 * (len(window) - 1))
                ]
            threshold = self.slow_threshold_ms
        if dropped:
            metrics.counter("telemetry.trace_dropped").inc(dropped)
        metrics.counter("request.records").inc()
        metrics.histogram("request.total_ms").observe(rec.dur_ms)
        for pname, _ts, pms in rec.phases:
            metrics.histogram(f"request.phase.{pname}_ms").observe(pms)
        reason = None
        if rec.status != "ok":
            reason = "error"
        elif rec.attrs.get("degraded"):
            reason = "degraded"
        elif rec.ctx.sampled:
            reason = "sampled"
        elif threshold is not None and rec.dur_ms >= threshold:
            reason = "slow"
        if reason is not None:
            self._persist(rec, reason)
        return rec

    def _persist(self, rec: RequestRecord, reason: str) -> None:
        """Emit the record as ``request:*`` spans through the process
        tracer (-> the span JSONL sink), joinable by ``trace_id``."""
        attrs = dict(rec.attrs)
        attrs.update(
            trace_id=rec.ctx.trace_id,
            request_id=rec.ctx.request_id,
            role=rec.role,
            status=rec.status,
            sampled_reason=reason,
            dur_ms=round(rec.dur_ms or 0.0, 3),
            phases={n: round(ms, 3) for n, _ts, ms in rec.phases},
        )
        if rec.error:
            attrs["error"] = rec.error
        parent = trace.TRACER.emit(
            f"request:{rec.name}",
            ts=rec.t_start,
            dur=max(0.0, (rec.t_end or rec.t_start) - rec.t_start),
            **attrs,
        )
        for pname, pts, pms in rec.phases:
            trace.TRACER.emit(
                f"request:{rec.name}:{pname}",
                ts=pts,
                dur=pms / 1000.0,
                parent=parent,
                trace_id=rec.ctx.trace_id,
                phase=pname,
            )
        metrics.counter("request.persisted").inc()

    # -- inspection ----------------------------------------------------------

    def records(self) -> list[dict[str, Any]]:
        """A snapshot of the ring, oldest first (JSON-safe dicts)."""
        with self._lock:
            return [r.to_dict() for r in self._ring]

    # -- the flight recorder -------------------------------------------------

    def flight_dump(
        self, path: str, last_s: float = 30.0
    ) -> Optional[int]:
        """Atomically dump the last ``last_s`` seconds of ring records to
        ``path`` (tmp-then-rename). Returns the record count, or None
        when the dump failed — a flight dump must never fail the drain
        path it rides on."""
        now = trace.TRACER.now()
        wall = datetime.datetime.now(datetime.timezone.utc)
        with self._lock:
            kept = [
                r.to_dict()
                for r in self._ring
                if r.t_end is not None and now - r.t_end <= last_s
            ]
            dropped = self.dropped
        doc: dict[str, Any] = {
            "type": "flight_record",
            "written": wall.isoformat(),
            # the same monotonic<->epoch anchor pair the trace_header
            # carries, so FleetReport aligns flight records too
            "anchor_unix_s": round(wall.timestamp(), 6),
            "monotonic_anchor": round(now, 6),
            "hostname": identity.hostname(),
            "window_s": last_s,
            "dropped": dropped,
            "records": kept,
        }
        proc = identity.fleet_process_index()
        if proc is not None:
            doc["process_index"] = proc
        from photon_ml_tpu.utils.atomic import atomic_write_json

        try:
            faults.fault_point(_FP_FLIGHT_DUMP)
            atomic_write_json(path, doc)
        except (faults.InjectedFault, faults.InjectedIOError, OSError):
            metrics.counter("telemetry.flight_dump_failures").inc()
            return None
        return len(kept)


#: Process-global request tracer; module-level helpers delegate to it.
REQUESTS = RequestTracer()

begin = REQUESTS.begin
finish = REQUESTS.finish
configure = REQUESTS.configure
records = REQUESTS.records
flight_dump = REQUESTS.flight_dump
reset = REQUESTS.reset


# -- flight-record files -----------------------------------------------------


def flight_path(directory: str, proc: Optional[int] = None) -> str:
    """``flight-proc-<i>.json`` under ``directory`` — the naming
    contract ``cli report --fleet`` adopts (and its ``.tmp`` shadow
    never matches, so a kill mid-dump leaves nothing adoptable)."""
    if proc is None:
        proc = identity.fleet_process_index() or 0
    return os.path.join(directory, f"flight-proc-{int(proc)}.json")


def read_flight(path: str) -> Optional[dict]:
    """Load one flight record, or None when absent/torn/not one."""
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("type") != "flight_record":
        return None
    return doc


def tail_records(
    path: str, max_tail_bytes: int = 256 * 1024
) -> tuple[Optional[dict], list[dict]]:
    """``(trace_header_or_None, records)`` from a BOUNDED tail read of a
    span JSONL stream: at most ``max_tail_bytes`` from the end, the torn
    first line of the tail window skipped, a torn LAST line (the
    hard-kill-mid-write case) skipped — never a parse failure."""
    start = 0
    try:
        with open(path, "rb") as fh:
            first = fh.readline(64 * 1024)
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            start = max(0, size - int(max_tail_bytes))
            fh.seek(start)
            blob = fh.read()
    except OSError:
        return None, []
    header: Optional[dict] = None
    try:
        rec = json.loads(first.decode("utf-8", "replace"))
        if isinstance(rec, dict) and rec.get("type") == "trace_header":
            header = rec
    except ValueError:
        pass
    lines = blob.decode("utf-8", "replace").splitlines()
    if start > 0 and lines:
        lines = lines[1:]  # the seek landed mid-line: torn, drop it
    out: list[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn last line of a killed writer
        if isinstance(rec, dict):
            out.append(rec)
    return header, out


def harvest_flight(
    trace_jsonl_path: str,
    out_path: str,
    last_s: float = 30.0,
    max_tail_bytes: int = 256 * 1024,
) -> Optional[int]:
    """Supervisor-side flight synthesis for a HARD-KILLED member (which
    never ran its own :func:`flight_dump`): bounded-tail read of the
    member's span JSONL, keep the spans whose end falls within
    ``last_s`` of the stream's latest timestamp, and write the same
    atomic ``flight_record`` document marked ``harvested``. Returns the
    span count, or None when the stream is missing/empty."""
    header, recs = tail_records(trace_jsonl_path, max_tail_bytes)
    spans = [
        r
        for r in recs
        if r.get("type") == "span" and isinstance(r.get("ts"), (int, float))
    ]
    if not spans:
        return None

    def _end(r: dict) -> float:
        dur = r.get("dur")
        return r["ts"] + (dur if isinstance(dur, (int, float)) else 0.0)

    t_last = max(_end(r) for r in spans)
    kept = [r for r in spans if _end(r) >= t_last - last_s]
    doc: dict[str, Any] = {
        "type": "flight_record",
        "harvested": True,
        "source": trace_jsonl_path,
        "window_s": float(last_s),
        "records": kept,
    }
    for key in (
        "anchor_unix_s", "monotonic_anchor", "hostname", "process_index",
    ):
        if header is not None and key in header:
            doc[key] = header[key]
    from photon_ml_tpu.utils.atomic import atomic_write_json

    try:
        atomic_write_json(out_path, doc)
    except OSError:
        return None
    return len(kept)
