"""Device-level performance accounting: every jitted hot path becomes an
accounted executable.

PRs 1/3 measure *wall time and HBM occupancy*; this layer answers the
questions that decide the next perf PR — is the per-entity vmap solve
compute-bound or bandwidth-bound? what fraction of a distributed solve is
psum traffic? which argument-shape change triggered that recompile storm?

Three pieces:

- :func:`instrumented_jit` — a drop-in ``jax.jit`` replacement (lint L011
  enforces it in hot-path library modules). The first call per argument
  shape-signature goes through ``lowered.compile()`` with the compile wall
  time, ``cost_analysis()`` FLOPs / bytes-accessed, and
  ``memory_analysis()`` temp/arg/output bytes recorded in the process-
  global :data:`XLA_REGISTRY`, keyed by ``(name, signature)``. Subsequent
  same-signature calls dispatch to the cached compiled executable and
  accumulate per-call FLOPs/bytes onto the open telemetry span (so the
  run report can compute per-phase roofline numbers from span wall time).
  A NEW signature for a known name is a **recompile**: it is attributed to
  the exact per-argument delta that caused it, counted
  (``xla.recompiles``), stamped as a span event, and escalated to a
  structured warning at ``RECOMPILE_WARN_THRESHOLD`` distinct signatures
  — the recompile-storm detector.
- roofline peaks — :func:`device_peaks` resolves the device's peak FLOP/s
  and HBM bandwidth (known TPU generations; ``PHOTON_PEAK_FLOPS`` /
  ``PHOTON_PEAK_HBM_GBPS`` env overrides; :func:`set_peaks` for tests)
  and publishes them as ``device.peak_*`` gauges so reports loaded from a
  metrics JSONL can compute MFU offline.
- collective estimates — :func:`record_collective` turns mesh sharding
  specs into estimated wire bytes (ring psum moves ``2(n-1)/n`` of the
  payload per device; all-gather ``(n-1)/n``), exposed as ``comms.*``
  counters/gauges and accumulated onto the open span, so MULTICHIP_r*
  results carry a comms fraction.

Everything degrades gracefully: backends without cost/memory analysis
leave those record fields ``None`` (rendered "unknown"), an executable
that cannot be AOT-compiled falls back to plain ``jax.jit`` dispatch
(``xla.fallback_calls``), and analysis is injectable for deterministic
tests via :func:`set_analysis_provider`.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Mapping, Optional, Sequence

from photon_ml_tpu.telemetry import metrics, trace

__all__ = [
    "ExecutableRecord",
    "ExecutableRegistry",
    "XLA_REGISTRY",
    "instrumented_jit",
    "shape_signature",
    "set_analysis_provider",
    "set_dispatch_profiler",
    "set_peaks",
    "device_peaks",
    "collective_bytes",
    "record_collective",
    "RECOMPILE_WARN_THRESHOLD",
    "reset",
]

logger = logging.getLogger("photon_ml_tpu.telemetry.xla")

#: Distinct signatures of ONE executable name at which the recompile
#: counter escalates to a structured warning (the recompile-storm signal
#: that explained nothing in BENCH_r05).
RECOMPILE_WARN_THRESHOLD = 3

# Peak per-chip dense-matmul FLOP/s (bf16) and HBM bandwidth (bytes/s) by
# device_kind substring, most specific first. Used for MFU / bandwidth
# utilization denominators; unknown kinds yield None ("unknown" in
# reports). Sources: published TPU system specs per generation.
_PEAK_TABLE: tuple[tuple[str, float, float], ...] = (
    ("TPU v6", 918e12, 1640e9),  # Trillium / v6e
    ("TPU v5p", 459e12, 2765e9),
    ("TPU v5 lite", 197e12, 819e9),  # v5e
    ("TPU v5e", 197e12, 819e9),
    ("TPU v5", 459e12, 2765e9),
    ("TPU v4", 275e12, 1228e9),
    ("TPU v3", 123e12, 900e9),
    ("TPU v2", 45e12, 700e9),
)

# test/override hooks (cleared by reset(); plain attribute swaps — set
# from the main/test thread, read racily by design: a torn read returns
# either the old or the new hook, both valid)
_peaks_override: Optional[tuple[Optional[float], Optional[float]]] = None
_analysis_provider: Optional[Callable] = None

# the executable-level profiler hook (telemetry.profile installs its
# sampler here at import). NOT cleared by reset() — disarming profiling
# is an explicit set_dispatch_profiler(None), never a side effect of
# test isolation.
_dispatch_profiler: Optional[Callable] = None


def set_dispatch_profiler(hook: Optional[Callable]) -> None:
    """Install the per-dispatch profiler hook. When set, every
    ``InstrumentedFunction`` invocation routes through
    ``hook(record, target, args, kwargs)`` — the hook must call
    ``target(*args, **kwargs)`` exactly once, return its result, and let
    target exceptions propagate unmodified (the AOT TypeError/ValueError
    fallback depends on seeing them). ``None`` disarms."""
    global _dispatch_profiler
    _dispatch_profiler = hook


# ---------------------------------------------------------------------------
# roofline peaks
# ---------------------------------------------------------------------------


def set_peaks(
    peak_flops: Optional[float], peak_hbm_bytes_per_sec: Optional[float]
) -> None:
    """Override the device peak numbers (deterministic tests / devices the
    table does not know). ``set_peaks(None, None)`` does NOT clear the
    override — it pins "unknown"; call :func:`reset` to restore probing."""
    global _peaks_override
    _peaks_override = (peak_flops, peak_hbm_bytes_per_sec)
    _publish_peaks(peak_flops, peak_hbm_bytes_per_sec)


def _publish_peaks(
    peak_flops: Optional[float], peak_bw: Optional[float]
) -> None:
    if peak_flops is not None:
        metrics.gauge("device.peak_flops").set(peak_flops)
    if peak_bw is not None:
        metrics.gauge("device.peak_hbm_bytes_per_sec").set(peak_bw)


def device_peaks() -> tuple[Optional[float], Optional[float]]:
    """``(peak_flops, peak_hbm_bytes_per_sec)`` for device 0, or ``None``s
    when unknown (CPU, unrecognized kinds). Resolution order: injected
    override, ``PHOTON_PEAK_FLOPS``/``PHOTON_PEAK_HBM_GBPS`` env vars,
    the known-TPU table. Publishes ``device.peak_*`` gauges when known so
    offline report loads can compute MFU from the metrics JSONL."""
    if _peaks_override is not None:
        return _peaks_override
    def _env_float(name: str, scale: float = 1.0) -> Optional[float]:
        raw = os.environ.get(name)
        if not raw:
            return None
        try:
            return float(raw) * scale
        except ValueError:  # malformed override: unknown, never a crash
            logger.warning("ignoring malformed %s=%r", name, raw)
            return None

    flops = _env_float("PHOTON_PEAK_FLOPS")
    bw = _env_float("PHOTON_PEAK_HBM_GBPS", scale=1e9)
    if flops is None or bw is None:
        try:
            import jax

            kind = str(jax.devices()[0].device_kind)
        except Exception:  # noqa: BLE001 — accounting must never fail
            kind = ""
        for sub, table_flops, table_bw in _PEAK_TABLE:
            if sub.lower() in kind.lower():
                flops = table_flops if flops is None else flops
                bw = table_bw if bw is None else bw
                break
    _publish_peaks(flops, bw)
    return flops, bw


# ---------------------------------------------------------------------------
# analysis (cost / memory) with injection
# ---------------------------------------------------------------------------


def set_analysis_provider(provider: Optional[Callable]) -> None:
    """Override executable analysis for tests: ``provider(compiled)`` must
    return ``(cost, mem)`` where ``cost`` is a ``cost_analysis()``-shaped
    mapping (``{"flops": ..., "bytes accessed": ...}``) or None, and
    ``mem`` a ``memory_analysis()``-shaped object/mapping or None.
    ``None`` restores the real XLA analysis."""
    global _analysis_provider
    _analysis_provider = provider


def _cost_mapping(raw: Any) -> Optional[Mapping[str, float]]:
    """Normalize ``cost_analysis()`` output: jax returns a dict on recent
    versions and a one-element list of dicts on older ones."""
    if raw is None:
        return None
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    return raw if isinstance(raw, Mapping) else None


def _mem_field(mem: Any, field: str) -> Optional[int]:
    if mem is None:
        return None
    if isinstance(mem, Mapping):
        v = mem.get(field)
    else:
        v = getattr(mem, field, None)
    return None if v is None else int(v)


def _analyze(compiled: Any) -> tuple[Optional[Mapping], Any]:
    """(cost mapping, memory stats) for a compiled executable; ``(None,
    None)`` on backends where the analyses are unavailable — never
    raises."""
    if _analysis_provider is not None:
        try:
            cost, mem = _analysis_provider(compiled)
            return _cost_mapping(cost), mem
        except Exception:  # noqa: BLE001 — a broken injected provider
            logger.debug("injected analysis provider failed", exc_info=True)
            return None, None
    cost = mem = None
    try:
        cost = _cost_mapping(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — unimplemented on some backends
        cost = None
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        mem = None
    return cost, mem


# ---------------------------------------------------------------------------
# shape signatures
# ---------------------------------------------------------------------------

_DTYPE_SHORT = {
    "float32": "f32", "float64": "f64", "float16": "f16",
    "bfloat16": "bf16", "int32": "i32", "int64": "i64", "int16": "i16",
    "int8": "i8", "uint32": "u32", "uint8": "u8", "bool": "b1",
}


_named_sharding_cls: Optional[type] = None


def _mesh_sharding(leaf: Any):
    """The leaf's NamedSharding when it is committed to a multi-device
    mesh, else None. Mesh placement is part of the compiled program (GSPMD
    partitions differently per sharding), so it must be part of both the
    dispatch key and the recompile-attribution signature; single-device
    and host leaves stay sharding-free so existing signatures are
    unchanged. jax is resolved lazily — a leaf carrying ``.sharding``
    proves it is already imported."""
    global _named_sharding_cls
    sh = getattr(leaf, "sharding", None)
    if sh is None:
        return None
    if _named_sharding_cls is None:
        from jax.sharding import NamedSharding

        _named_sharding_cls = NamedSharding
    if isinstance(sh, _named_sharding_cls) and sh.mesh.devices.size > 1:
        return sh
    return None


def _leaf_sig(leaf: Any) -> str:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        dt = _DTYPE_SHORT.get(str(dtype), str(dtype))
        weak = "*" if getattr(getattr(leaf, "aval", None), "weak_type", False) else ""
        sh = _mesh_sharding(leaf)
        mesh_sig = "" if sh is None else f"@{sh.spec}"
        return f"{dt}{weak}[{','.join(str(int(d)) for d in shape)}]{mesh_sig}"
    if isinstance(leaf, bool):
        return "pybool"
    if isinstance(leaf, int):
        return "pyint"
    if isinstance(leaf, float):
        return "pyfloat"
    if isinstance(leaf, complex):
        return "pycomplex"
    # structure-affecting leaves (strings, None never reaches here — it is
    # part of the treedef): keyed by value, they ARE the trace key
    return f"={leaf!r}"


def shape_signature(tree: Any) -> tuple[str, tuple[str, ...]]:
    """``(structure_key, per_leaf_shapes)`` for an argument pytree — the
    executable-registry key. Array leaves contribute ``dtype[shape]``
    (weak types marked ``*``); python scalars contribute their type only
    (values are traced, not trace keys); other leaves their repr."""
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    return str(treedef), tuple(_leaf_sig(x) for x in leaves)


def _leaf_key(leaf: Any):
    """Cheap hashable dispatch key for one leaf — no string formatting on
    the hot path (the pretty ``_leaf_sig`` strings are built only when a
    signature is first compiled)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        weak = getattr(getattr(leaf, "aval", None), "weak_type", False)
        return (dtype, tuple(shape), weak, _mesh_sharding(leaf))
    if isinstance(leaf, (bool, int, float, complex)):
        return type(leaf)
    return ("repr", repr(leaf))


def _signature_delta(
    old: Sequence[str], new: Sequence[str]
) -> str:
    """Human-readable per-leaf diff between two signatures — the exact
    argument change a recompile is attributed to."""
    changes = []
    n = max(len(old), len(new))
    for i in range(n):
        a = old[i] if i < len(old) else "<absent>"
        b = new[i] if i < len(new) else "<absent>"
        if a != b:
            changes.append(f"leaf[{i}]: {a} -> {b}")
    if not changes:
        return "argument structure changed (same leaf shapes)"
    head = "; ".join(changes[:4])
    if len(changes) > 4:
        head += f"; ... {len(changes) - 4} more leaves"
    return head


# ---------------------------------------------------------------------------
# executable registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ExecutableRecord:
    """One compiled (name, signature) executable's accounted state.

    ``flops`` / ``bytes_accessed`` are per-call estimates from XLA's cost
    analysis; ``None`` means the backend offers no analysis ("unknown"),
    never zero."""

    name: str
    signature: tuple[str, ...]
    structure: str = ""
    compile_seconds: float = 0.0
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    temp_bytes: Optional[int] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    calls: int = 0

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["signature"] = list(self.signature)
        return d


class ExecutableRegistry:
    """Process-global registry of accounted executables keyed by
    ``(name, shape-signature)``, with per-name signature history for
    recompile attribution."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: dict[tuple[str, tuple], ExecutableRecord] = {}
        # name -> signatures in arrival order (recompile attribution)
        self._history: dict[str, list[tuple[str, ...]]] = {}
        self._warned: set[str] = set()

    def record_compile(
        self,
        name: str,
        signature: tuple[str, ...],
        structure: str,
        compile_seconds: float,
        cost: Optional[Mapping],
        mem: Any,
        multi_shape: bool = False,
    ) -> ExecutableRecord:
        """Insert (or refresh) the record for a freshly compiled
        executable, publish its compile metrics, and attribute a
        recompile when ``name`` already had a different signature.

        ``multi_shape`` marks an executable whose signature SET is by
        design (the serving engine's padded batch buckets, per-bucket
        entity counts): new signatures still register and publish compile
        metrics, but are not counted as recompiles and never trip the
        storm warning — the gate metric must not flag healthy warmups."""
        rec = ExecutableRecord(
            name=name,
            signature=signature,
            structure=structure,
            compile_seconds=float(compile_seconds),
            flops=None if cost is None else _maybe_float(cost.get("flops")),
            bytes_accessed=(
                None if cost is None
                else _maybe_float(cost.get("bytes accessed"))
            ),
            temp_bytes=_mem_field(mem, "temp_size_in_bytes"),
            argument_bytes=_mem_field(mem, "argument_size_in_bytes"),
            output_bytes=_mem_field(mem, "output_size_in_bytes"),
            generated_code_bytes=_mem_field(
                mem, "generated_code_size_in_bytes"
            ),
        )
        with self._lock:
            self._records[(name, signature)] = rec
            history = self._history.setdefault(name, [])
            prior = list(history)
            history.append(signature)
            n_sigs = len(history)
            warn = (
                not multi_shape
                and n_sigs >= RECOMPILE_WARN_THRESHOLD
                and name not in self._warned
            )
            if warn:
                self._warned.add(name)
        metrics.counter("xla.compiles").inc()
        metrics.counter("xla.compile_seconds").inc(rec.compile_seconds)
        metrics.counter(f"xla.exec.{name}.compiles").inc()
        metrics.counter(f"xla.exec.{name}.compile_seconds").inc(
            rec.compile_seconds
        )
        if rec.flops is not None:
            metrics.gauge(f"xla.exec.{name}.flops_per_call").set(rec.flops)
        if rec.bytes_accessed is not None:
            metrics.gauge(f"xla.exec.{name}.bytes_per_call").set(
                rec.bytes_accessed
            )
        if rec.temp_bytes is not None:
            metrics.gauge(f"xla.exec.{name}.temp_bytes").set(rec.temp_bytes)
        if prior and multi_shape:
            # expected shape set: registered and accounted, not a storm
            logger.info(
                "executable '%s': signature #%d of its expected shape set "
                "(%s)",
                name,
                n_sigs,
                _signature_delta(prior[-1], signature),
            )
        elif prior:
            delta = _signature_delta(prior[-1], signature)
            metrics.counter("xla.recompiles").inc()
            metrics.counter(f"xla.exec.{name}.recompiles").inc()
            trace.add_event(
                "recompile",
                executable=name,
                delta=delta,
                distinct_signatures=n_sigs,
            )
            if warn:
                logger.warning(
                    "recompile storm: executable '%s' compiled %d distinct "
                    "signatures; last delta: %s — stabilize the argument "
                    "shapes (pad to buckets) or split the executable",
                    name,
                    n_sigs,
                    delta,
                )
            else:
                logger.info(
                    "recompile: '%s' signature #%d (%s)", name, n_sigs, delta
                )
        return rec

    def record_call(self, rec: ExecutableRecord) -> None:
        """Account one dispatch of ``rec``: global + per-executable call
        counters, FLOP/byte totals, and span-local accumulation for
        per-phase roofline numbers."""
        with self._lock:
            rec.calls += 1
            # re-attach records orphaned by a reset() (long-lived cached
            # solvers outlive test-isolation resets)
            self._records.setdefault((rec.name, rec.signature), rec)
            self._history.setdefault(rec.name, [rec.signature])
        metrics.counter("xla.calls").inc()
        metrics.counter(f"xla.exec.{rec.name}.calls").inc()
        if rec.flops is not None:
            metrics.counter("xla.flops_total").inc(rec.flops)
            metrics.counter(f"xla.exec.{rec.name}.flops_total").inc(rec.flops)
        if rec.bytes_accessed is not None:
            metrics.counter("xla.bytes_total").inc(rec.bytes_accessed)
            metrics.counter(f"xla.exec.{rec.name}.bytes_total").inc(
                rec.bytes_accessed
            )
        _accumulate_span_attr("xla_flops", rec.flops)
        _accumulate_span_attr("xla_bytes", rec.bytes_accessed)

    def executables(self, name: Optional[str] = None) -> list[ExecutableRecord]:
        with self._lock:
            recs = list(self._records.values())
        if name is not None:
            recs = [r for r in recs if r.name == name]
        return recs

    def signature_history(self, name: str) -> list[tuple[str, ...]]:
        with self._lock:
            return list(self._history.get(name, ()))

    def snapshot(self) -> list[dict[str, Any]]:
        """JSON-safe record list, most total-cost first (cost = per-call
        flops x calls when known, else compile seconds)."""

        def rank(r: ExecutableRecord) -> float:
            if r.flops is not None:
                return r.flops * max(r.calls, 1)
            return r.compile_seconds

        return [
            r.to_dict()
            for r in sorted(self.executables(), key=rank, reverse=True)
        ]

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._history.clear()
            self._warned.clear()


def _maybe_float(v: Any) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f >= 0 else None


def _accumulate_span_attr(key: str, value: Optional[float]) -> None:
    if value is None:
        return
    cur = trace.current_span()
    if cur is not None:
        cur.attrs[key] = float(cur.attrs.get(key, 0.0)) + float(value)


#: Process-global executable registry.
XLA_REGISTRY = ExecutableRegistry()


# ---------------------------------------------------------------------------
# instrumented_jit
# ---------------------------------------------------------------------------


class InstrumentedFunction:
    """``jax.jit`` with an accounted compile path (see module docstring).

    Thread-safe; per-signature compiled executables are cached on the
    instance. Two instances MAY share a ``name`` (e.g. one lru-cached
    solver factory per optimizer config): each instance's first compile
    of a signature is a distinct registry entry (suffix ``#<k>``), so a
    same-shape recompile caused by a new static configuration is still
    attributed instead of silently merged."""

    def __init__(
        self,
        fn: Callable,
        name: str,
        jit_kwargs: dict,
        multi_shape: bool = False,
    ):
        import jax

        self._fn = fn
        self.name = name
        self._jit = jax.jit(fn, **jit_kwargs)
        self._instance = _next_instance(name)
        self._multi_shape = multi_shape
        self._compiled: dict[tuple, tuple[Any, ExecutableRecord]] = {}
        self._lock = threading.Lock()
        self.__wrapped__ = fn

    # jax.jit API passthroughs used by callers/tests
    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def _dispatch_key(self, args, kwargs):
        """Hashable per-call key: pytree structure + cheap leaf keys (no
        string building — serving/solve hot paths dispatch through
        here)."""
        import jax

        leaves, treedef = jax.tree.flatten((args, kwargs))
        return (treedef, tuple(_leaf_key(x) for x in leaves)), leaves

    def record_for(self, *args, **kwargs) -> Optional[ExecutableRecord]:
        """The registry record this instance compiled for these arguments'
        signature, or None when that signature has not been compiled yet
        (no compile is triggered). Lets owners of per-shape executables
        (the serving engine's batch buckets) surface compile state."""
        key, _leaves = self._dispatch_key(args, kwargs)
        entry = self._compiled.get(key)
        return None if entry is None else entry[1]

    def __call__(self, *args, **kwargs):
        key, leaves = self._dispatch_key(args, kwargs)
        entry = self._compiled.get(key)
        if entry is None:
            with self._lock:
                entry = self._compiled.get(key)
                if entry is None:
                    leaf_sig = tuple(_leaf_sig(x) for x in leaves)
                    if self._instance:
                        leaf_sig = (
                            f"static-config#{self._instance}",
                        ) + leaf_sig
                    entry = self._compile(
                        str(key[0]), leaf_sig, args, kwargs
                    )
                    self._compiled[key] = entry
        compiled, rec = entry
        XLA_REGISTRY.record_call(rec)
        prof = _dispatch_profiler
        if compiled is None:
            if prof is not None:
                return prof(rec, self._jit, args, kwargs)
            return self._jit(*args, **kwargs)
        try:
            if prof is not None:
                return prof(rec, compiled, args, kwargs)
            return compiled(*args, **kwargs)
        except (TypeError, ValueError):
            # AOT argument-processing mismatch inside one key bucket
            # (weak-type / sharding variants): these raise BEFORE the
            # executable runs, so re-dispatching through plain jit is
            # safe even with donated arguments. Runtime errors (OOM,
            # XlaRuntimeError) propagate — re-executing after a partial
            # run could read already-donated buffers.
            logger.debug(
                "AOT dispatch of '%s' failed; falling back to jax.jit",
                self.name,
                exc_info=True,
            )
            metrics.counter("xla.fallback_calls").inc()
            self._compiled[key] = (None, rec)
            if prof is not None:
                return prof(rec, self._jit, args, kwargs)
            return self._jit(*args, **kwargs)

    def _compile(self, structure, leaf_sig, args, kwargs):
        t0 = time.monotonic()
        compiled = None
        cost = mem = None
        try:
            lowered = self._jit.lower(*args, **kwargs)
            compiled = lowered.compile()
        except Exception:  # noqa: BLE001 — backends/args AOT cannot handle
            logger.debug(
                "AOT compile of '%s' unavailable; using jax.jit dispatch",
                self.name,
                exc_info=True,
            )
            metrics.counter("xla.fallback_calls").inc()
        dt = time.monotonic() - t0
        if compiled is not None:
            cost, mem = _analyze(compiled)
        rec = XLA_REGISTRY.record_compile(
            self.name, leaf_sig, structure, dt, cost, mem,
            multi_shape=self._multi_shape,
        )
        trace.add_event(
            "xla_compile",
            executable=self.name,
            seconds=round(dt, 6),
            flops=rec.flops,
        )
        return compiled, rec


_instance_lock = threading.Lock()
_instance_counts: dict[str, int] = {}


def _next_instance(name: str) -> int:
    with _instance_lock:
        n = _instance_counts.get(name, 0)
        _instance_counts[name] = n + 1
        return n


def instrumented_jit(
    fn: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    multi_shape: bool = False,
    **jit_kwargs: Any,
) -> Any:
    """Accounted ``jax.jit``: usable as ``instrumented_jit(f, name=...)``
    or as a decorator ``@instrumented_jit(name=...)``. All ``jax.jit``
    keyword arguments (``donate_argnums``, ``out_shardings``, ...) pass
    through. ``multi_shape=True`` declares that this executable compiles
    a SET of signatures by design (padded batch buckets, per-bucket
    entity counts): its compiles register and publish cost normally but
    are never counted as recompiles or escalated to a storm warning."""
    if fn is None:
        return lambda f: instrumented_jit(
            f, name=name, multi_shape=multi_shape, **jit_kwargs
        )
    return InstrumentedFunction(
        fn,
        name or getattr(fn, "__name__", "jit_fn"),
        jit_kwargs,
        multi_shape=multi_shape,
    )


# ---------------------------------------------------------------------------
# collective-communication estimates
# ---------------------------------------------------------------------------


def collective_bytes(
    op: str, n_devices: int, payload_bytes: int
) -> int:
    """Estimated per-device wire bytes for one collective over an
    ``n_devices`` mesh axis: ring ``psum`` (all-reduce) moves
    ``2(n-1)/n`` of the payload; ``all_gather``/``reduce_scatter`` move
    ``(n-1)/n``. Zero on a 1-device axis (XLA elides the collective)."""
    n = int(n_devices)
    if n <= 1 or payload_bytes <= 0:
        return 0
    if op == "psum":
        frac = 2.0 * (n - 1) / n
    elif op in ("all_gather", "reduce_scatter"):
        frac = (n - 1) / n
    else:
        raise ValueError(f"unknown collective op '{op}'")
    return int(frac * payload_bytes)


def record_collective(
    label: str,
    op: str,
    n_devices: int,
    payload_bytes: int,
    count: int = 1,
) -> int:
    """Account ``count`` collectives of ``payload_bytes`` each under
    ``label``: ``comms.bytes_total`` / ``comms.<label>.bytes`` counters, a
    per-call gauge, and span-local ``comms_bytes`` accumulation (the run
    report's comms-fraction input). Returns the estimated bytes. This is
    a STATIC estimate from sharding specs — see README for its limits."""
    per_call = collective_bytes(op, n_devices, payload_bytes)
    total = per_call * max(int(count), 0)
    if total <= 0:
        return 0
    metrics.counter("comms.bytes_total").inc(total)
    metrics.counter(f"comms.{label}.bytes").inc(total)
    metrics.gauge(f"comms.{label}.bytes_per_call").set(per_call)
    _accumulate_span_attr("comms_bytes", total)
    return total


def reset() -> None:
    """Restore import-time defaults (test isolation): clear the registry,
    the injected analysis provider, and the peaks override. Compiled-
    executable caches inside live ``InstrumentedFunction`` instances
    survive (re-attached to the registry on their next call)."""
    global _peaks_override
    XLA_REGISTRY.reset()
    set_analysis_provider(None)
    _peaks_override = None
