"""Structured telemetry: tracing spans, a metrics registry, device/memory
accounting, live progress heartbeats, and run reports.

Five layers (ISSUE 1 gave emission; ISSUE 3 the interpretation):

- :mod:`photon_ml_tpu.telemetry.trace` — ``span(name, **attrs)`` opens a
  node of a thread-safe hierarchical span tree with a JSONL sink and a
  Chrome-trace/Perfetto exporter. ``utils.timing.timed()`` is a thin
  wrapper over it, so every driver phase is already a span.
- :mod:`photon_ml_tpu.telemetry.metrics` — process-global counters /
  gauges / histograms with a ``snapshot()`` dict and a JSONL flush;
  attached to the final ``TrainingFinishEvent`` and the bench JSON.
- :mod:`photon_ml_tpu.telemetry.device` — ``sync_fetch()``, the one
  sanctioned device->host fetch point (fetches / bytes / blocking
  seconds), plus per-compile counters via ``jax.monitoring``.
- :mod:`photon_ml_tpu.telemetry.memory` — HBM accounting over
  ``device.memory_stats()``: per-phase peak gauges, table-size estimates,
  and a headroom check that warns BEFORE a predicted allocation OOMs.
- :mod:`photon_ml_tpu.telemetry.progress` / ``.report`` — a heartbeat
  daemon that keeps long fits audible, and :class:`RunReport`, which
  merges trace + metrics + checkpoint manifests into one markdown/JSON
  report with a regression ``compare()`` (the ``cli report`` perf gate).
- :mod:`photon_ml_tpu.telemetry.xla` — device-level cost accounting:
  ``instrumented_jit`` records compile time, cost/memory analysis, and
  recompile attribution per executable; roofline peaks for MFU and
  bandwidth utilization; ``comms.*`` collective-bytes estimates (the
  run report's "Device utilization" section).
- :mod:`photon_ml_tpu.telemetry.profile` — the executable layer
  (ISSUE 16): every ``instrumented_jit`` dispatch is counted and every
  Nth honestly timed (fetch-synchronized through ``sync_fetch``),
  yielding per-executable exclusive seconds, MFU, arithmetic intensity,
  and a roofline bound class — the run report's "Hot executables" table
  and the heartbeat's ``hot_exec`` field. Armed at import; sampled, so
  steady-state overhead stays under 2%.
- :mod:`photon_ml_tpu.telemetry.identity` / ``.fleet_report`` — fleet
  observability (ISSUE 13): per-member artifact suffixing
  (``trace.proc-0.jsonl``), process identity + epoch anchors in every
  stream, and :class:`FleetReport`, which merges a fleet directory of
  member streams into one report with collective-wait/straggler
  attribution (``cli report --fleet``).

Typical use::

    from photon_ml_tpu import telemetry

    telemetry.configure(trace_out="run.trace.jsonl")
    with telemetry.Heartbeat(interval=30, jsonl_path="run.metrics.jsonl"):
        with telemetry.span("fit", task="logistic"):
            ...
            value = float(telemetry.sync_fetch(result.value, label="loss"))
    telemetry.flush_metrics("run.metrics.jsonl")
    telemetry.export_chrome_trace("run.trace.jsonl", "run.perfetto.json")

Importing this package installs the jit compile hooks (idempotent, and a
no-op without jax.monitoring), so recompiles are counted from the first
traced program onward.
"""

from __future__ import annotations

import os
from typing import Optional

from photon_ml_tpu.telemetry import (  # noqa: F401
    identity,
    memory,
    metrics,
    profile,
    trace,
    xla,
)
from photon_ml_tpu.telemetry import requests  # noqa: F401  (needs trace)
from photon_ml_tpu.telemetry.identity import member_artifact_path  # noqa: F401
from photon_ml_tpu.telemetry.device import (  # noqa: F401
    install_compile_hooks,
    sync_fetch,
)
from photon_ml_tpu.telemetry.xla import (  # noqa: F401
    XLA_REGISTRY,
    instrumented_jit,
    record_collective,
)
from photon_ml_tpu.telemetry.metrics import (  # noqa: F401
    counter,
    gauge,
    histogram,
    register_snapshot_provider,
    snapshot,
)
from photon_ml_tpu.telemetry.progress import Heartbeat  # noqa: F401
from photon_ml_tpu.telemetry.trace import (  # noqa: F401
    active_span_path,
    add_event,
    current_span,
    export_chrome_trace,
    finished_spans,
    perfetto_path,
    span,
    to_chrome_trace,
)

__all__ = [
    "span",
    "current_span",
    "add_event",
    "active_span_path",
    "finished_spans",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "register_snapshot_provider",
    "flush_metrics",
    "sync_fetch",
    "install_compile_hooks",
    "to_chrome_trace",
    "export_chrome_trace",
    "perfetto_path",
    "Heartbeat",
    "memory",
    "identity",
    "member_artifact_path",
    "xla",
    "profile",
    "requests",
    "instrumented_jit",
    "record_collective",
    "XLA_REGISTRY",
    "configure",
    "configure_from_env",
    "reset",
]

# configure_from_env side effects, remembered so reset() can undo them —
# without this, test ordering decides whether a leaked atexit flush or
# env-pointed sink survives into later tests (ISSUE 3 satellite).
_env_state: dict[str, object] = {"atexit_flush": None}


def configure(
    trace_out: Optional[str] = None,
    buffer_limit: Optional[int] = None,
) -> None:
    """Point the span JSONL sink at ``trace_out`` (None = leave as-is)."""
    trace.configure(jsonl_path=trace_out, buffer_limit=buffer_limit)


def flush_metrics(path: str) -> dict:
    """Append the metrics snapshot to ``path`` (``metrics.flush_jsonl``),
    after flushing the executable profiler's lazily-published derived
    gauges (MFU, bound class, ...) so offline report loads rebuild the
    Hot-executables table from the JSONL alone."""
    profile.publish_metrics()
    return metrics.flush_jsonl(path)


def configure_from_env() -> None:
    """Honor ``PHOTON_TRACE_OUT`` / ``PHOTON_TELEMETRY_OUT`` env vars: the
    span sink opens immediately; the metrics snapshot flushes at process
    exit. Lets benchmarks and ad-hoc scripts opt in without new flags.
    ``reset()`` fully undoes both (including the atexit hook).

    In a fleet (``PHOTON_PROC_ID`` set by the supervisor, or an
    already-initialized multi-process jax) both paths are suffixed per
    member (``trace.jsonl`` -> ``trace.proc-0.jsonl``) so N processes
    pointed at the same env value write N artifact streams instead of
    clobbering one file — the naming contract ``cli report --fleet``
    globs (telemetry.identity / telemetry.fleet_report)."""
    trace_out = os.environ.get("PHOTON_TRACE_OUT")
    if trace_out:
        configure(trace_out=identity.member_artifact_path(trace_out))
    metrics_out = os.environ.get("PHOTON_TELEMETRY_OUT")
    if metrics_out:
        import atexit
        import functools

        metrics_out = identity.member_artifact_path(metrics_out)
        old = _env_state["atexit_flush"]
        if old is not None:
            atexit.unregister(old)
        flush = functools.partial(flush_metrics, metrics_out)
        atexit.register(flush)
        _env_state["atexit_flush"] = flush


def reset() -> None:
    """Restore telemetry to import-time defaults (test isolation): clear
    spans and metrics, close the trace sink, restore the default buffer
    limit, drop any injected memory-stats provider, and unregister the
    ``configure_from_env`` atexit flush."""
    trace.reset()
    metrics.reset()
    memory.reset()
    xla.reset()
    profile.reset()
    requests.reset()
    flush = _env_state["atexit_flush"]
    if flush is not None:
        import atexit

        atexit.unregister(flush)
        _env_state["atexit_flush"] = None


install_compile_hooks()
# arm the executable-level dispatch sampler (idempotent; profile.reset()
# re-arms, so test isolation never leaves profiling dark)
profile.install()
