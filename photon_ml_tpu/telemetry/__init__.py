"""Structured telemetry: tracing spans, a metrics registry, and
device/transfer accounting for the whole training stack.

Three layers (ISSUE: you can't optimize what you can't measure):

- :mod:`photon_ml_tpu.telemetry.trace` — ``span(name, **attrs)`` opens a
  node of a thread-safe hierarchical span tree with a JSONL sink and a
  Chrome-trace/Perfetto exporter. ``utils.timing.timed()`` is a thin
  wrapper over it, so every driver phase is already a span.
- :mod:`photon_ml_tpu.telemetry.metrics` — process-global counters /
  gauges / histograms with a ``snapshot()`` dict and a JSONL flush;
  attached to the final ``TrainingFinishEvent`` and the bench JSON.
- :mod:`photon_ml_tpu.telemetry.device` — ``sync_fetch()``, the one
  sanctioned device->host fetch point (fetches / bytes / blocking
  seconds), plus per-compile counters via ``jax.monitoring``.

Typical use::

    from photon_ml_tpu import telemetry

    telemetry.configure(trace_out="run.trace.jsonl")
    with telemetry.span("fit", task="logistic"):
        ...
        value = float(telemetry.sync_fetch(result.value, label="loss"))
    telemetry.flush_metrics("run.metrics.jsonl")
    telemetry.export_chrome_trace("run.trace.jsonl", "run.perfetto.json")

Importing this package installs the jit compile hooks (idempotent, and a
no-op without jax.monitoring), so recompiles are counted from the first
traced program onward.
"""

from __future__ import annotations

import os
from typing import Optional

from photon_ml_tpu.telemetry import metrics, trace  # noqa: F401
from photon_ml_tpu.telemetry.device import (  # noqa: F401
    install_compile_hooks,
    sync_fetch,
)
from photon_ml_tpu.telemetry.metrics import (  # noqa: F401
    counter,
    gauge,
    histogram,
    snapshot,
)
from photon_ml_tpu.telemetry.metrics import flush_jsonl as flush_metrics  # noqa: F401
from photon_ml_tpu.telemetry.trace import (  # noqa: F401
    add_event,
    current_span,
    export_chrome_trace,
    finished_spans,
    perfetto_path,
    span,
    to_chrome_trace,
)

__all__ = [
    "span",
    "current_span",
    "add_event",
    "finished_spans",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "flush_metrics",
    "sync_fetch",
    "install_compile_hooks",
    "to_chrome_trace",
    "export_chrome_trace",
    "perfetto_path",
    "configure",
    "configure_from_env",
    "reset",
]


def configure(
    trace_out: Optional[str] = None,
    buffer_limit: Optional[int] = None,
) -> None:
    """Point the span JSONL sink at ``trace_out`` (None = leave as-is)."""
    trace.configure(jsonl_path=trace_out, buffer_limit=buffer_limit)


def configure_from_env() -> None:
    """Honor ``PHOTON_TRACE_OUT`` / ``PHOTON_TELEMETRY_OUT`` env vars: the
    span sink opens immediately; the metrics snapshot flushes at process
    exit. Lets benchmarks and ad-hoc scripts opt in without new flags."""
    trace_out = os.environ.get("PHOTON_TRACE_OUT")
    if trace_out:
        configure(trace_out=trace_out)
    metrics_out = os.environ.get("PHOTON_TELEMETRY_OUT")
    if metrics_out:
        import atexit

        atexit.register(flush_metrics, metrics_out)


def reset() -> None:
    """Clear spans and metrics and close the trace sink (test isolation)."""
    trace.reset()
    metrics.reset()


install_compile_hooks()
