"""Run reports: turn telemetry exhaust into answers.

PR 1 gave the system raw emission (span JSONL, metrics snapshots, device
accounting) and PR 2 durable state (checkpoint manifests) — but reading a
run still meant loading a trace into Perfetto by hand. :class:`RunReport`
merges the three exhaust streams into one document:

- **phase-time breakdown**: the aggregated ``fit > cd_iteration >
  coordinate:<name>`` span tree with per-phase count/total/self time;
- **top-k costs** and **fetch/recompile accounting** (the tunnel tax and
  silent-recompile counters, summarized instead of eyeballed);
- **per-coordinate convergence and guard history** from the newest
  checkpoint manifest (retries, rollbacks, frozen coordinates, metrics);
- **heartbeat liveness** (count + last line) from the progress sink;
- ``key_metrics()`` — the scalar summary a CI perf gate compares runs by.

``compare(baseline)`` flags key-metric regressions beyond a threshold;
``python -m photon_ml_tpu.cli report --compare baseline.json
--fail-on-regress`` exits nonzero on any, so every future perf PR is
measurable against the last good run.

This module only READS artifacts (plus the live in-process registry via
:meth:`RunReport.from_live`); it never touches a device.
"""

from __future__ import annotations

import dataclasses
import datetime
import json
import os
import re
from typing import Any, Mapping, Optional, Sequence

__all__ = [
    "RunReport",
    "MetricDelta",
    "PhaseNode",
    "compare_metrics",
    "directions_with_exec",
    "KEY_METRIC_DIRECTIONS",
    "REPORT_FORMAT_VERSION",
    "report_path",
]

REPORT_FORMAT_VERSION = 1

#: Key metrics and their goodness direction: +1 higher-is-better,
#: -1 lower-is-better. Only metrics named here participate in compare().
KEY_METRIC_DIRECTIONS: dict[str, int] = {
    "rows_per_sec": +1,
    "coeffs_per_sec": +1,
    "fit_seconds": -1,
    "jit_compiles": -1,
    "jit_compile_seconds": -1,
    "device_fetches": -1,
    "device_fetch_seconds": -1,
    "dropped_spans": -1,
    "mfu": +1,
    "xla_recompiles": -1,
}

_STEP_MANIFEST_RE = re.compile(r"^step-(\d{8})$")


def directions_with_exec(*metric_dicts: Mapping[str, Any]) -> dict[str, int]:
    """``KEY_METRIC_DIRECTIONS`` extended with the dynamic per-executable
    utilization metrics (``exec.<name>.mfu``, higher is better) present
    in any of the given metric dicts — executable names are data, so they
    cannot be enumerated statically like the other keys."""
    directions = dict(KEY_METRIC_DIRECTIONS)
    for metrics_dict in metric_dicts:
        for name in metrics_dict:
            if name.startswith("exec.") and name.endswith(".mfu"):
                directions[name] = +1
    return directions

# Fields of the xla.exec.<name>.<field> metric names the executable table
# is reconstructed from (suffix-matched: executable names may contain
# dots, field names never do).
_XLA_EXEC_COUNTER_FIELDS = (
    "calls",
    "compiles",
    "compile_seconds",
    "recompiles",
    "flops_total",
    "bytes_total",
)
_XLA_EXEC_GAUGE_FIELDS = ("flops_per_call", "bytes_per_call", "temp_bytes")

# Fields of the profile.exec.<name>.<field> gauges the Hot-executables
# table is reconstructed from (same suffix-match convention).
_PROFILE_EXEC_GAUGE_FIELDS = (
    "dispatches",
    "sampled",
    "sampled_seconds",
    "est_exclusive_seconds",
    "mean_dispatch_seconds",
    "mfu",
    "intensity",
    "bound_code",
    "timing_suspect",
)

# Human names for the profiler's numeric bound-class codes, kept in sync
# with telemetry.profile.BOUND_CLASS_NAMES (duplicated so reports load
# without importing the profiler stack).
_BOUND_CLASS_NAMES = {
    0: "unknown",
    1: "MXU-bound",
    2: "VPU-bound",
    3: "HBM-bound",
    4: "dispatch-bound",
}

# device_utilization() cache sentinel (the computed value may be None)
_DU_UNSET = object()


@dataclasses.dataclass
class MetricDelta:
    """One key metric compared against a baseline value."""

    metric: str
    current: float
    baseline: float
    change: float  # signed fraction: (current - baseline) / baseline
    regressed: bool

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def compare_metrics(
    current: Mapping[str, float],
    baseline: Mapping[str, float],
    threshold: float = 0.2,
    directions: Optional[Mapping[str, int]] = None,
) -> list[MetricDelta]:
    """Compare two key-metric dicts; a metric is *regressed* when it moved
    against its goodness direction by more than ``threshold`` (fractional,
    default 20%). Metrics missing from either side, or with a zero
    baseline (no ratio exists), are skipped. Shared by the run-report
    compare and the bench_suite ``--gate``."""
    directions = KEY_METRIC_DIRECTIONS if directions is None else directions
    out: list[MetricDelta] = []
    for name in sorted(set(current) & set(baseline)):
        direction = directions.get(name)
        if direction is None:
            continue
        cur, base = float(current[name]), float(baseline[name])
        if base == 0:
            continue
        change = (cur - base) / abs(base)
        regressed = (direction > 0 and change < -threshold) or (
            direction < 0 and change > threshold
        )
        out.append(
            MetricDelta(
                metric=name,
                current=cur,
                baseline=base,
                change=change,
                regressed=regressed,
            )
        )
    return out


@dataclasses.dataclass
class PhaseNode:
    """One aggregated node of the phase-time tree (all spans sharing the
    same name-path merged: count, total wall time, and self time).

    ``flops``/``bytes``/``comms_bytes`` hold the device-cost attrs the
    instrumented-jit layer accumulated on spans AT this node; the
    ``subtree_*`` accessors include descendants — the per-phase roofline
    numerators."""

    name: str
    count: int = 0
    total_s: float = 0.0
    flops: float = 0.0
    bytes: float = 0.0
    comms_bytes: float = 0.0
    children: dict[str, "PhaseNode"] = dataclasses.field(default_factory=dict)

    @property
    def self_s(self) -> float:
        return max(
            self.total_s - sum(c.total_s for c in self.children.values()), 0.0
        )

    def _subtree(self, field: str) -> float:
        return getattr(self, field) + sum(
            c._subtree(field) for c in self.children.values()
        )

    @property
    def subtree_flops(self) -> float:
        return self._subtree("flops")

    @property
    def subtree_bytes(self) -> float:
        return self._subtree("bytes")

    @property
    def subtree_comms_bytes(self) -> float:
        return self._subtree("comms_bytes")

    def to_dict(self) -> dict[str, Any]:
        d = {
            "name": self.name,
            "count": self.count,
            "total_s": round(self.total_s, 6),
            "self_s": round(self.self_s, 6),
            "children": [
                c.to_dict()
                for c in sorted(
                    self.children.values(), key=lambda c: -c.total_s
                )
            ],
        }
        if self.subtree_flops:
            d["flops"] = self.subtree_flops
        if self.subtree_bytes:
            d["bytes_accessed"] = self.subtree_bytes
        if self.subtree_comms_bytes:
            d["comms_bytes"] = self.subtree_comms_bytes
        return d


def build_phase_tree(spans: Sequence[Mapping[str, Any]]) -> PhaseNode:
    """Aggregate span records (``Span.to_dict()`` / trace JSONL lines)
    into a name-path tree under a synthetic root. Spans whose parents fell
    out of a bounded buffer root at their earliest surviving ancestor."""
    by_id = {s.get("id"): s for s in spans if s.get("id") is not None}
    root = PhaseNode(name="")
    for s in spans:
        names: list[str] = []
        cur: Optional[Mapping[str, Any]] = s
        seen: set[Any] = set()
        while cur is not None and cur.get("id") not in seen:
            seen.add(cur.get("id"))
            names.append(str(cur.get("name", "?")))
            parent = cur.get("parent")
            cur = by_id.get(parent) if parent is not None else None
        node = root
        for name in reversed(names):
            node = node.children.setdefault(name, PhaseNode(name=name))
        node.count += 1
        node.total_s += float(s.get("dur") or 0.0)
        attrs = s.get("attrs") or {}
        node.flops += float(attrs.get("xla_flops") or 0.0)
        node.bytes += float(attrs.get("xla_bytes") or 0.0)
        node.comms_bytes += float(attrs.get("comms_bytes") or 0.0)
    return root


def report_path(trace_out: str) -> str:
    """Sibling ``.report.md`` path for a trace/telemetry JSONL path."""
    base = trace_out[:-6] if trace_out.endswith(".jsonl") else trace_out
    return base + ".report.md"


def _read_jsonl(path: str) -> list[dict]:
    records = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # a crashed run leaves a truncated last line
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _load_checkpoint_manifests(directory: str) -> list[dict]:
    """Every readable ``step-*/manifest.json`` under ``directory``, oldest
    first. Reads only — no dependency on the checkpoint module (reports
    must load anywhere, including hosts without the training stack)."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in sorted(names):
        if not _STEP_MANIFEST_RE.match(name):
            continue
        path = os.path.join(directory, name, "manifest.json")
        try:
            with open(path, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError):
            continue  # partial/corrupt checkpoints are the restore path's job
        if isinstance(manifest, dict):
            out.append(manifest)
    return out


@dataclasses.dataclass
class RunReport:
    """One run's merged telemetry: spans + metrics snapshot + heartbeats +
    checkpoint manifests, with markdown/JSON rendering and compare()."""

    spans: list[dict] = dataclasses.field(default_factory=list)
    snapshot: dict = dataclasses.field(default_factory=dict)
    heartbeats: list[dict] = dataclasses.field(default_factory=list)
    manifests: list[dict] = dataclasses.field(default_factory=list)
    sources: dict = dataclasses.field(default_factory=dict)

    # -- construction --------------------------------------------------------

    @classmethod
    def load(
        cls,
        trace: Optional[str] = None,
        telemetry: Optional[str] = None,
        checkpoint_dir: Optional[str] = None,
    ) -> "RunReport":
        """Build from on-disk artifacts: a span JSONL (``--trace-out``), a
        telemetry JSONL (``--telemetry-out``; its last ``metrics`` line is
        the snapshot, its ``heartbeat`` lines the liveness record), and a
        checkpoint directory's manifests."""
        spans: list[dict] = []
        snapshot: dict = {}
        heartbeats: list[dict] = []
        manifests: list[dict] = []
        if trace:
            spans = [
                r for r in _read_jsonl(trace) if r.get("type") == "span"
            ]
        if telemetry:
            for rec in _read_jsonl(telemetry):
                if rec.get("type") == "metrics":
                    snapshot = rec.get("snapshot") or {}
                elif rec.get("type") == "heartbeat":
                    heartbeats.append(rec)
        if checkpoint_dir:
            manifests = _load_checkpoint_manifests(checkpoint_dir)
        return cls(
            spans=spans,
            snapshot=snapshot,
            heartbeats=heartbeats,
            manifests=manifests,
            sources={
                "trace": trace,
                "telemetry": telemetry,
                "checkpoint_dir": checkpoint_dir,
            },
        )

    @classmethod
    def from_live(
        cls, checkpoint_dir: Optional[str] = None
    ) -> "RunReport":
        """Build from THIS process's live registries (the train driver's
        ``--report-out`` path needs no re-parse of its own sinks)."""
        from photon_ml_tpu.telemetry import metrics, profile, trace

        # the profiler publishes its derived gauges (MFU, bound class)
        # lazily — flush them so the snapshot carries the hot list
        profile.publish_metrics()
        return cls(
            spans=[s.to_dict() for s in trace.finished_spans()],
            snapshot=metrics.snapshot(),
            manifests=(
                _load_checkpoint_manifests(checkpoint_dir)
                if checkpoint_dir
                else []
            ),
            sources={"live": True, "checkpoint_dir": checkpoint_dir},
        )

    # -- derived views -------------------------------------------------------

    def phase_tree(self) -> PhaseNode:
        return build_phase_tree(self.spans)

    def top_spans(self, k: int = 10) -> list[dict]:
        """Top-k span NAMES by total wall time (count + total), the
        flame-chart hotspots without opening Perfetto."""
        agg: dict[str, list[float]] = {}
        for s in self.spans:
            entry = agg.setdefault(str(s.get("name", "?")), [0, 0.0])
            entry[0] += 1
            entry[1] += float(s.get("dur") or 0.0)
        ranked = sorted(agg.items(), key=lambda kv: -kv[1][1])[:k]
        return [
            {"name": name, "count": int(c), "total_s": round(t, 6)}
            for name, (c, t) in ranked
        ]

    def key_metrics(self) -> dict[str, float]:
        """The scalar summary compare() gates on."""
        counters = self.snapshot.get("counters", {})
        gauges = self.snapshot.get("gauges", {})
        out: dict[str, float] = {}
        # OUTERMOST fit spans only: the train driver's timed("fit") phase
        # wraps the estimator's own fit span — summing both would double
        # the wall time
        by_id = {
            s.get("id"): s for s in self.spans if s.get("id") is not None
        }

        def _has_fit_ancestor(s) -> bool:
            seen: set[Any] = set()
            parent = s.get("parent")
            while parent is not None and parent not in seen:
                seen.add(parent)
                p = by_id.get(parent)
                if p is None:
                    return False
                if p.get("name") == "fit":
                    return True
                parent = p.get("parent")
            return False

        fit_s = sum(
            float(s.get("dur") or 0.0)
            for s in self.spans
            if s.get("name") == "fit" and not _has_fit_ancestor(s)
        )
        if fit_s:
            out["fit_seconds"] = round(fit_s, 6)
        for key, gauge_name in (
            ("rows_per_sec", "progress.rows_per_sec"),
            ("coeffs_per_sec", "progress.coeffs_per_sec"),
        ):
            value = gauges.get(gauge_name)
            if value is not None:
                out[key] = float(value)
        for name in (
            "jit_compiles",
            "jit_compile_seconds",
            "device_fetches",
            "device_fetch_seconds",
        ):
            if name in counters:
                out[name] = float(counters[name])
        dropped = counters.get("trace.dropped_spans")
        if dropped:
            out["dropped_spans"] = float(dropped)
        sweep_metric = gauges.get("sweep.selected_metric")
        if sweep_metric is not None:
            out["sweep_selected_metric"] = float(sweep_metric)
        recompiles = counters.get("xla.recompiles")
        if recompiles:
            out["xla_recompiles"] = float(recompiles)
        ingest_rate = gauges.get("ingest.rows_per_sec")
        if ingest_rate is not None:
            out["ingest_rows_per_sec"] = float(ingest_rate)
        ttf = gauges.get("incremental.time_to_fresh_s")
        if ttf is not None:
            out["time_to_fresh_s"] = float(ttf)
        du = self.device_utilization()
        if du is not None and du.get("mfu") is not None:
            out["mfu"] = float(du["mfu"])
        # per-executable MFU from the profiler (exec.<name>.mfu): lets a
        # compare flag "THIS kernel's utilization regressed" — names only
        # present on one side are skipped by compare_metrics (renamed/new
        # executables must never crash a baseline comparison)
        prefix, suffix = "profile.exec.", ".mfu"
        for key, value in gauges.items():
            if (
                key.startswith(prefix)
                and key.endswith(suffix)
                and value is not None
            ):
                name = key[len(prefix): -len(suffix)]
                out[f"exec.{name}.mfu"] = float(value)
        return out

    def coordinate_summary(self) -> list[dict]:
        """Per-coordinate convergence + guard history from the NEWEST
        checkpoint manifest (steps, seconds, retries, rollbacks, frozen
        status, last validation metrics)."""
        if not self.manifests:
            return []
        manifest = self.manifests[-1]
        frozen = set(manifest.get("frozen") or ())
        rollback_counts = manifest.get("consecutive_rollbacks") or {}
        agg: dict[str, dict[str, Any]] = {}
        for entry in manifest.get("history") or ():
            name = entry.get("coordinate")
            if name is None:
                continue
            c = agg.setdefault(
                name,
                {
                    "coordinate": name,
                    "steps": 0,
                    "seconds": 0.0,
                    "solve_retries": 0,
                    "rollbacks": 0,
                    "last_metrics": None,
                },
            )
            c["steps"] += 1
            c["seconds"] += float(entry.get("seconds") or 0.0)
            c["solve_retries"] += int(entry.get("solve_retries") or 0)
            c["rollbacks"] += 1 if entry.get("rolled_back") else 0
            if entry.get("metrics") is not None:
                c["last_metrics"] = entry["metrics"]
        for name, c in agg.items():
            c["seconds"] = round(c["seconds"], 6)
            c["frozen"] = name in frozen
            c["consecutive_rollbacks"] = int(rollback_counts.get(name, 0))
        return sorted(agg.values(), key=lambda c: c["coordinate"])

    def sweep_summary(self) -> Optional[dict[str, Any]]:
        """Per-config convergence record of a hyperparameter sweep, from
        the ``sweep_config`` spans the sweep runner emits (one per lane,
        attrs: λs, iterations, convergence reason, final loss, validation
        metric) plus the ``sweep.*`` counters/gauges. None when the run
        swept nothing."""
        configs = []
        for s in self.spans:
            if s.get("name") != "sweep_config":
                continue
            attrs = s.get("attrs") or {}
            configs.append(
                {
                    "index": attrs.get("index"),
                    "lambdas": {
                        k: v
                        for k, v in attrs.items()
                        if k == "lambda" or k.startswith("lambda.")
                    },
                    "iterations": attrs.get("iterations"),
                    "reason": attrs.get("reason"),
                    "final_loss": attrs.get("final_loss"),
                    "metric": attrs.get("metric"),
                    "metric_name": attrs.get("metric_name"),
                }
            )
        gauges = self.snapshot.get("gauges", {})
        counters = self.snapshot.get("counters", {})
        total = gauges.get("sweep.configs_total")
        if not configs and not total:
            return None
        configs.sort(key=lambda c: (c["index"] is None, c["index"]))
        out: dict[str, Any] = {"configs": configs}
        if total is not None:
            out["configs_total"] = int(total)
            out["configs_done"] = int(gauges.get("sweep.configs_done") or 0)
        if gauges.get("sweep.selected_index") is not None:
            out["selected_index"] = int(gauges["sweep.selected_index"])
            out["selected_metric"] = gauges.get("sweep.selected_metric")
        for name in ("sweep.solves", "sweep.nan_configs",
                     "sweep.published_versions"):
            if name in counters:
                out[name.split(".", 1)[1]] = counters[name]
        return out

    def _sweep_markdown(self) -> list[str]:
        sweep = self.sweep_summary()
        if sweep is None:
            return []
        out = ["## Hyperparameter sweep", ""]
        if "configs_total" in sweep:
            out.append(
                f"- {sweep['configs_done']}/{sweep['configs_total']} "
                "config(s) processed"
            )
        if "selected_index" in sweep:
            out.append(
                f"- selected config **#{sweep['selected_index']}** "
                f"(metric {_fmt_or_unknown(sweep.get('selected_metric'))})"
            )
        if sweep.get("nan_configs"):
            out.append(
                f"- **{int(sweep['nan_configs'])} config(s) excluded** "
                "(non-finite validation metric)"
            )
        configs = sweep["configs"]
        if configs:
            lam_keys: list[str] = []
            for c in configs:
                for k in c["lambdas"]:
                    if k not in lam_keys:
                        lam_keys.append(k)
            metric_name = next(
                (c["metric_name"] for c in configs if c.get("metric_name")),
                "metric",
            )
            header = (
                ["config"] + [f"`{k}`" for k in lam_keys]
                + ["iterations", "reason", "final loss", str(metric_name)]
            )
            out += [
                "",
                "| " + " | ".join(header) + " |",
                "|" + "---|" * len(header),
            ]
            for c in configs:
                row = [str(c["index"])]
                row += [
                    _fmt_or_unknown(c["lambdas"].get(k)) for k in lam_keys
                ]
                row += [
                    _fmt_or_unknown(c["iterations"]),
                    str(c["reason"] or "?"),
                    _fmt_or_unknown(c["final_loss"]),
                    _fmt_or_unknown(c["metric"]),
                ]
                out.append("| " + " | ".join(row) + " |")
        out.append("")
        return out

    # -- device utilization (telemetry.xla) ----------------------------------

    def xla_executables(self, k: int = 10) -> list[dict]:
        """Top-k accounted executables, reconstructed from the
        ``xla.exec.<name>.<field>`` metrics so a report loaded from a
        metrics JSONL alone still ranks them. Ranked by total FLOPs when
        known, else by compile seconds."""
        counters = self.snapshot.get("counters", {})
        gauges = self.snapshot.get("gauges", {})
        execs: dict[str, dict[str, Any]] = {}
        for source, fields in (
            (counters, _XLA_EXEC_COUNTER_FIELDS),
            (gauges, _XLA_EXEC_GAUGE_FIELDS),
        ):
            for key, value in source.items():
                if not key.startswith("xla.exec.") or value is None:
                    continue
                rest = key[len("xla.exec."):]
                for field in fields:
                    if rest.endswith("." + field):
                        name = rest[: -len(field) - 1]
                        execs.setdefault(name, {"name": name})[field] = value
                        break
        ranked = sorted(
            execs.values(),
            key=lambda e: (
                e.get("flops_total") or 0.0,
                e.get("compile_seconds") or 0.0,
            ),
            reverse=True,
        )
        return ranked[:k]

    def hot_executables(self, k: int = 10) -> list[dict]:
        """Top-k executables by estimated exclusive device time, from the
        ``profile.exec.<name>.<field>`` gauges (the executable-level
        profiler's sampled HONEST timings — see telemetry.profile), so a
        report loaded from a metrics JSONL alone still ranks them.
        Each row carries MFU / intensity / bound class plus the matching
        ``xla.exec.<name>.*`` compile split and recompile count. Empty
        when the run carried no profiled dispatches."""
        gauges = self.snapshot.get("gauges", {})
        counters = self.snapshot.get("counters", {})
        execs: dict[str, dict[str, Any]] = {}
        for key, value in gauges.items():
            if not key.startswith("profile.exec.") or value is None:
                continue
            rest = key[len("profile.exec."):]
            for field in _PROFILE_EXEC_GAUGE_FIELDS:
                if rest.endswith("." + field):
                    name = rest[: -len(field) - 1]
                    execs.setdefault(name, {"name": name})[field] = value
                    break
        for e in execs.values():
            e["bound_class"] = _BOUND_CLASS_NAMES.get(
                int(e.get("bound_code") or 0), "unknown"
            )
            e["timing_suspect"] = bool(e.get("timing_suspect"))
            for field, source in (
                ("compile_seconds", counters),
                ("recompiles", counters),
            ):
                v = source.get(f"xla.exec.{e['name']}.{field}")
                if v is not None:
                    e[field] = v
        ranked = sorted(
            execs.values(),
            key=lambda e: e.get("est_exclusive_seconds") or 0.0,
            reverse=True,
        )
        return ranked[:k]

    def device_utilization(self) -> Optional[dict[str, Any]]:
        """Roofline accounting for the run: overall + per-phase FLOPs,
        MFU, HBM-bandwidth utilization, comms bytes/fraction, and
        compile-time share. ``None`` when the run carried no
        instrumented-jit accounting at all; individual fields are None
        ("unknown") when the backend offers no cost analysis or the
        device peaks are unknown. Cached per instance: a report render
        consumes it from key_metrics, markdown, AND to_json, and the
        underlying spans/snapshot never change after construction."""
        cached = self.__dict__.get("_du_cache", _DU_UNSET)
        if cached is not _DU_UNSET:
            return cached
        du = self._device_utilization()
        self.__dict__["_du_cache"] = du
        return du

    def _device_utilization(self) -> Optional[dict[str, Any]]:
        counters = self.snapshot.get("counters", {})
        gauges = self.snapshot.get("gauges", {})
        if not any(
            k.startswith(("xla.", "comms.")) for k in counters
        ):
            return None
        peak_flops = gauges.get("device.peak_flops")
        peak_bw = gauges.get("device.peak_hbm_bytes_per_sec")
        tree = self.phase_tree()
        run_total_s = sum(c.total_s for c in tree.children.values())
        flops_total = counters.get("xla.flops_total")
        bytes_total = counters.get("xla.bytes_total")
        comms_total = counters.get("comms.bytes_total")
        compile_s = counters.get(
            "xla.compile_seconds", counters.get("jit_compile_seconds")
        )

        def _util(work, peak, seconds):
            if work is None or not peak or not seconds:
                return None
            return work / (peak * seconds)

        def _comms_fraction(comms, hbm_bytes):
            # comms recorded but HBM bytes unknown (no cost analysis):
            # the denominator is unknowable — say "unknown", never 100%
            if hbm_bytes is None:
                return None
            total = (comms or 0.0) + hbm_bytes
            return (comms or 0.0) / total if total else None

        phases: list[dict[str, Any]] = []

        def walk(node: PhaseNode, path: list[str]) -> None:
            for child in sorted(
                node.children.values(), key=lambda c: -c.total_s
            ):
                p = path + [child.name]
                f = child.subtree_flops or None
                b = child.subtree_bytes or None
                cb = child.subtree_comms_bytes or None
                if f or b or cb:
                    phases.append(
                        {
                            "phase": " > ".join(p),
                            "total_s": round(child.total_s, 6),
                            "flops": f,
                            "bytes_accessed": b,
                            "comms_bytes": cb,
                            "mfu": _util(f, peak_flops, child.total_s),
                            "bandwidth_utilization": _util(
                                b, peak_bw, child.total_s
                            ),
                            "comms_fraction": _comms_fraction(cb, b),
                        }
                    )
                walk(child, p)

        walk(tree, [])
        return {
            "peak_flops": peak_flops,
            "peak_hbm_bytes_per_sec": peak_bw,
            "flops_total": flops_total,
            "bytes_accessed_total": bytes_total,
            "comms_bytes_total": comms_total,
            "mfu": _util(flops_total, peak_flops, run_total_s),
            "bandwidth_utilization": _util(bytes_total, peak_bw, run_total_s),
            "comms_fraction": _comms_fraction(comms_total, bytes_total),
            "compile_seconds": compile_s,
            "compile_time_share": (
                compile_s / run_total_s
                if compile_s is not None and run_total_s
                else None
            ),
            "recompiles": counters.get("xla.recompiles", 0),
            "phases": phases,
            "top_executables": self.xla_executables(),
        }

    # -- compare -------------------------------------------------------------

    def compare(
        self,
        baseline: Mapping[str, Any],
        threshold: float = 0.2,
    ) -> list[MetricDelta]:
        """Compare against a baseline: either a full report JSON document
        (``to_json()`` output — its ``key_metrics`` field is used) or a
        bare ``{metric: value}`` dict. Per-executable rows
        (``exec.<name>.mfu``) compare when the executable exists on both
        sides; renamed/new executables are skipped by compare_metrics'
        shared-keys rule instead of crashing."""
        base = baseline.get("key_metrics", baseline)
        current = self.key_metrics()
        return compare_metrics(
            current,
            base,
            threshold=threshold,
            directions=directions_with_exec(current, base),
        )

    # -- rendering -----------------------------------------------------------

    def to_json(self) -> dict[str, Any]:
        counters = self.snapshot.get("counters", {})
        doc: dict[str, Any] = {
            "type": "run_report",
            "format_version": REPORT_FORMAT_VERSION,
            "generated": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "sources": self.sources,
            "key_metrics": self.key_metrics(),
            "phases": self.phase_tree().to_dict()["children"],
            "top_spans": self.top_spans(),
            "coordinates": self.coordinate_summary(),
            "sweep": self.sweep_summary(),
            "device_utilization": self.device_utilization(),
            "hot_executables": self.hot_executables(),
            "ingestion": self.ingestion_summary(),
            "serving": self.serving_summary(),
            "requests": self.requests_summary(),
            "slowest_requests": self.slowest_requests(),
            "recovery": self.recovery_summary(),
            "freshness": self.freshness_summary(),
            "pipeline": self.pipeline_summary(),
            "quality": self.quality_summary(),
            "counters": counters,
            "gauges": self.snapshot.get("gauges", {}),
            "histograms": self.snapshot.get("histograms", {}),
            "heartbeats": {
                "count": len(self.heartbeats),
                "last": self.heartbeats[-1] if self.heartbeats else None,
            },
        }
        if self.manifests:
            doc["checkpoint"] = {
                "steps": [int(m.get("step", -1)) for m in self.manifests],
                "last_step": int(self.manifests[-1].get("step", -1)),
                "best_metric": self.manifests[-1].get("best_metric"),
            }
        return doc

    def save_json(self, path: str) -> dict[str, Any]:
        from photon_ml_tpu.utils.atomic import atomic_write_json

        doc = self.to_json()
        atomic_write_json(path, doc, indent=2, sort_keys=True, default=str)
        return doc

    def to_markdown(
        self, deltas: Optional[Sequence[MetricDelta]] = None
    ) -> str:
        lines: list[str] = ["# Run report", ""]
        src = ", ".join(
            f"{k}=`{v}`" for k, v in self.sources.items() if v
        )
        if src:
            lines += [f"_Sources: {src}_", ""]

        metrics_now = self.key_metrics()
        if metrics_now:
            lines += ["## Key metrics", "", "| metric | value |", "|---|---|"]
            for name, value in sorted(metrics_now.items()):
                lines.append(f"| `{name}` | {_fmt(value)} |")
            lines.append("")

        tree = self.phase_tree()
        if tree.children:
            run_total = sum(c.total_s for c in tree.children.values())
            lines += ["## Phase time breakdown", ""]
            _render_tree(tree, 0, run_total, lines)
            lines.append("")

        top = self.top_spans()
        if top:
            lines += [
                "## Top spans by total time",
                "",
                "| span | count | total s |",
                "|---|---|---|",
            ]
            for t in top:
                lines.append(
                    f"| `{t['name']}` | {t['count']} | {t['total_s']:.3f} |"
                )
            lines.append("")

        lines += self._device_utilization_markdown()
        lines += self._hot_executables_markdown()
        lines += self._accounting_markdown()
        lines += self._ingestion_markdown()
        lines += self._serving_markdown()
        lines += self._requests_markdown()
        lines += self._recovery_markdown()
        lines += self._freshness_markdown()
        lines += self._pipeline_markdown()
        lines += self._quality_markdown()
        lines += self._memory_markdown()
        lines += self._coordinates_markdown()
        lines += self._sweep_markdown()
        lines += self._heartbeat_markdown()

        dropped = self.snapshot.get("counters", {}).get("trace.dropped_spans")
        if dropped:
            lines += [
                f"> **Warning**: {int(dropped)} span(s) were dropped from "
                "the bounded trace buffer — phase totals undercount; raise "
                "`telemetry.configure(buffer_limit=...)`.",
                "",
            ]

        if deltas is not None:
            lines += _compare_markdown(deltas)
        return "\n".join(lines).rstrip() + "\n"

    def _device_utilization_markdown(self) -> list[str]:
        du = self.device_utilization()
        if du is None:
            return []
        out = ["## Device utilization", ""]
        peak = du["peak_flops"]
        out.append(
            "- MFU: "
            + _fmt_pct(du["mfu"])
            + (
                f" (peak {_fmt(peak / 1e12)} TFLOP/s)"
                if peak
                else " (device peak FLOP/s unknown)"
            )
        )
        out.append(
            "- HBM bandwidth utilization: "
            + _fmt_pct(du["bandwidth_utilization"])
            + (
                f" (peak {_fmt_bytes(du['peak_hbm_bytes_per_sec'])}/s)"
                if du["peak_hbm_bytes_per_sec"]
                else " (device peak bandwidth unknown)"
            )
        )
        out.append(
            f"- total FLOPs: {_fmt_or_unknown(du['flops_total'])}; "
            f"bytes accessed: "
            + (
                _fmt_bytes(du["bytes_accessed_total"])
                if du["bytes_accessed_total"] is not None
                else "unknown"
            )
        )
        comms = du["comms_bytes_total"]
        out.append(
            "- estimated collective bytes: "
            + (_fmt_bytes(comms) if comms is not None else "unknown")
            + f" (comms fraction {_fmt_pct(du['comms_fraction'])})"
        )
        out.append(
            "- compile time: "
            + (
                f"{_fmt(du['compile_seconds'])}s "
                f"({_fmt_pct(du['compile_time_share'])} of run)"
                if du["compile_seconds"] is not None
                else "unknown"
            )
            + f"; recompiles: {int(du['recompiles'])}"
        )
        if du["phases"]:
            out += [
                "",
                "| phase | s | FLOPs | MFU | bytes | BW util | comms |",
                "|---|---|---|---|---|---|---|",
            ]
            for p in du["phases"]:
                out.append(
                    f"| `{p['phase']}` | {p['total_s']:.3f} | "
                    f"{_fmt_or_unknown(p['flops'])} | "
                    f"{_fmt_pct(p['mfu'])} | "
                    + (
                        _fmt_bytes(p["bytes_accessed"])
                        if p["bytes_accessed"] is not None
                        else "unknown"
                    )
                    + f" | {_fmt_pct(p['bandwidth_utilization'])} | "
                    + (
                        _fmt_bytes(p["comms_bytes"])
                        if p["comms_bytes"] is not None
                        else "—"
                    )
                    + " |"
                )
        top = du["top_executables"]
        if top:
            out += [
                "",
                "Top executables by cost:",
                "",
                "| executable | calls | compiles | compile s | "
                "FLOPs total | bytes total | recompiles |",
                "|---|---|---|---|---|---|---|",
            ]
            for e in top:
                out.append(
                    f"| `{e['name']}` | {_fmt(e.get('calls'))} | "
                    f"{_fmt(e.get('compiles'))} | "
                    f"{_fmt(e.get('compile_seconds'))} | "
                    f"{_fmt_or_unknown(e.get('flops_total'))} | "
                    f"{_fmt_or_unknown(e.get('bytes_total'))} | "
                    f"{_fmt(e.get('recompiles') or 0)} |"
                )
        out.append("")
        return out

    def _hot_executables_markdown(self, k: int = 10) -> list[str]:
        hot = self.hot_executables(k)
        if not hot:
            return []
        out = [
            "## Hot executables",
            "",
            "_Sampled honest timings per executable (telemetry.profile): "
            "exclusive device seconds are extrapolated from every-Nth "
            "fetch-synchronized measurements; see README \"Profiling\"._",
            "",
            "| executable | excl s | dispatches | mean ms | MFU | "
            "intensity | bound | compile s | recompiles |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for e in hot:
            mean = e.get("mean_dispatch_seconds")
            name = e["name"] + (" ⚠" if e["timing_suspect"] else "")
            out.append(
                f"| `{name}` | "
                f"{_fmt(e.get('est_exclusive_seconds'))} | "
                f"{_fmt(e.get('dispatches'))} | "
                f"{_fmt(None if mean is None else mean * 1e3)} | "
                f"{_fmt_pct(e.get('mfu'))} | "
                f"{_fmt_or_unknown(e.get('intensity'))} | "
                f"{e['bound_class']} | "
                f"{_fmt(e.get('compile_seconds'))} | "
                f"{_fmt(e.get('recompiles') or 0)} |"
            )
        suspects = [e["name"] for e in hot if e["timing_suspect"]]
        if suspects:
            out += [
                "",
                "> **Warning — timing suspect**: "
                + ", ".join(f"`{n}`" for n in suspects)
                + " measured ABOVE the resolved device peak, which is "
                "physically impossible — the clock is not seeing the "
                "device (PERF_NOTES: only a device->host fetch truly "
                "syncs). Treat these rates as fake.",
            ]
        out.append("")
        return out

    def _accounting_markdown(self) -> list[str]:
        c = self.snapshot.get("counters", {})
        h = self.snapshot.get("histograms", {})
        rows = []
        for name in (
            "device_fetches",
            "device_fetch_bytes",
            "device_fetch_seconds",
            "jit_compiles",
            "jit_compile_seconds",
        ):
            if name in c:
                extra = ""
                hist = h.get(name) if name.endswith("seconds") else None
                if hist and hist.get("count"):
                    extra = (
                        f"p50 {_fmt(hist.get('p50'))}, "
                        f"p95 {_fmt(hist.get('p95'))}"
                    )
                rows.append((name, c[name], extra))
        if not rows:
            return []
        out = [
            "## Fetch / compile accounting",
            "",
            "| counter | total | distribution |",
            "|---|---|---|",
        ]
        for name, value, extra in rows:
            out.append(f"| `{name}` | {_fmt(value)} | {extra} |")
        out.append("")
        return out

    def ingestion_summary(self) -> Optional[dict[str, Any]]:
        """Ingest-pipeline accounting, or None when no stream ran.

        The headline is ``solve_waits``/``solve_wait_seconds``: whether
        (and for how long) the SOLVE ever waited on data after warm-up —
        zero means the decode/upload/solve overlap fully hid ingestion;
        a large fraction of the chunks means the fit is ingest-bound and
        needs more decode workers or deeper prefetch.
        """
        c = self.snapshot.get("counters", {})
        g = self.snapshot.get("gauges", {})
        h = self.snapshot.get("histograms", {})
        if "ingest.chunks" not in c and "ingest.rows" not in c:
            return None
        wait = h.get("ingest.solve_wait_s") or {}
        out: dict[str, Any] = {
            "rows": c.get("ingest.rows"),
            "chunks": c.get("ingest.chunks"),
            "rows_per_sec": g.get("ingest.rows_per_sec"),
            "stalls": c.get("ingest.stalls", 0),
            "buffer_growths": c.get("ingest.buffer_growths", 0),
            "read_retries": c.get("ingest.read_retries", 0),
            "solve_waits": c.get("ingest.solve_waits", 0),
            "solve_wait_seconds": (
                round(wait["mean"] * wait["count"], 6)
                if wait.get("count") and wait.get("mean") is not None
                else 0.0
            ),
            "staging_bytes": g.get("ingest.staging_bytes"),
            "queue_depth_last": g.get("ingest.queue_depth"),
        }
        return out

    def _ingestion_markdown(self) -> list[str]:
        ing = self.ingestion_summary()
        if ing is None:
            return []
        out = ["## Ingestion", ""]
        rows = ing.get("rows")
        if rows is not None:
            rate = ing.get("rows_per_sec")
            out.append(
                f"- streamed {int(rows)} rows in "
                f"{int(ing.get('chunks') or 0)} chunks"
                + (f" ({rate:,.0f} rows/s end-to-end)" if rate else "")
            )
        if ing.get("staging_bytes") is not None:
            out.append(
                "- host staging ring: "
                f"{_fmt_bytes(ing['staging_bytes'])} resident"
            )
        waits = int(ing.get("solve_waits") or 0)
        if waits:
            out.append(
                f"- **the solve waited on data {waits} time(s)** "
                f"({ing['solve_wait_seconds']:.3f} s total) — the fit is "
                "(partly) ingest-bound; add decode workers or prefetch "
                "depth"
            )
        else:
            out.append(
                "- the solve never waited on data after warm-up — "
                "decode + upload fully overlapped the compute"
            )
        stalls = int(ing.get("stalls") or 0)
        if stalls:
            out.append(
                f"- **{stalls} pipeline stall(s)** (`ingest.stalls`) — "
                "a stage hit its stall timeout"
            )
        growths = int(ing.get("buffer_growths") or 0)
        if growths:
            out.append(
                f"- {growths} staging-buffer growth(s) — raise "
                "`nnz_per_row_hint` to pre-size the ring exactly"
            )
        retries = int(ing.get("read_retries") or 0)
        if retries:
            out.append(
                f"- {retries} transient read failure(s) absorbed by the "
                "per-chunk retry (`ingest.read_retries`) — the storage "
                "layer flaked but the stream survived"
            )
        out.append("")
        return out

    def serving_summary(self) -> Optional[dict[str, Any]]:
        """Online-serving accounting, or None when no requests were
        served. The headline is request latency (p50/p99 of
        ``serving.total_ms``) plus the SLO disturbance story: how many
        hot swaps happened, how many nearline per-entity applies landed
        and how fast (``serving.nearline.update_lag_ms`` — the
        event-enqueue -> applied-on-tables window), and how much traffic
        admission control shed."""
        c = self.snapshot.get("counters", {})
        h = self.snapshot.get("histograms", {})
        if not c.get("serving.requests"):
            return None
        total = h.get("serving.total_ms") or {}
        batch = h.get("serving.batch_size") or {}
        lag = h.get("serving.nearline.update_lag_ms") or {}
        out: dict[str, Any] = {
            "requests": int(c.get("serving.requests", 0)),
            "scored_rows": int(c.get("serving.scored_rows", 0)),
            "shed": int(c.get("serving.shed", 0)),
            "p50_ms": total.get("p50"),
            "p99_ms": total.get("p99"),
            "mean_batch_rows": batch.get("mean"),
            "model_swaps": int(c.get("serving.model_swaps", 0)),
            "nearline_applies": int(c.get("serving.nearline.applies", 0)),
            "nearline_applied_rows": int(
                c.get("serving.nearline.applied_rows", 0)
            ),
            "nearline_lag_p99_ms": lag.get("p99"),
            "unseen_entities": int(c.get("serving.unseen_entities", 0)),
        }
        return out

    def _serving_markdown(self) -> list[str]:
        srv = self.serving_summary()
        if srv is None:
            return []
        out = ["## Serving", ""]
        line = f"- {srv['requests']} request(s), {srv['scored_rows']} rows"
        if srv.get("p99_ms") is not None:
            line += (
                f" — p50 {srv['p50_ms']:.1f} ms / p99 {srv['p99_ms']:.1f} ms"
            )
        if srv.get("mean_batch_rows"):
            line += f" ({srv['mean_batch_rows']:.1f} rows/device batch)"
        out.append(line)
        shed = srv.get("shed", 0)
        if shed:
            out.append(
                f"- **{shed} request(s) shed** by admission control "
                "(returned 503 — the queue-depth budget, not failures)"
            )
        swaps = srv.get("model_swaps", 0)
        applies = srv.get("nearline_applies", 0)
        if swaps or applies:
            line = f"- {swaps} registry hot-swap(s)"
            if applies:
                line += (
                    f", {applies} nearline apply(ies) covering "
                    f"{srv['nearline_applied_rows']} entity row(s)"
                )
                if srv.get("nearline_lag_p99_ms") is not None:
                    line += (
                        f" — p99 event->applied "
                        f"{srv['nearline_lag_p99_ms']:.1f} ms"
                    )
            line += (
                " — p99 across each disturbance is the SLO bench's "
                "flatness gate (`serving_slo_p99_swap_ratio`)"
            )
            out.append(line)
        unseen = srv.get("unseen_entities", 0)
        if unseen:
            out.append(
                f"- {unseen} unseen-entity row(s) served fixed-effect-only"
            )
        out.append("")
        return out

    def requests_summary(self) -> Optional[dict[str, Any]]:
        """Request-scoped tracing accounting (the request layer of the
        observability stack), or None when no request records were
        taken: ring volume, tail-sampling persistence, drop-counted
        overflow, and p50/p99 latency DECOMPOSED by phase (batcher
        wait, device dispatch, fan-out, fold, ...)."""
        c = self.snapshot.get("counters", {})
        h = self.snapshot.get("histograms", {})
        if not c.get("request.records"):
            return None
        total = h.get("request.total_ms") or {}
        phases: dict[str, Any] = {}
        prefix = "request.phase."
        for name, summary in sorted(h.items()):
            if name.startswith(prefix) and name.endswith("_ms"):
                phases[name[len(prefix):-3]] = {
                    "count": summary.get("count"),
                    "p50_ms": summary.get("p50"),
                    "p99_ms": summary.get("p99"),
                }
        return {
            "records": int(c.get("request.records", 0)),
            "persisted": int(c.get("request.persisted", 0)),
            "dropped": int(c.get("telemetry.trace_dropped", 0)),
            "p50_ms": total.get("p50"),
            "p99_ms": total.get("p99"),
            "phases": phases,
        }

    def slowest_requests(self, k: int = 10) -> list[dict[str, Any]]:
        """The slowest PERSISTED request traces (``request:*`` root
        spans from tail sampling), slowest first: trace/request ids,
        terminal status, why it was persisted, and its phase
        decomposition."""
        out = []
        for s in self.spans:
            name = s.get("name") or ""
            attrs = s.get("attrs") or {}
            if not name.startswith("request:"):
                continue
            if "request_id" not in attrs:
                continue  # phase child spans ride under their root
            out.append(
                {
                    "name": name[len("request:"):],
                    "trace_id": attrs.get("trace_id"),
                    "request_id": attrs.get("request_id"),
                    "role": attrs.get("role"),
                    "status": attrs.get("status"),
                    "sampled_reason": attrs.get("sampled_reason"),
                    "dur_ms": attrs.get("dur_ms"),
                    "phases": attrs.get("phases") or {},
                    "error": attrs.get("error"),
                }
            )
        out.sort(
            key=lambda r: (
                -(r["dur_ms"] if isinstance(r["dur_ms"], (int, float))
                  else 0.0)
            )
        )
        return out[:k]

    def _requests_markdown(self, k: int = 5) -> list[str]:
        rs = self.requests_summary()
        if rs is None:
            return []
        out = ["## Requests", ""]
        line = f"- {rs['records']} request record(s)"
        if rs.get("p99_ms") is not None:
            line += (
                f" — p50 {rs['p50_ms']:.1f} ms / p99 {rs['p99_ms']:.1f} ms"
            )
        line += (
            f"; {rs['persisted']} persisted by tail sampling"
        )
        if rs.get("dropped"):
            line += f"; **{rs['dropped']} ring overflow drop(s)**"
        out.append(line)
        if rs["phases"]:
            out += [
                "",
                "| phase | count | p50 ms | p99 ms |",
                "|---|---|---|---|",
            ]
            for pname, p in rs["phases"].items():
                out.append(
                    f"| `{pname}` | {p['count']} | "
                    f"{_fmt_or_unknown(p['p50_ms'])} | "
                    f"{_fmt_or_unknown(p['p99_ms'])} |"
                )
        slow = self.slowest_requests(k=k)
        if slow:
            out += [
                "",
                "_Slowest persisted traces (tail sampling: "
                "slow / degraded / errored / sampled):_",
                "",
                "| request | ms | status | why | phases |",
                "|---|---|---|---|---|",
            ]
            for r in slow:
                phases = "; ".join(
                    f"{n} {ms:.1f}"
                    for n, ms in r["phases"].items()
                    if isinstance(ms, (int, float))
                )
                out.append(
                    f"| `{r['name']}` `{r['trace_id']}` | "
                    f"{_fmt_or_unknown(r['dur_ms'])} | {r['status']} | "
                    f"{r['sampled_reason']} | {phases} |"
                )
        out.append("")
        return out

    def freshness_summary(self) -> Optional[dict[str, Any]]:
        """The incremental-retrain accounting, or None when the run was
        not an incremental fit.

        Answers the continuous-freshness questions: what base did this
        model start from (the ``incremental_fit`` span's lineage attrs),
        how much of the entity space did the delta touch, how many RE
        lanes actually re-solved vs kept their converged coefficients
        bit-identical (lane/bucket skip counters — the structural
        speedup evidence), and how long retrain-to-fresh-model took.
        """
        c = self.snapshot.get("counters", {})
        g = self.snapshot.get("gauges", {})
        fit_spans = [
            s for s in self.spans if s.get("name") == "incremental_fit"
        ]
        keys = (
            "incremental.lanes_solved", "incremental.lanes_skipped",
            "incremental.bucket_solves", "incremental.buckets_skipped",
            "incremental.touched_entities", "incremental.warm_restores",
            "incremental.grown_entities",
            "incremental.published_versions", "incremental.fits",
        )
        if not fit_spans and not any(c.get(k) for k in keys):
            return None
        out: dict[str, Any] = {
            k.split(".", 1)[1]: int(c.get(k, 0)) for k in keys if k in c
        }
        frac = g.get("incremental.touched_fraction")
        if frac is not None:
            out["touched_fraction"] = float(frac)
        per_coord = {
            name[len("incremental.touched_fraction."):]: float(v)
            for name, v in g.items()
            if name.startswith("incremental.touched_fraction.")
        }
        if per_coord:
            out["touched_fraction_by_coordinate"] = per_coord
        ttf = g.get("incremental.time_to_fresh_s")
        if ttf is not None:
            out["time_to_fresh_s"] = float(ttf)
        if fit_spans:
            # the newest incremental_fit span carries the lineage attrs
            attrs = fit_spans[-1].get("attrs") or {}
            base = {
                k: v for k, v in attrs.items()
                if k in ("base", "kind", "base_digest", "base_step",
                         "delta_digest", "delta_rows", "touched_fraction")
            }
            if base:
                out["base"] = base
        solved = out.get("lanes_solved", 0)
        skipped = out.get("lanes_skipped", 0)
        if solved or skipped:
            out["lanes_solved_fraction"] = round(
                solved / max(solved + skipped, 1), 6
            )
        return out

    def _freshness_markdown(self) -> list[str]:
        fresh = self.freshness_summary()
        if fresh is None:
            return []
        out = ["## Freshness", ""]
        base = fresh.get("base") or {}
        if base.get("base"):
            line = f"- warm-started from `{base['base']}`"
            if base.get("kind"):
                line += f" ({base['kind']}"
                if base.get("base_step") is not None:
                    line += f", step {base['base_step']}"
                line += ")"
            out.append(line)
            if base.get("base_digest"):
                out.append(f"  - base digest `{base['base_digest'][:16]}…`")
        if base.get("delta_digest"):
            line = f"- delta digest `{base['delta_digest'][:16]}…`"
            if base.get("delta_rows") is not None:
                line += f", {int(base['delta_rows'])} delta row(s)"
            out.append(line)
        touched = fresh.get("touched_entities")
        if touched is not None:
            line = f"- touched entities: {touched}"
            if fresh.get("touched_fraction") is not None:
                line += f" ({_fmt_pct(fresh['touched_fraction'])})"
            out.append(line)
        grown = fresh.get("grown_entities", 0)
        if grown:
            out.append(f"- {grown} new entity row(s) zero-initialized "
                       "(vocabulary growth)")
        solved = fresh.get("lanes_solved", 0)
        skipped = fresh.get("lanes_skipped", 0)
        if solved or skipped:
            out.append(
                f"- RE lanes re-solved: **{solved}**; kept bit-identical: "
                f"**{skipped}** "
                f"({_fmt_pct(fresh.get('lanes_solved_fraction'))} of lanes "
                "solved)"
            )
        bs = fresh.get("bucket_solves", 0)
        bsk = fresh.get("buckets_skipped", 0)
        if bs or bsk:
            out.append(
                f"- bucket solves dispatched: {bs}; skipped entirely "
                f"(zero touched entities): {bsk}"
            )
        ttf = fresh.get("time_to_fresh_s")
        if ttf is not None:
            out.append(f"- time-to-fresh-model: {ttf:.2f} s")
        published = fresh.get("published_versions", 0)
        if published:
            out.append(
                f"- {published} version(s) published with lineage metadata"
            )
        out.append("")
        return out

    def pipeline_summary(self) -> Optional[dict[str, Any]]:
        """The freshness conductor's accounting, or None when no
        ``cli pipeline`` daemon ran.

        Answers the freshness-tier questions: how many cycles ran (and
        how many were idle — unchanged delta digest), how many versions
        published vs escalated to full retrains, how many cycles had a
        nearline version to reconcile against, and the headline SLO —
        event→served staleness p99 across every delta shard served.
        """
        c = self.snapshot.get("counters", {})
        g = self.snapshot.get("gauges", {})
        cycle_spans = [
            s for s in self.spans if s.get("name") == "pipeline.cycle"
        ]
        keys = (
            "pipeline.cycles", "pipeline.idle_cycles",
            "pipeline.publishes", "pipeline.escalations",
            "pipeline.reconciliations",
        )
        if not cycle_spans and not any(c.get(k) for k in keys):
            return None
        out: dict[str, Any] = {
            k.split(".", 1)[1]: int(c.get(k, 0)) for k in keys if k in c
        }
        p99 = g.get("pipeline.event_to_served_staleness_p99_s")
        if p99 is not None:
            out["event_to_served_staleness_p99_s"] = float(p99)
        if cycle_spans:
            out["cycle_time_s"] = {
                "count": len(cycle_spans),
                "total": round(
                    sum(float(s.get("dur") or 0.0) for s in cycle_spans), 3
                ),
                "max": round(
                    max(float(s.get("dur") or 0.0) for s in cycle_spans), 3
                ),
            }
        return out

    def _pipeline_markdown(self) -> list[str]:
        pipe = self.pipeline_summary()
        if pipe is None:
            return []
        out = ["## Pipeline", ""]
        cycles = pipe.get("cycles", 0)
        idle = pipe.get("idle_cycles", 0)
        if cycles:
            out.append(
                f"- {cycles} conductor cycle(s), {idle} idle "
                "(unchanged delta digest)"
            )
        publishes = pipe.get("publishes", 0)
        escalations = pipe.get("escalations", 0)
        if publishes:
            line = f"- {publishes} version(s) published with lineage"
            if escalations:
                line += (
                    f", {escalations} via full-retrain escalation"
                )
            out.append(line)
        rec = pipe.get("reconciliations", 0)
        if rec:
            out.append(
                f"- {rec} cycle(s) reconciled a nearline-published "
                "version (retrain-wins-touched; superseded version named "
                "in lineage)"
            )
        p99 = pipe.get("event_to_served_staleness_p99_s")
        if p99 is not None:
            out.append(
                f"- **event→served staleness p99: {p99:.3f} s** (delta "
                "shard mtime → registry hot-swap confirmed)"
            )
        ct = pipe.get("cycle_time_s")
        if ct:
            out.append(
                f"- non-idle cycle time: {ct['total']:.3f} s total over "
                f"{ct['count']} cycle(s), max {ct['max']:.3f} s"
            )
        out.append("")
        return out

    def quality_summary(self) -> Optional[dict[str, Any]]:
        """Quality-observability accounting, or None when the run never
        touched the quality layer (no gated publish, no bootstrap, no
        drift sketches).

        Answers the ISSUE-20 questions in one place: how many candidate
        versions had quality stats computed (weighted AUC + bootstrap
        CI), what the champion/challenger gate decided (published /
        quarantined / bypassed / no-champion), how many masked-lane
        bootstrap fits attached coefficient CIs, and the online drift
        rows (per-version score sketches + calibration bins + PSI) the
        serving fleet accumulated — lifted verbatim from the ``quality``
        snapshot section the drift monitor publishes.
        """
        c = self.snapshot.get("counters", {})
        drift = self.snapshot.get("quality") or {}
        keys = (
            "quality.stats_computed", "quality.bootstrap_fits",
            "quality.gate_published", "quality.gate_quarantined",
            "quality.gate_bypassed", "quality.gate_no_champion",
            "quality.scores_observed", "quality.labeled_observed",
            "quality.versions_evicted", "pipeline.quarantines",
        )
        if not drift.get("versions") and not any(c.get(k) for k in keys):
            return None
        out: dict[str, Any] = {
            k.replace("quality.", "").replace(".", "_"): int(c.get(k, 0))
            for k in keys
            if k in c
        }
        if drift.get("versions"):
            out["drift"] = drift
        return out

    def _quality_markdown(self) -> list[str]:
        q = self.quality_summary()
        if q is None:
            return []
        out = ["## Quality", ""]
        stats = q.get("stats_computed", 0)
        if stats:
            out.append(
                f"- candidate quality stats computed: {stats} "
                "(weighted validation AUC + bootstrap CI"
                " + Hosmer–Lemeshow where logistic)"
            )
        fits = q.get("bootstrap_fits", 0)
        if fits:
            out.append(
                f"- {fits} masked-lane bootstrap fit(s) attached "
                "per-entity coefficient CIs to published metadata"
            )
        gate_bits = []
        for key, label in (
            ("gate_published", "published"),
            ("gate_quarantined", "**quarantined**"),
            ("gate_bypassed", "gate-bypassed"),
            ("gate_no_champion", "published without a champion"),
        ):
            v = q.get(key, 0)
            if v:
                gate_bits.append(f"{v} {label}")
        if gate_bits:
            out.append(
                "- champion/challenger gate decisions: "
                + ", ".join(gate_bits)
            )
        quarantines = q.get("pipeline_quarantines", 0)
        if quarantines:
            out.append(
                f"- **{quarantines} regressed challenger(s) quarantined "
                "by the conductor** (digest advanced; no retry loop)"
            )
        drift = q.get("drift") or {}
        versions = drift.get("versions") or {}
        if versions:
            base = drift.get("baseline_version")
            line = f"- online drift sketches for {len(versions)} version(s)"
            if base:
                line += f" (PSI baseline `{base}`)"
            out.append(line)
            out.append("")
            out.append(
                "| version | scores | mean | std | PSI vs baseline "
                "| labeled | max calib gap |"
            )
            out.append("|---|---|---|---|---|---|---|")
            for v, row in versions.items():
                s = row.get("scores") or {}
                cal = row.get("calibration") or {}
                out.append(
                    "| `{}` | {} | {} | {} | {} | {} | {} |".format(
                        v,
                        s.get("count", 0),
                        _fmt(s.get("mean")),
                        _fmt(s.get("std")),
                        _fmt(row.get("psi_vs_baseline")),
                        cal.get("count", 0),
                        _fmt(cal.get("max_gap")),
                    )
                )
        out.append("")
        return out

    def recovery_summary(self) -> Optional[dict[str, Any]]:
        """Fault-tolerance accounting, or None when the run exercised no
        recovery machinery at all (no checkpoints, no retries, no
        injections — the common healthy case).

        The section exists so "the run recovered" is an auditable
        statement: how many checkpoints were written (and with how many
        per-shard saves — ``max_shard_fetch_bytes`` proves a sharded save
        never assembled the table on the host), whether restore fell back
        past corrupt directories, whether a resume was ELASTIC (restored
        onto a different device topology than the one that saved), and
        how many transient-IO retries the ingest/serving paths absorbed.
        ``faults.injected`` is nonzero only under deliberate fault
        injection (tools/chaos.py or an armed ``PHOTON_FAULT_PLAN``) —
        loud in a report because an armed production run is an incident.
        """
        c = self.snapshot.get("counters", {})
        g = self.snapshot.get("gauges", {})
        keys = (
            "checkpoint.saves", "checkpoint.restores", "checkpoint.corrupt",
            "checkpoint.shard_saves", "recovery.elastic_resumes",
            "faults.injected", "serving.version_retries",
            "ingest.read_retries", "streaming.feed_retries",
            "solves.rolled_back", "solves.frozen",
            # fleet recovery (multi-process fits under tools/fleet.py)
            "recovery.fleet_member_deaths", "recovery.fleet_relaunches",
            "checkpoint.quorum_timeouts", "checkpoint.peer_manifests",
            "checkpoint.quorum_cover_violations",
            "multihost.init_retries",
        )
        if not any(c.get(k) for k in keys):
            return None
        out: dict[str, Any] = {k.replace(".", "_"): int(c.get(k, 0))
                               for k in keys}
        max_fetch = g.get("checkpoint.max_shard_fetch_bytes")
        if max_fetch is not None:
            out["max_shard_fetch_bytes"] = int(max_fetch)
        injected_by_point = {
            name[len("faults.injected."):]: int(value)
            for name, value in c.items()
            if name.startswith("faults.injected.")
        }
        if injected_by_point:
            out["faults_injected_by_point"] = injected_by_point
        return out

    def _recovery_markdown(self) -> list[str]:
        rec = self.recovery_summary()
        if rec is None:
            return []
        out = ["## Recovery", ""]
        saves = rec.get("checkpoint_saves", 0)
        if saves:
            line = f"- {saves} checkpoint save(s)"
            shard_saves = rec.get("checkpoint_shard_saves", 0)
            if shard_saves:
                line += f", {shard_saves} per-shard payload write(s)"
                max_fetch = rec.get("max_shard_fetch_bytes")
                if max_fetch is not None:
                    line += (
                        f" (largest single host fetch "
                        f"{_fmt_bytes(max_fetch)} — never the full table)"
                    )
            out.append(line)
        restores = rec.get("checkpoint_restores", 0)
        if restores:
            elastic = rec.get("recovery_elastic_resumes", 0)
            out.append(
                f"- {restores} restore(s)"
                + (
                    f", **{elastic} elastic** (resumed onto a different "
                    "device topology than the one that saved)"
                    if elastic else ""
                )
            )
        corrupt = rec.get("checkpoint_corrupt", 0)
        if corrupt:
            out.append(
                f"- **{corrupt} corrupt/partial checkpoint(s) skipped** "
                "during restore (newest-valid fallback)"
            )
        retries = [
            ("serving_version_retries", "serving model-version loads"),
            ("ingest_read_retries", "ingest chunk reads"),
            ("streaming_feed_retries", "streaming host→device feeds"),
        ]
        for key, what in retries:
            n = rec.get(key, 0)
            if n:
                out.append(
                    f"- {n} transient-IO retry(ies) absorbed on {what}"
                )
        deaths = rec.get("recovery_fleet_member_deaths", 0)
        relaunches = rec.get("recovery_fleet_relaunches", 0)
        if deaths or relaunches:
            out.append(
                f"- **fleet: {deaths} member death(s), {relaunches} "
                "survivor relaunch(es)** (supervised multi-process fit — "
                "the fit continued on the surviving host set)"
            )
        quorum_timeouts = rec.get("checkpoint_quorum_timeouts", 0)
        peer_manifests = rec.get("checkpoint_peer_manifests", 0)
        if quorum_timeouts or peer_manifests:
            out.append(
                f"- coordinated checkpoints: {peer_manifests} per-process "
                f"manifest(s) written, {quorum_timeouts} quorum "
                "timeout(s) (saves abandoned uncertified — a dead peer "
                "never hangs the fleet or certifies a partial checkpoint)"
            )
        cover = rec.get("checkpoint_quorum_cover_violations", 0)
        if cover:
            out.append(
                f"- **{cover} coordinated save(s) abandoned on a "
                "shard-cover violation** (merged peer shards had a "
                "gap/overlap or a missing payload file — never certified)"
            )
        init_retries = rec.get("multihost_init_retries", 0)
        if init_retries:
            out.append(
                f"- {init_retries} distributed-init retry(ies) absorbed "
                "(flaky rendezvous, exponential backoff)"
            )
        rolled = rec.get("solves_rolled_back", 0)
        frozen = rec.get("solves_frozen", 0)
        if rolled or frozen:
            out.append(
                f"- guard: {rolled} solve rollback(s), {frozen} "
                "coordinate freeze(s)"
            )
        injected = rec.get("faults_injected", 0)
        if injected:
            by_point = rec.get("faults_injected_by_point") or {}
            detail = ", ".join(
                f"`{p}`×{n}" for p, n in sorted(by_point.items())
            )
            out.append(
                f"- **{injected} fault(s) deliberately injected** "
                f"({detail}) — this run had an armed fault plan"
            )
        out.append("")
        return out

    def _memory_markdown(self) -> list[str]:
        g = self.snapshot.get("gauges", {})
        phase_peaks = {
            name[len("memory.phase."):-len(".peak_bytes")]: value
            for name, value in g.items()
            if name.startswith("memory.phase.")
            and name.endswith(".peak_bytes")
            # memory.phase.<phase>.device.<id>.peak_bytes rows are the
            # per-device watermarks, rendered separately below
            and ".device." not in name[len("memory.phase."):]
            and value is not None
        }
        headroom = self.snapshot.get("counters", {}).get(
            "memory.headroom_warnings"
        )
        has_device_gauges = any(
            name.startswith("memory.device.") and name.endswith(".bytes_in_use")
            for name in g
        )
        if (
            not phase_peaks
            and not headroom
            and not has_device_gauges
            and "memory.bytes_in_use" not in g
        ):
            return []
        out = ["## HBM / memory", ""]
        if "memory.bytes_in_use" in g:
            out.append(
                f"- in use: {_fmt_bytes(g['memory.bytes_in_use'])}"
                + (
                    f" of {_fmt_bytes(g['memory.bytes_limit'])}"
                    if g.get("memory.bytes_limit") is not None
                    else ""
                )
            )
        per_device = {
            name[len("memory.device."):-len(".bytes_in_use")]: value
            for name, value in g.items()
            if name.startswith("memory.device.")
            and name.endswith(".bytes_in_use")
            and value is not None
        }
        if len(per_device) >= 2:
            # shard-imbalance signal: a balanced entity sharding keeps the
            # per-device spread near zero; a lopsided one concentrates
            # table bytes on few devices (heartbeats carry the same number
            # live as hbm_device_spread_bytes)
            lo, hi = min(per_device.values()), max(per_device.values())
            out.append(
                f"- per-device in use across {len(per_device)} devices: "
                f"min {_fmt_bytes(lo)}, max {_fmt_bytes(hi)}, spread "
                f"{_fmt_bytes(hi - lo)}"
            )
        elif g.get("memory.device_spread_bytes") is not None:
            out.append(
                "- per-device in-use spread (max-min): "
                f"{_fmt_bytes(g['memory.device_spread_bytes'])}"
            )
        watermarks = {
            name[len("memory.device."):-len(".peak_bytes")]: value
            for name, value in g.items()
            if name.startswith("memory.device.")
            and name.endswith(".peak_bytes")
            and value is not None
        }
        if watermarks:
            # live high-watermarks from the profiler's sampling cadence:
            # they catch the transient mid-solve spike the end-of-phase
            # probes sleep through
            lo, hi = min(watermarks.values()), max(watermarks.values())
            line = (
                f"- HBM high-watermark across {len(watermarks)} "
                f"device(s): peak {_fmt_bytes(hi)}"
            )
            if len(watermarks) >= 2:
                line += (
                    f" (min {_fmt_bytes(lo)}, watermark spread "
                    f"{_fmt_bytes(hi - lo)})"
                )
            out.append(line)
        if headroom:
            out.append(
                f"- **{int(headroom)} headroom warning(s)** — predicted "
                "allocations exceeded free HBM (`memory.headroom_warnings`)"
            )
        if phase_peaks:
            out += ["", "| phase | peak bytes |", "|---|---|"]
            for phase, value in sorted(
                phase_peaks.items(), key=lambda kv: -(kv[1] or 0)
            ):
                out.append(f"| `{phase}` | {_fmt_bytes(value)} |")
        out.append("")
        return out

    def _coordinates_markdown(self) -> list[str]:
        coords = self.coordinate_summary()
        if not coords:
            return []
        out = [
            "## Coordinates (from newest checkpoint)",
            "",
            "| coordinate | steps | seconds | retries | rollbacks "
            "| frozen | last metrics |",
            "|---|---|---|---|---|---|---|",
        ]
        for c in coords:
            metrics_str = (
                json.dumps(c["last_metrics"], default=str)
                if c["last_metrics"]
                else ""
            )
            out.append(
                f"| `{c['coordinate']}` | {c['steps']} | "
                f"{c['seconds']:.3f} | {c['solve_retries']} | "
                f"{c['rollbacks']} | {'yes' if c['frozen'] else ''} | "
                f"{metrics_str} |"
            )
        out.append("")
        return out

    def _heartbeat_markdown(self) -> list[str]:
        if not self.heartbeats:
            return []
        last = self.heartbeats[-1]
        line = (
            f"- {len(self.heartbeats)} beat(s); last at uptime "
            f"{last.get('uptime_s', '?')}s in span "
            f"`{last.get('span') or '(idle)'}` — "
            f"{_fmt(last.get('rows_per_s'))} rows/s, "
            f"{_fmt(last.get('coeffs_per_s'))} coeffs/s"
        )
        if last.get("hot_exec"):
            line += f"; hot executable `{last['hot_exec']}`"
        return ["## Heartbeats", "", line, ""]


def _render_tree(
    node: PhaseNode, depth: int, run_total: float, lines: list[str]
) -> None:
    for child in sorted(node.children.values(), key=lambda c: -c.total_s):
        pct = 100.0 * child.total_s / run_total if run_total else 0.0
        lines.append(
            f"{'  ' * depth}- `{child.name}` — n={child.count}, "
            f"total {child.total_s:.3f}s, self {child.self_s:.3f}s "
            f"({pct:.1f}%)"
        )
        _render_tree(child, depth + 1, run_total, lines)


def _compare_markdown(deltas: Sequence[MetricDelta]) -> list[str]:
    out = [
        "## Comparison vs baseline",
        "",
        "| metric | current | baseline | change | status |",
        "|---|---|---|---|---|",
    ]
    for d in deltas:
        status = "**REGRESSED**" if d.regressed else "ok"
        out.append(
            f"| `{d.metric}` | {_fmt(d.current)} | {_fmt(d.baseline)} | "
            f"{d.change:+.1%} | {status} |"
        )
    regressed = [d.metric for d in deltas if d.regressed]
    out.append("")
    if regressed:
        out.append(
            f"**{len(regressed)} regression(s)**: "
            + ", ".join(f"`{m}`" for m in regressed)
        )
    else:
        out.append("No regressions beyond threshold.")
    out.append("")
    return out


def _fmt(value: Any) -> str:
    if value is None:
        return ""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return str(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return f"{f:.4g}"


def _fmt_pct(value: Any) -> str:
    """Percentage or the explicit string "unknown" (backends without cost
    analysis / unknown device peaks must say so, never show 0)."""
    if value is None:
        return "unknown"
    try:
        return f"{float(value):.1%}"
    except (TypeError, ValueError):
        return "unknown"


def _fmt_or_unknown(value: Any) -> str:
    return "unknown" if value is None else _fmt(value)


def _fmt_bytes(value: Any) -> str:
    try:
        b = float(value)
    except (TypeError, ValueError):
        return str(value)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(b) < 1024 or unit == "TiB":
            return f"{b:.1f} {unit}" if unit != "B" else f"{int(b)} B"
        b /= 1024
    return f"{b:.1f} TiB"
