"""Device/transfer accounting: the sanctioned device->host fetch point and
jit compile counters.

PERF_NOTES.md's two invisible costs become metrics here:

- Every host-visible fetch through the tunnel costs ~100 ms of fixed
  latency, and ``block_until_ready()`` is a NO-OP there — a device->host
  fetch is the only true sync. :func:`sync_fetch` is the one place the
  library crosses that boundary: it counts fetches, bytes, and blocking
  seconds, and stamps a ``device_fetch`` event on the open span
  (``tools/check.py`` L007 points bare ``block_until_ready()`` calls here).
- Silent recompiles dominated the 20M north-star run (FE 1501 s
  "upload+compile dominated"). :func:`install_compile_hooks` subscribes to
  ``jax.monitoring``'s backend-compile duration events, so every compile
  increments ``jit_compiles``, feeds the ``jit_compile_seconds`` histogram,
  and shows up as a named ``compile`` event on whatever span was open.

Metric names emitted:

- ``device_fetches`` / ``device_fetch_bytes`` / ``device_fetch_seconds``
  (counters) and ``device_fetch_seconds`` (histogram)
- ``jit_compiles`` / ``jit_compile_seconds`` (counter) and
  ``jit_compile_seconds`` (histogram)
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np

from photon_ml_tpu.telemetry import metrics, trace

__all__ = ["sync_fetch", "install_compile_hooks"]

# jax.monitoring duration events counted as compiles: the backend (XLA)
# compile is the expensive one; trace/lowering durations are recorded
# under their own short names for completeness.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_hooks_lock = threading.Lock()
_hooks_installed = False


def sync_fetch(x: Any, label: Optional[str] = None) -> np.ndarray:
    """Fetch a device array to the host — the ONE sanctioned sync point.

    Returns ``np.asarray(x)`` (a true device->host copy, which really
    synchronizes even through the tunnel, unlike ``block_until_ready``)
    while accounting for the crossing: counters ``device_fetches``,
    ``device_fetch_bytes``, ``device_fetch_seconds``, a blocking-time
    histogram, and a ``device_fetch`` event on the current span.

    Use it for every result the host must observe (convergence scalars,
    tracker vectors, timing syncs); batch values into one array first —
    each call pays the full tunnel round trip.
    """
    t0 = time.monotonic()
    out = np.asarray(x)
    dt = time.monotonic() - t0
    metrics.counter("device_fetches").inc()
    metrics.counter("device_fetch_bytes").inc(out.nbytes)
    metrics.counter("device_fetch_seconds").inc(dt)
    metrics.histogram("device_fetch_seconds").observe(dt)
    trace.add_event(
        "device_fetch",
        label=label,
        bytes=out.nbytes,
        seconds=round(dt, 6),
    )
    return out


def install_compile_hooks() -> bool:
    """Subscribe compile counters to ``jax.monitoring`` (idempotent).

    Returns True when the hook is (already) installed, False when the
    running jax has no monitoring API. Registered once per process; jax
    offers no unregister, so the listener guards itself against a reset
    registry and never raises into the compiler.
    """
    global _hooks_installed
    with _hooks_lock:
        if _hooks_installed:
            return True
        try:
            from jax import monitoring
        except ImportError:
            return False
        if not hasattr(monitoring, "register_event_duration_secs_listener"):
            return False

        def _on_duration(event: str, duration: float, **_kw: Any) -> None:
            try:
                if event != _COMPILE_EVENT:
                    return
                metrics.counter("jit_compiles").inc()
                metrics.counter("jit_compile_seconds").inc(duration)
                metrics.histogram("jit_compile_seconds").observe(duration)
                trace.add_event("compile", seconds=round(duration, 6))
            except Exception:  # noqa: BLE001 — never fail a compile
                pass

        monitoring.register_event_duration_secs_listener(_on_duration)
        _hooks_installed = True
        return True
