"""Fleet reports: merge per-member telemetry into one answer.

A multi-process fit writes ONE artifact stream per member
(``trace.proc-0.jsonl``, ``telemetry.proc-1.jsonl``, … — the
``telemetry.identity`` suffixing contract), and nothing used to merge
them: reading a 2-process run meant two disjoint RunReports and no way to
say who stalled whom. :class:`FleetReport` is the aggregation layer — the
TPU-fleet analog of the Spark UI's per-executor task timelines:

- **discovery**: glob a fleet directory for ``*.proc-<i>.jsonl`` streams,
  classify each by its first record (``trace_header``/``span`` vs
  ``metrics``/``heartbeat``), and build one :class:`RunReport` per member
  — every derived view (MFU, comms fraction, phase trees) is reused, not
  reimplemented;
- **alignment**: each trace header records a monotonic<->epoch anchor
  pair (``anchor_unix_s``/``monotonic_anchor``), so member-local span
  times map onto one absolute timeline; residual clock skew is estimated
  from the coordinated-checkpoint rendezvous (the ``checkpoint:save``
  spans with ``coordinated=True`` end at the same barrier on every
  member, so per-member deltas of those endpoints ARE the skew);
- **attribution**: per-member rows (rows/s, MFU, comms fraction,
  collective wait share, chunk progress, heartbeat gaps) plus the
  straggler callout — at a barrier the member who arrives LAST waits
  ~zero while everyone else's wait clock runs, so the member with the
  minimum total ``comms.wait_seconds_total`` is the one the fleet stood
  around for;
- **degradation**: a member whose artifacts are missing or truncated
  mid-line (the hard-killed-member case the distributed crash matrix
  produces) renders as a partial row marked ``lost`` — never a crash,
  never silently complete.

Surfaced as ``python -m photon_ml_tpu.cli report --fleet <dir>``;
``compare``/``--fail-on-regress`` gate the aggregated
:meth:`FleetReport.key_metrics` through the same ``compare_metrics``
machinery single-run reports use. Like RunReport, this module only READS
artifacts — it never touches a device.
"""

from __future__ import annotations

import dataclasses
import datetime
import glob as _glob
import json
import os
import re
import statistics
from typing import Any, Mapping, Optional, Sequence

from photon_ml_tpu.telemetry.report import (
    KEY_METRIC_DIRECTIONS,
    MetricDelta,
    RunReport,
    _compare_markdown,
    _fmt,
    _fmt_or_unknown,
    _fmt_pct,
    compare_metrics,
)

__all__ = [
    "FleetMember",
    "FleetReport",
    "FLEET_KEY_METRIC_DIRECTIONS",
    "FLEET_REPORT_FORMAT_VERSION",
    "discover_member_streams",
    "discover_flight_records",
    "discover_router_trace",
]

FLEET_REPORT_FORMAT_VERSION = 1

_PROC_RE = re.compile(r"\.proc-(\d+)\.jsonl$")
_GEN_RE = re.compile(r"^gen(\d+)$")
#: anchored at the exact ``.json`` suffix, so the ``.tmp`` shadow a kill
#: mid-dump leaves behind is never adopted as a flight record
_FLIGHT_RE = re.compile(r"^flight-proc-(\d+)\.json$")

#: Aggregated fleet metrics and their goodness direction (the
#: ``cli report --fleet --compare`` gate set). Single-run directions are
#: inherited so a fleet baseline may also carry plain key metrics.
FLEET_KEY_METRIC_DIRECTIONS: dict[str, int] = {
    **KEY_METRIC_DIRECTIONS,
    "fleet_rows_per_sec": +1,
    "fleet_coeffs_per_sec": +1,
    "fleet_collective_wait_fraction": -1,
    "fleet_collective_wait_s": -1,
    "fleet_mfu_spread": -1,
    "fleet_lost_members": -1,
    "fleet_heartbeat_gap_max_s": -1,
    "fleet_clock_skew_max_s": -1,
}

#: Below this many seconds of fleet-wide wait spread the straggler callout
#: stays silent — naming a "straggler" over scheduler jitter is noise.
_STRAGGLER_MIN_SPREAD_S = 0.005


def discover_member_streams(fleet_dir: str) -> dict[int, dict]:
    """Map ``process_index -> {"trace": path, "telemetry": path,
    "header": dict}`` for the per-member artifact streams under
    ``fleet_dir`` (``header`` is the trace's leading ``trace_header``
    record, captured during classification; absent on headerless
    streams).

    The naming contract is the ``identity.member_artifact_path`` suffix:
    any ``*.proc-<i>.jsonl`` file belongs to member ``i``. Classification
    reads the file's FIRST parseable record — ``trace_header``/``span``
    means a trace stream, ``metrics``/``heartbeat`` a telemetry stream —
    so renamed prefixes still sort correctly. When the directory itself
    holds no member streams, the tools/fleet.py workdir layout is tried:
    a ``telemetry/`` subdirectory, then the NEWEST ``gen<g>`` generation
    directory under either (one directory = one generation's fleet;
    relaunch generations renumber members) — so ``--fleet <workdir>``
    works on a supervisor directory directly and reads the final
    generation's run.
    """
    out: dict[int, dict] = {}
    for directory in _candidate_dirs(fleet_dir):
        for path in sorted(_glob.glob(os.path.join(directory, "*.jsonl"))):
            m = _PROC_RE.search(os.path.basename(path))
            if not m:
                continue
            proc = int(m.group(1))
            kind, first = _classify_stream(path)
            if kind is None:
                continue
            entry = out.setdefault(proc, {})
            entry.setdefault(kind, path)
            if (
                kind == "trace"
                and entry["trace"] == path
                and first.get("type") == "trace_header"
            ):
                # the header was just parsed for classification — carry
                # it so load() need not re-open the file for it
                entry["header"] = first
        if out:
            break
    return out


def _candidate_dirs(fleet_dir: str) -> list[str]:
    """The directories one fleet run's artifacts may live in: the dir
    itself, a ``telemetry/`` subdirectory (the tools/fleet.py workdir
    layout), and the NEWEST ``gen<g>`` generation under either."""
    candidates = [fleet_dir, os.path.join(fleet_dir, "telemetry")]
    for base in list(candidates):
        gens = sorted(
            (
                d
                for d in _glob.glob(os.path.join(base, "gen*"))
                if os.path.isdir(d) and _GEN_RE.match(os.path.basename(d))
            ),
            key=lambda d: int(os.path.basename(d)[3:]),
        )
        if gens:
            candidates.append(gens[-1])
    return candidates


def discover_flight_records(fleet_dir: str) -> dict[int, str]:
    """``process_index -> flight-proc-<i>.json`` under the first
    candidate directory holding any. Only the exact ``.json`` name
    matches — a process killed mid-dump leaves a ``.tmp`` that is
    invisible here (the crash-safety contract of the flight recorder)."""
    for directory in _candidate_dirs(fleet_dir):
        out: dict[int, str] = {}
        for path in sorted(
            _glob.glob(os.path.join(directory, "flight-proc-*.json"))
        ):
            m = _FLIGHT_RE.match(os.path.basename(path))
            if m:
                out[int(m.group(1))] = path
        if out:
            return out
    return {}


def discover_router_trace(fleet_dir: str) -> Optional[str]:
    """The serving ROUTER's own span stream (``trace.router.jsonl``):
    the supervisor process carries no member suffix, but its
    ``request:route`` spans are one half of every fan-out trace."""
    for directory in _candidate_dirs(fleet_dir):
        for path in sorted(
            _glob.glob(os.path.join(directory, "*.router.jsonl"))
        ):
            kind, _first = _classify_stream(path)
            if kind == "trace":
                return path
    return None


def _classify_stream(path: str) -> tuple[Optional[str], dict]:
    """``("trace"|"telemetry"|None, first_record)`` from the first
    parseable record (the record doubles as the trace header when it is
    one — a truncated or headerless stream classifies by whatever leads
    it)."""
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict):
                    continue
                kind = rec.get("type")
                if kind in ("trace_header", "span"):
                    return "trace", rec
                if kind in ("metrics", "heartbeat"):
                    return "telemetry", rec
    except OSError:
        return None, {}
    return None, {}


@dataclasses.dataclass
class FleetMember:
    """One fleet member's artifacts + the per-member derived row."""

    process_index: int
    trace_path: Optional[str] = None
    telemetry_path: Optional[str] = None
    report: RunReport = dataclasses.field(default_factory=RunReport)
    header: dict = dataclasses.field(default_factory=dict)
    lost: bool = False
    #: estimated clock skew vs the reference member (seconds; 0 for the
    #: reference itself or when no shared rendezvous exists)
    clock_skew_s: float = 0.0
    #: adopted flight record (drain-path dump or supervisor harvest);
    #: None when absent or torn
    flight: Optional[dict] = None
    flight_path: Optional[str] = None
    # derived-view memos: RunReport.key_metrics()/phase_tree() walk every
    # span, and a fleet report consumes them from rows(), key_metrics(),
    # markdown AND to_json — compute once per member (the underlying
    # report never changes after load)
    _km: Optional[dict] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    _run_s: Optional[float] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    @property
    def hostname(self) -> Optional[str]:
        return self.header.get("hostname")

    def key_metrics(self) -> dict[str, float]:
        if self._km is None:
            self._km = self.report.key_metrics()
        return self._km

    def _abs_time(self, ts: float) -> Optional[float]:
        """Member-local tracer seconds -> absolute epoch seconds (skew-
        corrected), or None without an anchor pair in the header."""
        anchor_unix = self.header.get("anchor_unix_s")
        anchor_mono = self.header.get("monotonic_anchor")
        if anchor_unix is None or anchor_mono is None:
            return None
        return anchor_unix + (ts - anchor_mono) - self.clock_skew_s

    def run_seconds(self) -> float:
        """This member's total traced wall time (top-level phase sum)."""
        if self._run_s is None:
            tree = self.report.phase_tree()
            self._run_s = sum(c.total_s for c in tree.children.values())
        return self._run_s

    def collective_wait_seconds(self) -> Optional[float]:
        c = self.report.snapshot.get("counters", {})
        value = c.get("comms.wait_seconds_total")
        return None if value is None else float(value)

    def heartbeat_gap_max_s(self) -> Optional[float]:
        """Largest gap between consecutive heartbeat lines (uptime
        deltas) — a long gap means the member went quiet mid-run."""
        ups = [
            hb.get("uptime_s")
            for hb in self.report.heartbeats
            if isinstance(hb.get("uptime_s"), (int, float))
        ]
        if len(ups) < 2:
            return None
        return max(b - a for a, b in zip(ups, ups[1:]))

    def row(self) -> dict[str, Any]:
        """The per-member report row (JSON-safe)."""
        km = self.key_metrics()
        counters = self.report.snapshot.get("counters", {})
        du = self.report.device_utilization()
        wait = self.collective_wait_seconds()
        run_s = self.run_seconds()
        last_hb = (
            self.report.heartbeats[-1] if self.report.heartbeats else None
        )
        chunks = counters.get("streaming_chunks")
        hot = self.report.hot_executables(k=1)
        return {
            "process_index": self.process_index,
            "hostname": self.hostname,
            "status": "lost" if self.lost else "ok",
            "rows_per_sec": km.get("rows_per_sec"),
            "coeffs_per_sec": km.get("coeffs_per_sec"),
            "mfu": km.get("mfu"),
            "comms_fraction": (
                du.get("comms_fraction") if du is not None else None
            ),
            "collective_wait_s": wait,
            "collective_wait_calls": counters.get("comms.wait_calls"),
            "collective_wait_share": (
                wait / run_s if wait is not None and run_s else None
            ),
            "chunks_done": None if chunks is None else int(chunks),
            "hot_exec": hot[0]["name"] if hot else None,
            "run_seconds": round(run_s, 6) if run_s else None,
            "heartbeats": len(self.report.heartbeats),
            "heartbeat_gap_max_s": self.heartbeat_gap_max_s(),
            "last_heartbeat": last_hb,
            "clock_skew_s": round(self.clock_skew_s, 6),
            "flight_records": (
                len(self.flight.get("records") or [])
                if self.flight is not None
                else None
            ),
            "artifacts": {
                "trace": self.trace_path,
                "telemetry": self.telemetry_path,
                "flight": self.flight_path,
            },
        }


def _rendezvous_endpoints(member: FleetMember) -> dict[int, float]:
    """``next_chunk -> absolute end time`` of this member's COORDINATED
    checkpoint-save spans — the shared barrier events skew is estimated
    from (every member leaves ``_save_coordinated`` within one quorum
    poll of the rename landing)."""
    out: dict[int, float] = {}
    for s in member.report.spans:
        if s.get("name") != "checkpoint:save":
            continue
        attrs = s.get("attrs") or {}
        if not attrs.get("coordinated"):
            continue
        chunk = attrs.get("next_chunk")
        if not isinstance(chunk, int):
            continue
        ts = s.get("ts")
        dur = s.get("dur")
        if not isinstance(ts, (int, float)) or not isinstance(
            dur, (int, float)
        ):
            continue
        end = member._abs_time(ts + dur)
        if end is not None:
            out[chunk] = end
    return out


@dataclasses.dataclass
class FleetReport:
    """Merged per-member telemetry for one fleet run."""

    fleet_dir: str
    members: list[FleetMember] = dataclasses.field(default_factory=list)
    num_processes: int = 0
    #: the router's own span stream + pseudo-member (process_index -1),
    #: joined into request traces but excluded from member accounting
    router_trace_path: Optional[str] = None
    router: Optional[FleetMember] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    # -- construction --------------------------------------------------------

    @classmethod
    def load(cls, fleet_dir: str) -> "FleetReport":
        """Build from a directory of per-member artifact streams.

        Degradation contract: missing/truncated/half-written artifacts
        (the killed-member case) never raise — the member renders with
        whatever survived, marked ``lost`` when its final metrics
        snapshot is absent. An expected member with NO artifacts at all
        (fleet size known from a peer's header) gets a synthesized
        ``lost`` row."""
        streams = discover_member_streams(fleet_dir)
        members: list[FleetMember] = []
        for proc in sorted(streams):
            paths = streams[proc]
            trace_path = paths.get("trace")
            telemetry_path = paths.get("telemetry")
            report = RunReport.load(
                trace=trace_path, telemetry=telemetry_path
            )
            header = paths.get("header") or {}
            member = FleetMember(
                process_index=proc,
                trace_path=trace_path,
                telemetry_path=telemetry_path,
                report=report,
                header=header,
            )
            # a member that never flushed its final metrics snapshot died
            # before atexit ran (os._exit / SIGKILL — the chaos shape):
            # its spans/heartbeats are real but the run is incomplete
            member.lost = not report.snapshot
            members.append(member)
        flights = discover_flight_records(fleet_dir)
        expected = 0
        for member in members:
            nproc = member.header.get("num_processes")
            if isinstance(nproc, int):
                expected = max(expected, nproc)
        if members:
            expected = max(expected, members[-1].process_index + 1)
        if flights:
            expected = max(expected, max(flights) + 1)
        present = {m.process_index for m in members}
        for proc in range(expected):
            if proc not in present:
                members.append(
                    FleetMember(process_index=proc, lost=True)
                )
        members.sort(key=lambda m: m.process_index)
        # adopt flight records (the torn-.tmp case parses to None and the
        # member simply has no last words)
        from photon_ml_tpu.telemetry import requests as _requests

        for member in members:
            path = flights.get(member.process_index)
            if path is not None:
                member.flight_path = path
                member.flight = _requests.read_flight(path)
        router_path = discover_router_trace(fleet_dir)
        router = None
        if router_path is not None:
            kind, first = _classify_stream(router_path)
            router = FleetMember(
                process_index=-1,
                trace_path=router_path,
                report=RunReport.load(trace=router_path),
                header=(
                    first if first.get("type") == "trace_header" else {}
                ),
            )
        report = cls(
            fleet_dir=fleet_dir,
            members=members,
            num_processes=max(expected, len(members)),
            router_trace_path=router_path,
            router=router,
        )
        report._estimate_skew()
        return report

    def _estimate_skew(self) -> None:
        """Residual clock skew per member vs the first member with
        rendezvous data, from shared coordinated-checkpoint endpoints.
        Limits (README): resolution is one quorum poll (~50 ms) and a
        fleet that never checkpointed coordinates carries skew 0 — the
        anchor pair alone aligns its timelines."""
        endpoints = {
            m.process_index: _rendezvous_endpoints(m) for m in self.members
        }
        reference: Optional[int] = None
        for proc in sorted(endpoints):
            if endpoints[proc]:
                reference = proc
                break
        if reference is None:
            return
        ref = endpoints[reference]
        for member in self.members:
            if member.process_index == reference:
                continue
            mine = endpoints[member.process_index]
            shared = sorted(set(mine) & set(ref))
            if not shared:
                continue
            member.clock_skew_s = statistics.median(
                [mine[k] - ref[k] for k in shared]
            )

    # -- derived views -------------------------------------------------------

    def merged_spans(self) -> list[dict]:
        """Every member's spans on ONE absolute timeline: each record
        gains ``process_index`` and ``abs_ts`` (skew-corrected epoch
        seconds; absent without an anchor), sorted by absolute start."""
        merged: list[dict] = []
        for member in self.members:
            for s in member.report.spans:
                rec = dict(s)
                rec["process_index"] = member.process_index
                ts = s.get("ts")
                if isinstance(ts, (int, float)):
                    abs_ts = member._abs_time(ts)
                    if abs_ts is not None:
                        rec["abs_ts"] = round(abs_ts, 6)
                merged.append(rec)
        merged.sort(
            key=lambda r: (
                r.get("abs_ts") is None,
                r.get("abs_ts") or 0.0,
                r.get("process_index"),
            )
        )
        return merged

    def request_traces(self) -> list[dict[str, Any]]:
        """Per-REQUEST joined views: every persisted ``request:*`` root
        span (tail sampling — slow/degraded/errored/sampled) from the
        router stream and each member stream, plus flight-record
        entries, grouped by ``trace_id``. One user request that fanned
        out through the router reads as one trace whose hops span
        processes. Slowest first (by the slowest hop)."""
        traces: dict[str, dict[str, Any]] = {}
        seen: set[tuple] = set()

        def _hop(trace_id: str, entry: dict[str, Any]) -> None:
            key = (
                trace_id,
                entry.get("source"),
                entry.get("name"),
                entry.get("request_id"),
                entry.get("dur_ms"),
            )
            if key in seen:
                # a harvested flight re-reads the same span stream its
                # member already persisted to — one hop, not two
                return
            seen.add(key)
            traces.setdefault(
                trace_id, {"trace_id": trace_id, "hops": []}
            )["hops"].append(entry)

        def _span_hop(member: FleetMember, label: str, s: dict) -> None:
            name = s.get("name") or ""
            if not name.startswith("request:"):
                return
            attrs = s.get("attrs") or {}
            tid = attrs.get("trace_id")
            if not tid or "request_id" not in attrs:
                return  # phase children join via their root
            entry: dict[str, Any] = {
                "source": label,
                "process_index": member.process_index,
                "name": name[len("request:"):],
                "request_id": attrs.get("request_id"),
                "role": attrs.get("role"),
                "status": attrs.get("status"),
                "sampled_reason": attrs.get("sampled_reason"),
                "dur_ms": attrs.get("dur_ms"),
                "phases": attrs.get("phases") or {},
                "attrs": attrs,
            }
            ts = s.get("ts")
            if isinstance(ts, (int, float)):
                abs_ts = member._abs_time(ts)
                if abs_ts is not None:
                    entry["abs_ts"] = round(abs_ts, 6)
            _hop(tid, entry)

        sources = list(self.members)
        if self.router is not None:
            sources.append(self.router)
        for member in sources:
            label = (
                "router"
                if member.process_index < 0
                else f"proc-{member.process_index}"
            )
            for s in member.report.spans:
                _span_hop(member, label, s)
            fl = member.flight
            if not fl:
                continue
            for r in fl.get("records") or []:
                if not isinstance(r, dict):
                    continue
                if r.get("type") == "request" and r.get("trace_id"):
                    _hop(
                        r["trace_id"],
                        {
                            "source": label,
                            "process_index": member.process_index,
                            "name": r.get("name"),
                            "request_id": r.get("request_id"),
                            "role": r.get("role"),
                            "status": r.get("status"),
                            "dur_ms": r.get("dur_ms"),
                            "phases": {
                                p["name"]: p["ms"]
                                for p in r.get("phases") or []
                                if isinstance(p, dict) and "name" in p
                            },
                            "attrs": r.get("attrs") or {},
                            "from_flight": True,
                        },
                    )
                elif r.get("type") == "span":
                    _span_hop(member, label, r)
        out = list(traces.values())
        for t in out:
            durs = [
                h["dur_ms"]
                for h in t["hops"]
                if isinstance(h.get("dur_ms"), (int, float))
            ]
            t["dur_ms"] = max(durs) if durs else None
            t["status"] = (
                "error"
                if any(h.get("status") == "error" for h in t["hops"])
                else "ok"
            )
            t["sources"] = sorted({h["source"] for h in t["hops"]})
            t["hops"].sort(
                key=lambda h: (
                    h.get("abs_ts") is None,
                    h.get("abs_ts") or 0.0,
                    h.get("source") or "",
                )
            )
        out.sort(key=lambda t: -(t["dur_ms"] or 0.0))
        return out

    def rows(self) -> list[dict[str, Any]]:
        return [m.row() for m in self.members]

    def lost_members(self) -> list[int]:
        return [m.process_index for m in self.members if m.lost]

    def straggler(self) -> Optional[dict[str, Any]]:
        """Name the member the fleet waited on: minimum total collective
        wait across members with wait data (the last to arrive at every
        barrier waits ~zero). None when fewer than two members report
        waits or the spread is below noise."""
        waits = {
            m.process_index: w
            for m in self.members
            if (w := m.collective_wait_seconds()) is not None
        }
        if len(waits) < 2:
            return None
        spread = max(waits.values()) - min(waits.values())
        if spread < _STRAGGLER_MIN_SPREAD_S:
            return None
        straggler = min(waits, key=lambda p: waits[p])
        return {
            "process_index": straggler,
            "wait_s": round(waits[straggler], 6),
            "fleet_max_wait_s": round(max(waits.values()), 6),
            "spread_s": round(spread, 6),
            "waits_by_member": {
                str(p): round(w, 6) for p, w in sorted(waits.items())
            },
        }

    def merged_hot_executables(self, k: int = 10) -> list[dict[str, Any]]:
        """The fleet-wide hot-executable list: per-NAME sums of the
        members' profiled exclusive seconds and dispatch counts (the
        same executable runs on every member of an SPMD fleet, so the
        fleet's cost of a kernel is the sum of its members' costs).
        MFU is reported as the max across members (the best-observed
        utilization of that kernel anywhere in the fleet); bound classes
        are the set observed. Empty when no member profiled anything."""
        merged: dict[str, dict[str, Any]] = {}
        for m in self.members:
            for e in m.report.hot_executables(k=1_000_000):
                agg = merged.setdefault(
                    e["name"],
                    {
                        "name": e["name"],
                        "est_exclusive_seconds": 0.0,
                        "dispatches": 0,
                        "members": 0,
                        "mfu_max": None,
                        "bound_classes": [],
                        "timing_suspect": False,
                    },
                )
                agg["est_exclusive_seconds"] += float(
                    e.get("est_exclusive_seconds") or 0.0
                )
                agg["dispatches"] += int(e.get("dispatches") or 0)
                agg["members"] += 1
                mfu = e.get("mfu")
                if mfu is not None and (
                    agg["mfu_max"] is None or mfu > agg["mfu_max"]
                ):
                    agg["mfu_max"] = mfu
                bc = e.get("bound_class", "unknown")
                if bc not in agg["bound_classes"]:
                    agg["bound_classes"].append(bc)
                agg["timing_suspect"] = agg["timing_suspect"] or bool(
                    e.get("timing_suspect")
                )
        out = list(merged.values())
        for agg in out:
            agg["est_exclusive_seconds"] = round(
                agg["est_exclusive_seconds"], 6
            )
            agg["bound_classes"] = sorted(agg["bound_classes"])
        out.sort(key=lambda e: e["est_exclusive_seconds"], reverse=True)
        return out[:k]

    def _hot_executables_markdown(self, k: int = 10) -> list[str]:
        hot = self.merged_hot_executables(k)
        if not hot:
            return []
        lines = [
            "## Fleet hot executables",
            "",
            "_Per-executable profiled exclusive seconds summed across "
            "members (SPMD: the fleet pays every member's copy); MFU is "
            "the best observed on any member._",
            "",
            "| executable | excl s (fleet) | dispatches | members | "
            "MFU max | bound |",
            "|---|---|---|---|---|---|",
        ]
        for e in hot:
            name = f"`{e['name']}`"
            if e["timing_suspect"]:
                name += " ⚠"
            lines.append(
                f"| {name} | {_fmt(e['est_exclusive_seconds'])} | "
                f"{e['dispatches']} | {e['members']} | "
                f"{_fmt_pct(e['mfu_max'])} | "
                f"{', '.join(e['bound_classes'])} |"
            )
        lines.append("")
        return lines

    def _requests_markdown(self, k: int = 10) -> list[str]:
        traces = self.request_traces()
        if not traces:
            return []
        lines = [
            "## Requests",
            "",
            "_Persisted request traces (tail sampling: slow / degraded / "
            "errored / explicitly sampled), joined across router and "
            "member streams by `trace_id`; slowest hop first._",
            "",
            "| trace | ms | status | hops | phases |",
            "|---|---|---|---|---|",
        ]
        for t in traces[:k]:
            phases: list[str] = []
            for h in t["hops"]:
                for name, ms in (h.get("phases") or {}).items():
                    if isinstance(ms, (int, float)):
                        phases.append(f"{name} {ms:.1f}")
            lines.append(
                f"| `{t['trace_id']}` | {_fmt_or_unknown(t['dur_ms'])} | "
                f"{t['status']} | {', '.join(t['sources'])} | "
                f"{'; '.join(phases[:8])} |"
            )
        lines.append("")
        return lines

    def _last_words_markdown(self, k: int = 5) -> list[str]:
        """Flight-recorder renderings for LOST members: the last entries
        of each harvested/dumped flight record — what the member was
        doing when it died."""
        lines: list[str] = []
        for m in self.members:
            if not m.lost or not m.flight:
                continue
            recs = m.flight.get("records") or []
            how = (
                "harvested from the span-stream tail"
                if m.flight.get("harvested")
                else "drain-path dump"
            )
            note = (
                f"_{len(recs)} record(s) in the final "
                f"{_fmt(m.flight.get('window_s'))}s window ({how}"
            )
            if m.flight.get("dropped"):
                note += f"; {m.flight['dropped']} ring drop(s)"
            note += ")._"
            lines += [f"### Last words — member {m.process_index}", "", note, ""]
            for r in recs[-k:]:
                if not isinstance(r, dict):
                    continue
                if r.get("type") == "request":
                    desc = (
                        f"- `{r.get('name')}` {r.get('status')} "
                        f"{_fmt_or_unknown(r.get('dur_ms'))} ms"
                    )
                    if r.get("error"):
                        desc += f" — {r['error']}"
                else:
                    desc = f"- span `{r.get('name')}`"
                    dur = r.get("dur")
                    if isinstance(dur, (int, float)):
                        desc += f" {dur * 1000.0:.1f} ms"
                    err = (r.get("attrs") or {}).get("error")
                    if err:
                        desc += f" — {err}"
                lines.append(desc)
            lines.append("")
        if lines:
            lines = ["## Flight recorder", ""] + lines
        return lines

    def key_metrics(self) -> dict[str, float]:
        """The aggregated scalar summary ``compare()`` gates on."""
        out: dict[str, float] = {
            "fleet_members": float(self.num_processes),
            "fleet_lost_members": float(len(self.lost_members())),
        }
        rates = [
            m.key_metrics().get("rows_per_sec") for m in self.members
        ]
        rates = [r for r in rates if r]
        if rates:
            out["fleet_rows_per_sec"] = float(sum(rates))
        coeff_rates = [
            m.key_metrics().get("coeffs_per_sec") for m in self.members
        ]
        coeff_rates = [r for r in coeff_rates if r]
        if coeff_rates:
            out["fleet_coeffs_per_sec"] = float(sum(coeff_rates))
        waits = [
            w
            for m in self.members
            if (w := m.collective_wait_seconds()) is not None
        ]
        run_total = sum(m.run_seconds() for m in self.members)
        if waits:
            out["fleet_collective_wait_s"] = round(sum(waits), 6)
            if run_total:
                out["fleet_collective_wait_fraction"] = round(
                    sum(waits) / run_total, 6
                )
        mfus = [
            mfu
            for m in self.members
            if (mfu := m.key_metrics().get("mfu")) is not None
        ]
        if len(mfus) >= 2:
            out["fleet_mfu_spread"] = round(max(mfus) - min(mfus), 6)
        gaps = [
            g
            for m in self.members
            if (g := m.heartbeat_gap_max_s()) is not None
        ]
        if gaps:
            out["fleet_heartbeat_gap_max_s"] = round(max(gaps), 3)
        skews = [abs(m.clock_skew_s) for m in self.members]
        if any(skews):
            out["fleet_clock_skew_max_s"] = round(max(skews), 6)
        return out

    def compare(
        self,
        baseline: Mapping[str, Any],
        threshold: float = 0.2,
    ) -> list[MetricDelta]:
        """Diff aggregated key metrics against a baseline fleet-report
        JSON (its ``key_metrics``) or a bare ``{metric: value}`` dict —
        the same contract as ``RunReport.compare``."""
        base = baseline.get("key_metrics", baseline)
        return compare_metrics(
            self.key_metrics(),
            base,
            threshold=threshold,
            directions=FLEET_KEY_METRIC_DIRECTIONS,
        )

    # -- rendering -----------------------------------------------------------

    def to_json(self) -> dict[str, Any]:
        return {
            "type": "fleet_report",
            "format_version": FLEET_REPORT_FORMAT_VERSION,
            "generated": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "fleet_dir": self.fleet_dir,
            "num_processes": self.num_processes,
            "lost_members": self.lost_members(),
            "key_metrics": self.key_metrics(),
            "members": self.rows(),
            "straggler": self.straggler(),
            "hot_executables": self.merged_hot_executables(),
            "router_trace": self.router_trace_path,
            "request_traces": self.request_traces()[:20],
        }

    def save_json(self, path: str) -> dict[str, Any]:
        from photon_ml_tpu.utils.atomic import atomic_write_json

        doc = self.to_json()
        atomic_write_json(path, doc, indent=2, sort_keys=True, default=str)
        return doc

    def _quality_markdown(self) -> list[str]:
        """Fleet quality rollup: gate decisions summed across members,
        drift-sketch coverage per member. Empty when no member touched
        the quality layer."""
        totals: dict[str, int] = {}
        drift_rows: list[str] = []
        for m in self.members:
            q = m.report.quality_summary()
            if not q:
                continue
            for key in (
                "stats_computed", "bootstrap_fits", "gate_published",
                "gate_quarantined", "gate_bypassed", "gate_no_champion",
                "pipeline_quarantines",
            ):
                if q.get(key):
                    totals[key] = totals.get(key, 0) + int(q[key])
            versions = (q.get("drift") or {}).get("versions") or {}
            if versions:
                scored = sum(
                    (row.get("scores") or {}).get("count", 0)
                    for row in versions.values()
                )
                drift_rows.append(
                    f"- member {m.process_index}: drift sketches for "
                    f"{len(versions)} version(s), {scored} score(s) "
                    "observed"
                )
        if not totals and not drift_rows:
            return []
        out = ["## Quality", ""]
        if totals:
            bits = [f"{v} {k.replace('_', ' ')}" for k, v in
                    sorted(totals.items())]
            out.append("- fleet totals: " + ", ".join(bits))
        out += drift_rows
        out.append("")
        return out

    def to_markdown(
        self, deltas: Optional[Sequence[MetricDelta]] = None
    ) -> str:
        lines: list[str] = ["# Fleet report", ""]
        lines.append(
            f"_Fleet dir: `{self.fleet_dir}` — "
            f"{self.num_processes} member(s)_"
        )
        lines.append("")
        lost = self.lost_members()
        if lost:
            lines += [
                f"> **Warning**: member(s) {lost} are **lost** — their "
                "final metrics snapshot never landed (killed before the "
                "atexit flush, or artifacts missing). Rows below render "
                "whatever survived; fleet aggregates undercount.",
                "",
            ]

        km = self.key_metrics()
        if km:
            lines += [
                "## Fleet key metrics",
                "",
                "| metric | value |",
                "|---|---|",
            ]
            for name, value in sorted(km.items()):
                lines.append(f"| `{name}` | {_fmt(value)} |")
            lines.append("")

        lines += [
            "## Members",
            "",
            "| proc | status | rows/s | MFU | comms | wait s | wait "
            "share | chunks | hot exec | beats | max gap s | skew s |",
            "|---|---|---|---|---|---|---|---|---|---|---|---|",
        ]
        for row in self.rows():
            lines.append(
                f"| {row['process_index']}"
                + (f" ({row['hostname']})" if row.get("hostname") else "")
                + f" | {row['status']} | "
                f"{_fmt_or_unknown(row['rows_per_sec'])} | "
                f"{_fmt_pct(row['mfu'])} | "
                f"{_fmt_pct(row['comms_fraction'])} | "
                f"{_fmt_or_unknown(row['collective_wait_s'])} | "
                f"{_fmt_pct(row['collective_wait_share'])} | "
                f"{_fmt_or_unknown(row['chunks_done'])} | "
                + (
                    f"`{row['hot_exec']}`"
                    if row.get("hot_exec")
                    else "unknown"
                )
                + f" | {row['heartbeats']} | "
                f"{_fmt_or_unknown(row['heartbeat_gap_max_s'])} | "
                f"{_fmt(row['clock_skew_s'])} |"
            )
        lines.append("")

        lines += self._last_words_markdown()
        lines += self._requests_markdown()
        lines += self._hot_executables_markdown()
        lines += self._quality_markdown()

        straggler = self.straggler()
        if straggler is not None:
            lines += [
                f"**Straggler: member {straggler['process_index']}** — "
                f"it waited only {straggler['wait_s']:.3f}s at the "
                "fleet's collectives while the slowest-waiting member "
                f"stood by for {straggler['fleet_max_wait_s']:.3f}s "
                "(low wait = last to arrive = the member everyone else "
                "waited on).",
                "",
            ]
        elif not lost:
            lines += [
                "No straggler callout: collective waits are balanced "
                "(or unrecorded) across members.",
                "",
            ]

        if deltas is not None:
            lines += _compare_markdown(deltas)
        return "\n".join(lines).rstrip() + "\n"
