"""Live progress heartbeat: long fits and benches are never silent.

BENCH_r05's north-star run timed out (rc=124) with NOTHING on stdout — an
hours-long GAME fit gives no liveness signal between its start and its
finish line. The :class:`Heartbeat` is a daemon thread that every
``interval`` seconds emits ONE structured line to the
``photon_ml_tpu.telemetry.progress`` logger and (optionally) a JSONL sink:

    {"type": "heartbeat", "seq": 3, "uptime_s": 90.1,
     "span": "fit > cd_iteration > coordinate:per-user",
     "rows_per_s": 812345.0, "coeffs_per_s": 104321.0,
     "rows_total": 2.4e7, "coeffs_total": 3.1e6,
     "hbm_bytes_in_use": 7516192768, "checkpoint_age_s": 41.0,
     "checkpoint_last_step": 7, "dropped_spans": 0,
     "guard": {"diverged": 0, "retried": 0, "rolled_back": 0, "frozen": 0}}

Rates are deltas of the ``progress.rows`` / ``progress.coeffs`` counters
(incremented by coordinate descent and the streaming trainer) over the
beat window; each beat also refreshes the ``progress.rows_per_sec`` /
``progress.coeffs_per_sec`` gauges so the final metrics snapshot carries
the last observed rates. ``span`` is the deepest open span path across
threads. The FIRST beat fires one full interval after start, so anything
shorter than ``interval`` (quick fits, unit tests) emits nothing — the
train CLI leaves the heartbeat on by default with a ~30 s interval.

The heartbeat must never fail or slow training: all probes swallow
errors, the JSONL sink is append-only and disabled on write failure, and
``stop()`` always joins the thread.

``beat()`` is public (tests drive it directly) AND the daemon thread's
whole job, so the sampling state (``_seq``, the ``_last_*`` rate cursors,
the sink path) is written from two threads: every write sits under
``self._lock`` (lint L015 — the lock-discipline pass — enforces this).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Optional

from photon_ml_tpu.telemetry import (
    identity,
    memory,
    metrics,
    profile,
    trace,
    xla,
)

__all__ = ["Heartbeat", "DEFAULT_INTERVAL_S", "tail_heartbeat_fields"]

logger = logging.getLogger("photon_ml_tpu.telemetry.progress")

#: Default beat interval: long enough that sub-30 s fits stay silent.
DEFAULT_INTERVAL_S = 30.0

_GUARD_COUNTERS = ("diverged", "retried", "rolled_back", "frozen")


class Heartbeat:
    """Periodic liveness/progress emitter (daemon thread).

    Use as a context manager around a fit, or ``start()``/``stop()``
    explicitly. ``beat()`` is callable directly for deterministic tests.
    """

    def __init__(
        self,
        interval: float = DEFAULT_INTERVAL_S,
        jsonl_path: Optional[str] = None,
    ):
        if interval <= 0:
            raise ValueError("heartbeat interval must be > 0 seconds")
        self.interval = float(interval)
        self.jsonl_path = jsonl_path
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # guards the sampling cursors below: beat() runs on the daemon
        # thread AND is public API for deterministic tests — an unlocked
        # read-modify-write of the _last_* deltas from both sides would
        # double-count or lose a rate window (lint L015)
        self._lock = threading.Lock()
        self._seq = 0
        self._t0 = time.monotonic()
        self._last_t = self._t0
        self._last_rows = 0.0
        self._last_coeffs = 0.0
        self._last_flops = 0.0
        self._last_xla_bytes = 0.0
        self._last_comms = 0.0
        self._last_ingest_rows = 0.0
        self._last_profile_excl: dict[str, float] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Heartbeat":
        if self._thread is not None:
            return self  # idempotent
        self._stop.clear()
        with self._lock:
            self._t0 = time.monotonic()
            self._last_t = self._t0
            self._last_rows = metrics.counter("progress.rows").value
            self._last_coeffs = metrics.counter("progress.coeffs").value
            # peek, don't create: registering these at 0 would turn the
            # run report's "unknown" (counter absent) into a fabricated 0
            self._last_flops = metrics.peek_counter("xla.flops_total") or 0.0
            self._last_xla_bytes = (
                metrics.peek_counter("xla.bytes_total") or 0.0
            )
            self._last_comms = metrics.peek_counter("comms.bytes_total") or 0.0
            self._last_ingest_rows = (
                metrics.peek_counter("ingest.rows") or 0.0
            )
            self._last_profile_excl = profile.exclusive_seconds_by_name()
        self._thread = threading.Thread(
            target=self._run, name="photon-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(5.0, self.interval))
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        # first beat one FULL interval in: short runs emit nothing
        while not self._stop.wait(self.interval):
            try:
                self.beat()
            except Exception:  # noqa: BLE001 — never fail training
                logger.debug("heartbeat probe failed", exc_info=True)

    # -- one beat ------------------------------------------------------------

    def beat(self) -> dict[str, Any]:
        """Sample progress, emit one line, and return it.

        Sampling (the ``_last_*`` delta cursors and ``_seq``) runs under
        ``self._lock`` — the daemon thread and a direct test caller may
        beat concurrently; the log/sink emit stays outside the lock so
        slow I/O never blocks the other sampler."""
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._last_t, 1e-9)
            rows = metrics.counter("progress.rows").value
            coeffs = metrics.counter("progress.coeffs").value
            rows_per_s = (rows - self._last_rows) / dt
            coeffs_per_s = (coeffs - self._last_coeffs) / dt
            self._last_t, self._last_rows, self._last_coeffs = (
                now, rows, coeffs,
            )
            if rows_per_s > 0:
                metrics.gauge("progress.rows_per_sec").set(rows_per_s)
            if coeffs_per_s > 0:
                metrics.gauge("progress.coeffs_per_sec").set(coeffs_per_s)

            self._seq += 1
            line: dict[str, Any] = {
                "type": "heartbeat",
                "seq": self._seq,
                "uptime_s": round(now - self._t0, 3),
                "span": trace.active_span_path(),
                "rows_per_s": round(rows_per_s, 1),
                "coeffs_per_s": round(coeffs_per_s, 1),
                "rows_total": rows,
                "coeffs_total": coeffs,
                "dropped_spans": metrics.counter("trace.dropped_spans").value,
            }
            # fleet attribution: interleaved multi-process progress logs
            # need to say WHOSE line this is. Field present only inside a
            # fleet (PHOTON_PROC_ID / multi-process jax) — the
            # single-process line format is pinned unchanged by tests
            proc = identity.fleet_process_index()
            if proc is not None:
                line["proc"] = proc
            # device utilization over the beat window (ISSUE 5): live MFU
            # needs both cost analysis (flops counted) and a known device
            # peak; comms fraction needs a comms estimate — absent either,
            # the fields are simply omitted ("unknown"), never zero
            flops = metrics.peek_counter("xla.flops_total") or 0.0
            xla_bytes = metrics.peek_counter("xla.bytes_total") or 0.0
            comms = metrics.peek_counter("comms.bytes_total") or 0.0
            d_flops = flops - self._last_flops
            d_bytes = xla_bytes - self._last_xla_bytes
            d_comms = comms - self._last_comms
            self._last_flops, self._last_xla_bytes = flops, xla_bytes
            self._last_comms = comms
            # ingest pipeline liveness (peek: absence stays "unknown")
            ingest_rows = metrics.peek_counter("ingest.rows")
            d_ingest = (
                None
                if ingest_rows is None
                else ingest_rows - self._last_ingest_rows
            )
            if ingest_rows is not None:
                self._last_ingest_rows = ingest_rows
            # hottest executable THIS window: top positive delta of the
            # profiler's estimated exclusive seconds (pure registry read —
            # no device probes). No sampled dispatches this window, or no
            # profiler data at all, omits the field ("unknown", never a
            # stale winner carried forward)
            excl = profile.exclusive_seconds_by_name()
            hot_exec = None
            hot_delta = 0.0
            for name, secs in excl.items():
                d = secs - self._last_profile_excl.get(name, 0.0)
                if d > hot_delta:
                    hot_delta, hot_exec = d, name
            self._last_profile_excl = excl
            if hot_exec is not None:
                line["hot_exec"] = hot_exec
            sink = self.jsonl_path

        # everything below reads device/metrics state, not heartbeat
        # cursors — it stays OUTSIDE the lock so a stalled device probe
        # (hbm_stats queries every mesh device) never blocks the other
        # sampler; the deltas feeding these fields were captured above
        if d_flops > 0:
            peak_flops, _peak_bw = xla.device_peaks()
            if peak_flops:
                line["mfu"] = round(d_flops / (dt * peak_flops), 6)
        if d_comms > 0 and d_bytes > 0:
            # both sides of the ratio known this window; without HBM
            # bytes (no cost analysis) the fraction is unknowable — omit
            # rather than emit a fabricated 100%
            line["comms_fraction"] = round(d_comms / (d_comms + d_bytes), 6)
        stats = memory.hbm_stats()
        if stats and "bytes_in_use" in stats:
            line["hbm_bytes_in_use"] = int(stats["bytes_in_use"])
            if "bytes_limit" in stats:
                line["hbm_bytes_limit"] = int(stats["bytes_limit"])
        if d_ingest is not None:
            # how fast data is entering the device vs how often the solve
            # had to wait for it — the live form of the RunReport
            # "Ingestion" section
            line["ingest_rows_per_s"] = round(d_ingest / dt, 1)
            depth = metrics.peek_gauge("ingest.queue_depth")
            if depth is not None:
                line["ingest_queue_depth"] = int(depth)
            stalls = metrics.peek_counter("ingest.stalls")
            if stalls:
                line["ingest_stalls"] = int(stalls)
            waits = metrics.peek_counter("ingest.solve_waits")
            if waits:
                line["ingest_solve_waits"] = int(waits)
        spread = memory.device_spread_bytes()
        if spread is not None:
            # shard imbalance signal: max-min HBM in use across the mesh
            # devices (a balanced entity sharding keeps this near zero;
            # a lopsided one concentrates table bytes on few devices)
            line["hbm_device_spread_bytes"] = spread
        sweep_total = metrics.gauge("sweep.configs_total").value
        if sweep_total:
            # mid-sweep liveness: how many of the G config lanes the
            # batched executables have fully processed so far
            line["sweep_configs_total"] = int(sweep_total)
            line["sweep_configs_done"] = int(
                metrics.gauge("sweep.configs_done").value or 0
            )
        last_save = metrics.gauge("checkpoint.last_save_ts").value
        if last_save is not None:
            line["checkpoint_age_s"] = round(
                max(trace.TRACER.now() - last_save, 0.0), 3
            )
            step = metrics.gauge("checkpoint.last_step").value
            if step is not None:
                line["checkpoint_last_step"] = int(step)
        guard = {
            name: metrics.counter(f"solves.{name}").value
            for name in _GUARD_COUNTERS
        }
        if any(guard.values()):
            line["guard"] = guard

        logger.info("heartbeat %s", json.dumps(line, default=str))
        if sink is not None:
            try:
                with open(sink, "a", encoding="utf-8") as fh:
                    fh.write(json.dumps(line, default=str) + "\n")
            except OSError:
                logger.warning(
                    "heartbeat sink %s unwritable; disabling it", sink
                )
                with self._lock:
                    self.jsonl_path = None
        return line


def tail_heartbeat_fields(
    path: str,
    max_bytes: int = 65536,
    expect_proc: Optional[int] = None,
) -> Optional[dict[str, Any]]:
    """The newest parseable ``{"type": "heartbeat", ...}`` line of a
    telemetry JSONL — the fleet supervisor's live-status probe.

    Reads only the file's last ``max_bytes`` (the supervisor polls every
    member on a cadence; re-reading whole telemetry files would scale the
    poll with run length), walks candidate lines newest-first, and skips
    anything unparseable — a member killed mid-write leaves a truncated
    final line, and the beat before it is still the freshest truth.

    ``expect_proc`` makes the parser REQUIRE member attribution: lines
    without a matching ``proc`` field are rejected, so a mis-pointed file
    (or a single-process artifact polled as member i's) reads as "no
    heartbeat" instead of silently attributing another member's progress.
    Returns None when no acceptable heartbeat line exists. Pure file IO —
    this runs on the supervisor's status thread and must never touch a
    device (the static gate seeds it into the L013 sync walk).
    """
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(size - max_bytes, 0))
            tail = fh.read()
    except OSError:
        return None
    for raw in reversed(tail.splitlines()):
        raw = raw.strip()
        if not raw:
            continue
        try:
            rec = json.loads(raw.decode("utf-8", errors="replace"))
        except ValueError:
            continue  # truncated / partial line: keep walking backward
        if not isinstance(rec, dict) or rec.get("type") != "heartbeat":
            continue
        if expect_proc is not None and rec.get("proc") != expect_proc:
            continue
        return rec
    return None
