"""Hierarchical tracing spans with a JSONL sink and a Chrome-trace exporter.

The photon-ml driver wraps every phase in named ``Timed`` blocks
(util/Timed.scala) but only ever logs flat durations. Here every phase is a
*span* in a thread-safe tree: ``with trace.span("fit"):`` nests under
whatever span is open on the current thread, records monotonic wall time,
arbitrary attributes, and point-in-time events (device fetches, jit
compiles). Completed spans stream to a JSONL file (one object per line) and
convert to the Chrome trace-event format, so a full GAME fit opens as a
flame chart in Perfetto (https://ui.perfetto.dev).

Durations use ``time.monotonic()`` exclusively — wall-clock steps (NTP,
DST) corrupt phase timings (PERF_NOTES.md "fake timing" gotcha). The one
wall-clock anchor, recorded at configure time for human correlation, comes
from ``datetime`` so the ``time.time()`` lint stays meaningful.

Span JSONL schema (one line per completed span)::

    {"type": "span", "id": 7, "parent": 3, "name": "coordinate:fixed",
     "ts": 1.042, "dur": 0.381, "thread": "MainThread",
     "attrs": {"iteration": 0},
     "events": [{"name": "device_fetch", "ts": 1.401,
                 "attrs": {"bytes": 4, "seconds": 0.1}}]}

``ts`` is seconds since the tracer's monotonic anchor; ``events[].ts``
shares the same timebase.
"""

from __future__ import annotations

import datetime
import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterable, Iterator, Optional

from photon_ml_tpu.telemetry import identity

__all__ = [
    "Span",
    "Tracer",
    "TRACER",
    "span",
    "current_span",
    "add_event",
    "active_span_path",
    "configure",
    "reset",
    "finished_spans",
    "set_annotation_factory",
    "to_chrome_trace",
    "export_chrome_trace",
    "perfetto_path",
]

DEFAULT_BUFFER_LIMIT = 50_000


class Span:
    """One timed phase: a node of the per-thread span tree."""

    __slots__ = (
        "name", "span_id", "parent_id", "ts", "dur", "attrs", "events",
        "thread",
    )

    def __init__(
        self,
        name: str,
        span_id: int,
        parent_id: Optional[int],
        ts: float,
        thread: str,
        attrs: dict[str, Any],
    ):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.ts = ts
        self.dur: Optional[float] = None  # set when the span closes
        self.attrs = attrs
        self.events: list[dict[str, Any]] = []
        self.thread = thread

    def set_attr(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def add_event(self, name: str, ts: float, **attrs: Any) -> None:
        self.events.append({"name": name, "ts": ts, "attrs": attrs})

    def to_dict(self) -> dict[str, Any]:
        return {
            "type": "span",
            "id": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "ts": round(self.ts, 6),
            "dur": None if self.dur is None else round(self.dur, 6),
            "thread": self.thread,
            "attrs": self.attrs,
            "events": self.events,
        }


class Tracer:
    """Thread-safe span collector: per-thread open-span stacks, a shared
    bounded buffer of completed spans, and an optional JSONL sink.

    Tracing must never fail training: sink write errors are swallowed after
    disabling the sink, and attribute values that are not JSON-serializable
    are stringified.
    """

    def __init__(self, buffer_limit: int = DEFAULT_BUFFER_LIMIT):
        self._local = threading.local()
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._anchor = time.monotonic()
        self._finished: list[Span] = []
        # every thread's open-span stack, so reset() can clear them ALL
        # (threading.local is only visible from its own thread)
        self._all_stacks: list[list[Span]] = []
        self._default_buffer_limit = buffer_limit
        self._buffer_limit = buffer_limit
        self.dropped_spans = 0
        self._sink_path: Optional[str] = None
        self._sink_fh = None
        self._wall_anchor: Optional[str] = None
        # optional per-span mirror: a context-manager factory (e.g.
        # jax.profiler.TraceAnnotation) entered/exited with every span so
        # the span tree aligns with xprof timelines (`cli profile`)
        self._annotation_factory = None

    # -- configuration -------------------------------------------------------

    def configure(
        self,
        jsonl_path: Optional[str] = None,
        buffer_limit: Optional[int] = None,
    ) -> None:
        """Set (or replace) the JSONL sink and/or the in-memory buffer cap."""
        with self._lock:
            if buffer_limit is not None:
                self._buffer_limit = int(buffer_limit)
            if jsonl_path is not None and jsonl_path != self._sink_path:
                self._close_sink_locked()
                self._sink_path = jsonl_path
                # truncate: one session per file — appending a rerun would
                # mix incompatible monotonic timebases (and a second
                # mid-file trace_header) into one Perfetto export
                self._sink_fh = open(jsonl_path, "w", encoding="utf-8")
                wall = datetime.datetime.now(datetime.timezone.utc)
                self._wall_anchor = wall.isoformat()
                header = {
                    "type": "trace_header",
                    "wall_time": self._wall_anchor,
                    "monotonic_anchor": round(time.monotonic() - self._anchor, 6),
                    # the monotonic<->epoch anchor pair: a span at tracer
                    # time `ts` happened at absolute epoch second
                    # `anchor_unix_s + (ts - monotonic_anchor)` — the
                    # alignment FleetReport merges member timelines on
                    "anchor_unix_s": round(wall.timestamp(), 6),
                    "hostname": identity.hostname(),
                }
                proc = identity.fleet_process_index()
                if proc is not None:
                    header["process_index"] = proc
                    nproc = identity.fleet_process_count()
                    if nproc is not None:
                        header["num_processes"] = nproc
                self._sink_fh.write(json.dumps(header) + "\n")
                self._sink_fh.flush()

    def _close_sink_locked(self) -> None:
        if self._sink_fh is not None:
            try:
                self._sink_fh.close()
            except OSError:
                pass
        self._sink_fh = None
        self._sink_path = None

    def set_annotation_factory(self, factory) -> None:
        """Mirror every span into ``factory(name)`` context managers —
        ``jax.profiler.TraceAnnotation`` makes the span tree line up with
        xprof timelines during a ``cli profile`` capture. ``None``
        disables. Annotation failures never fail the span."""
        self._annotation_factory = factory

    def reset(self) -> None:
        """Drop all finished spans, close the sink, clear EVERY thread's
        open-span stack (test isolation; a span left open on a worker
        thread must not parent post-reset spans), and restore the
        constructor-default buffer limit, drop accounting, and the span
        annotation mirror."""
        with self._lock:
            self._finished.clear()
            self._close_sink_locked()
            for stack in self._all_stacks:
                stack.clear()
            self._buffer_limit = self._default_buffer_limit
            self.dropped_spans = 0
            self._annotation_factory = None

    # -- span lifecycle ------------------------------------------------------

    def _stack(self) -> list[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
            with self._lock:
                self._all_stacks.append(stack)
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def open_spans(self) -> list[Span]:
        """The deepest currently-open span path ACROSS threads, outermost
        first — the stack whose innermost span started most recently wins.
        Safe to call from a monitor thread (the heartbeat): stacks are
        copied under the GIL; a span closing mid-copy at worst drops one
        path element."""
        with self._lock:
            stacks = [list(s) for s in self._all_stacks]
        stacks = [s for s in stacks if s]
        if not stacks:
            return []
        return max(stacks, key=lambda s: s[-1].ts)

    def active_span_path(self, sep: str = " > ") -> str:
        """``"fit > cd_iteration > coordinate:fixed"`` for the deepest
        open span path, or ``""`` when nothing is open."""
        return sep.join(s.name for s in self.open_spans())

    def now(self) -> float:
        """Seconds on the tracer's monotonic timebase."""
        return time.monotonic() - self._anchor

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        stack = self._stack()
        parent = stack[-1] if stack else None
        s = Span(
            name=name,
            span_id=next(self._ids),
            parent_id=None if parent is None else parent.span_id,
            ts=self.now(),
            thread=threading.current_thread().name,
            attrs=dict(attrs),
        )
        stack.append(s)
        annotation = None
        factory = self._annotation_factory
        if factory is not None:
            try:
                annotation = factory(name)
                annotation.__enter__()
            except Exception:  # noqa: BLE001 — mirroring must never fail
                annotation = None
        try:
            yield s
        finally:
            if annotation is not None:
                try:
                    annotation.__exit__(None, None, None)
                except Exception:  # noqa: BLE001
                    pass
            s.dur = self.now() - s.ts
            # close even if exits arrive out of order (a leaked child span)
            while stack and stack[-1] is not s:
                stack.pop()
            if stack:
                stack.pop()
            self._finish(s)

    def add_event(self, name: str, **attrs: Any) -> None:
        """Attach a point-in-time event to the current span (no-op when no
        span is open — telemetry must never fail the caller)."""
        cur = self.current()
        if cur is not None:
            cur.add_event(name, ts=self.now(), **attrs)

    def emit(
        self,
        name: str,
        ts: float,
        dur: float,
        parent: Optional[int] = None,
        **attrs: Any,
    ) -> int:
        """Record an already-measured span retroactively (no context
        manager): the request tracer's tail sampler decides AFTER a
        request finished whether its phases deserve full spans. Returns
        the span id so callers can parent children under it."""
        s = Span(
            name=name,
            span_id=next(self._ids),
            parent_id=parent,
            ts=float(ts),
            thread=threading.current_thread().name,
            attrs=dict(attrs),
        )
        s.dur = max(0.0, float(dur))
        self._finish(s)
        return s.span_id

    def _finish(self, s: Span) -> None:
        dropped = 0
        with self._lock:
            self._finished.append(s)
            if len(self._finished) > self._buffer_limit:
                dropped = len(self._finished) - self._buffer_limit
                del self._finished[:dropped]
                self.dropped_spans += dropped
            if self._sink_fh is not None:
                try:
                    self._sink_fh.write(
                        json.dumps(s.to_dict(), default=str) + "\n"
                    )
                    self._sink_fh.flush()
                except (OSError, ValueError):
                    self._close_sink_locked()  # never fail training
        if dropped:
            # buffer overflow was silent data loss — surface it in the
            # metrics snapshot and the run report (local import: metrics
            # must stay importable without trace)
            from photon_ml_tpu.telemetry import metrics

            metrics.counter("trace.dropped_spans").inc(dropped)

    # -- inspection ----------------------------------------------------------

    def finished_spans(self, name: Optional[str] = None) -> list[Span]:
        with self._lock:
            spans = list(self._finished)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return spans


#: Process-global tracer; module-level helpers below delegate to it.
TRACER = Tracer()

span = TRACER.span
current_span = TRACER.current
add_event = TRACER.add_event
active_span_path = TRACER.active_span_path
configure = TRACER.configure
reset = TRACER.reset
finished_spans = TRACER.finished_spans
set_annotation_factory = TRACER.set_annotation_factory


# -- Chrome trace (Perfetto) export ------------------------------------------


def to_chrome_trace(records: Iterable[dict] | str) -> dict:
    """Convert span dicts (``Span.to_dict()`` / JSONL lines) to the Chrome
    trace-event JSON object Perfetto and chrome://tracing load directly.

    Complete spans become ``ph: "X"`` duration events; span events become
    ``ph: "i"`` thread-scoped instants. Timestamps are microseconds on the
    tracer's monotonic timebase.

    ``records`` may instead be a FLEET telemetry directory path: every
    member's ``trace.proc-<i>.jsonl`` stream merges into one file with a
    Perfetto track per process (``proc-<i> (<hostname>)``) and timestamps
    aligned through the PR 13 skew anchors — a request that fanned out
    across members renders as one timeline.
    """
    if isinstance(records, str):
        return _fleet_chrome_trace(records)
    tids: dict[str, int] = {}
    events: list[dict] = []
    meta: list[dict] = []

    def tid(thread: str) -> int:
        if thread not in tids:
            tids[thread] = len(tids) + 1
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": tids[thread],
                    "args": {"name": thread},
                }
            )
        return tids[thread]

    for rec in records:
        if rec.get("type") != "span":
            continue
        t = tid(rec.get("thread", "main"))
        events.append(
            {
                "name": rec["name"],
                "cat": "span",
                "ph": "X",
                "ts": round(rec["ts"] * 1e6, 3),
                "dur": round((rec.get("dur") or 0.0) * 1e6, 3),
                "pid": 1,
                "tid": t,
                "args": rec.get("attrs", {}),
            }
        )
        for ev in rec.get("events", ()):
            events.append(
                {
                    "name": ev["name"],
                    "cat": "event",
                    "ph": "i",
                    "s": "t",
                    "ts": round(ev["ts"] * 1e6, 3),
                    "pid": 1,
                    "tid": t,
                    "args": ev.get("attrs", {}),
                }
            )
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def _fleet_chrome_trace(fleet_dir: str) -> dict:
    """One Chrome trace for a whole fleet directory: per-process tracks,
    member timelines aligned on FleetReport's absolute (anchor + skew)
    timebase, origin at the earliest anchored span."""
    # local import: fleet_report imports report which imports this module
    from photon_ml_tpu.telemetry.fleet_report import FleetReport

    fleet = FleetReport.load(fleet_dir)
    merged = fleet.merged_spans()
    anchored = [
        r["abs_ts"] for r in merged if isinstance(r.get("abs_ts"), (int, float))
    ]
    t0 = min(anchored) if anchored else 0.0
    hosts = {m.process_index: m.hostname for m in fleet.members}
    events: list[dict] = []
    meta: list[dict] = []
    pids: set[int] = set()
    tids: dict[tuple[int, str], int] = {}

    def pid_of(proc: int) -> int:
        pid = int(proc) + 1  # Perfetto hides pid 0
        if pid not in pids:
            pids.add(pid)
            label = f"proc-{proc}"
            if hosts.get(proc):
                label += f" ({hosts[proc]})"
            meta.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": label},
                }
            )
        return pid

    def tid_of(pid: int, thread: str) -> int:
        key = (pid, thread)
        if key not in tids:
            tids[key] = sum(1 for k in tids if k[0] == pid) + 1
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tids[key],
                    "args": {"name": thread},
                }
            )
        return tids[key]

    for rec in merged:
        if rec.get("type") != "span":
            continue
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        pid = pid_of(int(rec.get("process_index") or 0))
        t = tid_of(pid, rec.get("thread", "main"))
        abs_ts = rec.get("abs_ts")
        # the per-record delta from member-local to fleet-absolute time;
        # an unanchored stream keeps its local timebase (better skewed
        # than dropped)
        shift = (abs_ts - t0 - ts) if isinstance(abs_ts, (int, float)) else 0.0
        events.append(
            {
                "name": rec["name"],
                "cat": "span",
                "ph": "X",
                "ts": round((ts + shift) * 1e6, 3),
                "dur": round((rec.get("dur") or 0.0) * 1e6, 3),
                "pid": pid,
                "tid": t,
                "args": rec.get("attrs", {}),
            }
        )
        for ev in rec.get("events", ()):
            if not isinstance(ev.get("ts"), (int, float)):
                continue
            events.append(
                {
                    "name": ev["name"],
                    "cat": "event",
                    "ph": "i",
                    "s": "t",
                    "ts": round((ev["ts"] + shift) * 1e6, 3),
                    "pid": pid,
                    "tid": t,
                    "args": ev.get("attrs", {}),
                }
            )
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def perfetto_path(trace_out: str) -> str:
    """The sibling ``.perfetto.json`` path for a span JSONL path (shared by
    every driver that auto-exports a Chrome trace next to its JSONL)."""
    base = trace_out[:-6] if trace_out.endswith(".jsonl") else trace_out
    return base + ".perfetto.json"


def export_chrome_trace(jsonl_path: str, out_path: str) -> int:
    """Convert a span JSONL file — or a fleet telemetry DIRECTORY of
    ``trace.proc-<i>.jsonl`` streams — to one Chrome/Perfetto trace file.

    Returns the number of trace events written. Unparseable lines are
    skipped (a crashed run leaves a truncated last line)."""
    if os.path.isdir(jsonl_path):
        doc = to_chrome_trace(jsonl_path)
        from photon_ml_tpu.utils.atomic import atomic_write_json

        atomic_write_json(out_path, doc)
        return len(doc["traceEvents"])
    records = []
    with open(jsonl_path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    doc = to_chrome_trace(records)
    # atomic write (tools/check.py L008): a crash mid-export must not leave
    # a truncated trace that viewers reject wholesale
    from photon_ml_tpu.utils.atomic import atomic_write_json

    atomic_write_json(out_path, doc)
    return len(doc["traceEvents"])
