"""GAME coordinates: the per-block training strategies driven by coordinate
descent.

Reference analog: photon-api algorithm/{Coordinate,FixedEffectCoordinate,
RandomEffectCoordinate}.scala (SURVEY.md §2.c). A coordinate owns its data
block and knows how to (re)train its sub-model given residual scores from
the other coordinates and how to produce its scores on the training data.

TPU realization:
  - FixedEffectCoordinate: one (optionally mesh-sharded) GLM solve; the
    residuals enter as extra offsets (addScoresToOffsets analog).
  - RandomEffectCoordinate: per geometry bucket, ONE vmapped optimizer call
    solves every entity's independent problem simultaneously; converged
    entities freeze in the masked while-loop. No cross-device communication
    during the solve (SURVEY.md §2.f "per-entity model parallelism").
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional, Protocol

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    RandomEffectBucketModel,
    RandomEffectModel,
)
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.game.random_effect_data import EntityBucket, RandomEffectDataset
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.factory import OptimizerConfig, dispatch_solve

Array = jax.Array


class Coordinate(Protocol):
    name: str

    def initialize_model(self): ...

    def update_model(self, model, residual_scores: Array): ...

    def score(self, model) -> Array: ...


# ---------------------------------------------------------------------------
# Fixed effect
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _fe_solver(config: OptimizerConfig, loss_name: str):
    def run(obj, batch, w0, l1):
        return dispatch_solve(glm_adapter(obj, batch), w0, config, l1)

    return jax.jit(run)


@dataclasses.dataclass
class FixedEffectCoordinate:
    """Global GLM block (the DP strategy; FixedEffectCoordinate.scala:33-167).

    Residual scores arrive as additional offsets; the solve warm-starts from
    the current sub-model. Down-sampling (BinaryClassificationDownSampler
    analog) re-weights kept negatives by 1/rate.
    """

    name: str
    data: GameDataset
    shard_name: str
    loss_name: str
    config: OptimizerConfig
    seed: int = 0
    normalization: Optional["NormalizationContext"] = None

    def __post_init__(self):
        self.config.validate(self.loss_name)
        self._base_batch = self.data.batch_for(self.shard_name)
        # fresh sample per update_model (runWithSampling parity: the reference
        # re-samples on every coordinate update, DistributedOptimizationProblem
        # .scala:113-125); counter salts the rng so updates differ
        self._update_count = 0
        key_cfg = dataclasses.replace(self.config, regularization_weight=0.0)
        self._solver = _fe_solver(key_cfg, self.loss_name)
        norm = self.normalization
        self._obj = make_objective(
            self.loss_name,
            l2_weight=self.config.regularization.l2_weight(
                self.config.regularization_weight
            ),
            factors=None if norm is None else norm.factors,
            shifts=None if norm is None else norm.shifts,
        )
        self._l1 = jnp.float32(
            self.config.regularization.l1_weight(self.config.regularization_weight)
        )

    def _maybe_downsample(self, batch, update_index: int):
        rate = self.config.down_sampling_rate
        if rate >= 1.0:
            return batch
        rng = np.random.default_rng((self.seed, update_index))
        labels = np.asarray(batch.labels)
        weights = np.asarray(batch.weights).copy()
        if "logistic" in self.loss_name or "hinge" in self.loss_name:
            # keep all positives, sample negatives at rate, reweight by 1/rate
            neg = (labels <= 0.5) & (weights > 0)
            drop = neg & (rng.random(len(labels)) >= rate)
            weights[drop] = 0.0
            weights[neg & ~drop] /= rate
        else:
            keep = rng.random(len(labels)) < rate
            weights[~keep] = 0.0
            weights[keep] /= rate
        return dataclasses.replace(batch, weights=jnp.asarray(weights, batch.dtype))

    def initialize_model(self) -> FixedEffectModel:
        d = self._base_batch.num_features
        return FixedEffectModel(
            coefficients=jnp.zeros((d,), self._base_batch.dtype),
            shard_name=self.shard_name,
        )

    def update_model(
        self, model: FixedEffectModel, residual_scores: Optional[Array]
    ) -> FixedEffectModel:
        batch = self._maybe_downsample(self._base_batch, self._update_count)
        self._update_count += 1
        if residual_scores is not None:
            batch = batch.with_offsets(batch.offsets + residual_scores)
        w0 = model.coefficients
        if self.normalization is not None:
            # models live in ORIGINAL space; the solve runs in normalized
            # space (createModel analog, GeneralizedLinearOptimizationProblem)
            w0 = self.normalization.inverse_transform_model_coefficients(w0)
        res = self._solver(self._obj, batch, w0, self._l1)
        w = res.w
        if self.normalization is not None:
            w = self.normalization.transform_model_coefficients(w)
        return dataclasses.replace(model, coefficients=w)

    def score(self, model: FixedEffectModel) -> Array:
        return model.score(self.data)


# ---------------------------------------------------------------------------
# Random effect
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _re_solver(config: OptimizerConfig, loss_name: str):
    def solve_one(obj, batch, w0, l1):
        return dispatch_solve(glm_adapter(obj, batch), w0, config, l1)

    # obj, l1 broadcast; batch leaves and w0 map over the entity axis
    return jax.jit(jax.vmap(solve_one, in_axes=(None, 0, 0, None)))


@lru_cache(maxsize=64)
def _re_scorer():
    def score_bucket(coeffs, bucket_batch):
        # per-entity margins x.w (no offsets) -> [E, R]
        return jax.vmap(lambda w, b: b.dot_rows(w))(coeffs, bucket_batch)

    return jax.jit(score_bucket)


@dataclasses.dataclass
class RandomEffectCoordinate:
    """Per-entity GLM blocks (RandomEffectCoordinate.scala:37-208).

    Each bucket's entities are solved by one vmapped jit-compiled optimizer
    run — the analog of Spark's mapValues-with-local-solver, with identical
    per-entity optimization configs (RandomEffectOptimizationProblem
    semantics). Passive rows are scored through the model's searchsorted
    path.
    """

    name: str
    data: GameDataset
    re_data: RandomEffectDataset
    loss_name: str
    config: OptimizerConfig

    def __post_init__(self):
        self.config.validate(self.loss_name)
        key_cfg = dataclasses.replace(self.config, regularization_weight=0.0)
        self._solver = _re_solver(key_cfg, self.loss_name)
        self._scorer = _re_scorer()
        self._obj = make_objective(
            self.loss_name,
            l2_weight=self.config.regularization.l2_weight(
                self.config.regularization_weight
            ),
        )
        self._l1 = jnp.float32(
            self.config.regularization.l1_weight(self.config.regularization_weight)
        )

    def initialize_model(self) -> RandomEffectModel:
        buckets = tuple(
            RandomEffectBucketModel(
                coefficients=jnp.zeros(
                    (b.num_entities, b.num_local_features), b.values.dtype
                ),
                projection=b.projection,
                entity_codes=b.entity_codes,
            )
            for b in self.re_data.buckets
        )
        return RandomEffectModel(
            id_name=self.re_data.id_name,
            shard_name=self.re_data.shard_name,
            buckets=buckets,
            entity_bucket=self.re_data.entity_bucket,
            entity_pos=self.re_data.entity_pos,
            vocab=self.data.id_columns[self.re_data.id_name].vocab,
        )

    def update_model(
        self, model: RandomEffectModel, residual_scores: Optional[Array]
    ) -> RandomEffectModel:
        new_buckets = []
        for b, bm in zip(self.re_data.buckets, model.buckets):
            bucket = (
                b if residual_scores is None else b.with_extra_offsets(residual_scores)
            )
            res = self._solver(
                self._obj, bucket.entity_batch(), bm.coefficients, self._l1
            )
            new_buckets.append(dataclasses.replace(bm, coefficients=res.w))
        return dataclasses.replace(model, buckets=tuple(new_buckets))

    def score(self, model: RandomEffectModel) -> Array:
        """Scores on the training data: fast bucket path for active rows,
        model searchsorted path for passive rows."""
        n_pad = self.data.shard(self.re_data.shard_name).num_rows
        scores = jnp.zeros((n_pad,), jnp.float32)
        for b, bm in zip(self.re_data.buckets, model.buckets):
            margins = self._scorer(bm.coefficients, b.entity_batch())  # [E, R]
            idx = b.row_index.reshape(-1)
            vals = margins.reshape(-1)
            scores = scores.at[jnp.maximum(idx, 0)].add(
                jnp.where(idx >= 0, vals, 0.0)
            )
        if len(self.re_data.passive_rows):
            passive_scores = model.score(self.data)
            mask = np.zeros(n_pad, bool)
            mask[self.re_data.passive_rows] = True
            scores = jnp.where(jnp.asarray(mask), passive_scores, scores)
        return scores
