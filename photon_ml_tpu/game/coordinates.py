"""GAME coordinates: the per-block training strategies driven by coordinate
descent.

Reference analog: photon-api algorithm/{Coordinate,FixedEffectCoordinate,
RandomEffectCoordinate}.scala (SURVEY.md §2.c). A coordinate owns its data
block and knows how to (re)train its sub-model given residual scores from
the other coordinates and how to produce its scores on the training data.

TPU realization:
  - FixedEffectCoordinate: one (optionally mesh-sharded) GLM solve; the
    residuals enter as extra offsets (addScoresToOffsets analog). Under a
    mesh the FLAT design is committed with
    ``NamedSharding(mesh, P("batch"))`` and the whole optimizer while-loop
    runs in one GSPMD jit (parallel.distributed.gspmd_solve) — no
    shard_map, no host restacking.
  - RandomEffectCoordinate: per geometry bucket, ONE vmapped optimizer call
    solves every entity's independent problem simultaneously; converged
    entities freeze in the masked while-loop. Under a mesh the bucket's
    entity axis is committed with ``entity_sharding(mesh, P("model"))``
    (parallel.sharding) and GSPMD partitions the vmap lanes — no
    cross-device communication during the solve beyond the one-scalar
    convergence test (SURVEY.md §2.f "per-entity model parallelism").
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional, Protocol

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    RandomEffectBucketModel,
    RandomEffectModel,
)
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.game.random_effect_data import RandomEffectDataset
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.ops.tiled import ROWS_PER_TILE, TiledBatch
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.common import BoxConstraints
from photon_ml_tpu.optim.factory import OptimizerConfig, dispatch_solve
from photon_ml_tpu.optim.guard import damped_objective, solve_health
from photon_ml_tpu.parallel.distributed import gspmd_solve
from photon_ml_tpu.parallel import sharding as psharding
from photon_ml_tpu.telemetry.xla import instrumented_jit, record_collective

Array = jax.Array


class Coordinate(Protocol):
    name: str

    def initialize_model(self): ...

    def update_model(self, model, residual_scores: Array): ...

    def score(self, model) -> Array: ...


# ---------------------------------------------------------------------------
# Fixed effect
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _fe_solver(config: OptimizerConfig, loss_name: str):
    def run(obj, batch, w0, l1, constraints):
        return dispatch_solve(
            glm_adapter(obj, batch), w0, config, l1, constraints=constraints
        )

    # multi_shape: one lru-shared solver serves every FE coordinate
    # (and dataset) with this config — distinct feature/row shapes are by
    # design, not a storm
    return instrumented_jit(run, name="fe_solve", multi_shape=True)


@dataclasses.dataclass
class FixedEffectCoordinate:
    """Global GLM block (the DP strategy; FixedEffectCoordinate.scala:33-167).

    Residual scores arrive as additional offsets; the solve warm-starts from
    the current sub-model. Down-sampling (BinaryClassificationDownSampler
    analog) re-weights kept negatives by 1/rate.
    """

    name: str
    data: GameDataset
    shard_name: str
    loss_name: str
    config: OptimizerConfig
    seed: int = 0
    normalization: Optional[NormalizationContext] = None
    mesh: Optional[Mesh] = None  # mesh with a batch/data axis -> gspmd_solve
    layout: str = "auto"  # "auto" | "tiled" | "coo" training layout

    def __post_init__(self):
        self.config.validate(self.loss_name)
        self._base_batch = self.data.batch_for(self.shard_name)
        # "auto": the tiled one-hot-matmul layout is the TPU fast path
        # (~6x over COO gather/scatter, ops/tiled.py); elsewhere pallas
        # falls back to interpret mode, so COO is faster
        if self.layout not in ("auto", "tiled", "coo"):
            raise ValueError(f"unknown layout '{self.layout}'")
        self._use_tiled = self.layout == "tiled" or (
            self.layout == "auto" and jax.default_backend() == "tpu"
        )
        if self._use_tiled:
            self._tiled = TiledBatch.from_batch(self._base_batch)
        # fresh sample per update_model (runWithSampling parity: the reference
        # re-samples on every coordinate update, DistributedOptimizationProblem
        # .scala:113-125); counter salts the rng so updates differ
        self._update_count = 0
        # guarded-solve hooks (optim.guard): extra L2 added to the next
        # update's objective (traced leaf -> no recompile), and the device
        # health scalar of the last solve — computed only when the guard
        # flips health_check on (unguarded fits skip the extra reduces)
        self.extra_l2 = 0.0
        self.health_check = False
        self.last_health = None
        key_cfg = dataclasses.replace(self.config, regularization_weight=0.0)
        self._solver = _fe_solver(key_cfg, self.loss_name)
        self._constraints = self.config.build_box_constraints(
            self._base_batch.num_features
        )
        norm = self.normalization
        if self._constraints is not None and norm is not None:
            # bounds are declared in ORIGINAL space; the solve runs in
            # normalized space where w_original = w' * factor, so enforce
            # w' in [lo/factor, hi/factor]. With shifts, the intercept's
            # original value additionally absorbs -w.shift at
            # back-transform time, so an intercept bound cannot be
            # enforced inside the solve — reject it.
            f = norm.factors
            if f is not None:
                self._constraints = type(self._constraints)(
                    lower=self._constraints.lower / f,
                    upper=self._constraints.upper / f,
                )
            if norm.shifts is not None and norm.intercept_index is not None:
                ii = norm.intercept_index
                if np.isfinite(
                    float(self._constraints.lower[ii])
                ) or np.isfinite(float(self._constraints.upper[ii])):
                    raise ValueError(
                        "a box constraint on the intercept cannot be "
                        "enforced under shift normalization (the intercept "
                        "absorbs -w.shift at back-transform)"
                    )
        self._obj = make_objective(
            self.loss_name,
            l2_weight=self.config.regularization.l2_weight(
                self.config.regularization_weight
            ),
            factors=None if norm is None else norm.factors,
            shifts=None if norm is None else norm.shifts,
        )
        self._l1 = jnp.float32(
            self.config.regularization.l1_weight(self.config.regularization_weight)
        )
        if self.mesh is not None and psharding.data_axis(self.mesh) is None:
            # an entity-only mesh has no row axis to data-parallel over;
            # the FE block runs single-device (its RE siblings still shard)
            self.mesh = None
        if self.mesh is not None:
            # GSPMD path: the FLAT design (tiles or COO slots) is committed
            # with NamedSharding(mesh, P(batch)) ONCE; per-update offsets
            # and weights are re-placed with the same row sharding
            # (_place_rows) so residual updates and fresh down-samples
            # never rebuild the nnz arrays
            self._axis = psharding.data_axis(self.mesh)
            self._n_shards = psharding.axis_size(self.mesh, self._axis)
            self._row_sharding = psharding.batch_sharding(self.mesh, self._axis)
            self._solve_batch = psharding.place_batch(
                self._tiled if self._use_tiled else self._base_batch,
                self.mesh,
                self._axis,
            )
        elif not self._use_tiled:
            # single-device COO solve path: upload the design ONCE; per-row
            # updates (offsets/weights) are swapped onto this device copy
            self._solve_batch = self._base_batch.device()

    def _downsampled_weights(self, batch, update_index: int):
        rate = self.config.down_sampling_rate
        if rate >= 1.0:
            return batch.weights
        rng = np.random.default_rng((self.seed, update_index))
        labels = np.asarray(batch.labels)
        weights = np.asarray(batch.weights).copy()
        if "logistic" in self.loss_name or "hinge" in self.loss_name:
            # keep all positives, sample negatives at rate, reweight by 1/rate
            neg = (labels <= 0.5) & (weights > 0)
            drop = neg & (rng.random(len(labels)) >= rate)
            weights[drop] = 0.0
            weights[neg & ~drop] /= rate
        else:
            keep = rng.random(len(labels)) < rate
            weights[~keep] = 0.0
            weights[keep] /= rate
        return jnp.asarray(weights, batch.dtype)

    def _maybe_downsample(self, batch, update_index: int):
        if self.config.down_sampling_rate >= 1.0:
            return batch
        return dataclasses.replace(
            batch, weights=self._downsampled_weights(batch, update_index)
        )

    def _place_rows(self, per_row: Array) -> Array:
        """Pad a global [n_pad] per-row array to the sharded solve batch's
        row count (tiled: into its [T, 1, 128] grid) and commit it with the
        batch-axis sharding, matching the resident design's placement."""
        a = jnp.asarray(per_row, jnp.float32)
        if self._use_tiled:
            tiles = self._solve_batch.num_tiles
            a = jnp.pad(a, (0, tiles * ROWS_PER_TILE - a.shape[0]))
            a = a.reshape(tiles, 1, ROWS_PER_TILE)
        else:
            a = jnp.pad(a, (0, self._solve_batch.num_rows - a.shape[0]))
        return jax.device_put(a, self._row_sharding)

    def _tiled_rows(self, per_row: Array, reshape: bool = True) -> Array:
        """Pad a global [n_pad] per-row array to the tiled row count
        (multiple of 128), optionally into the [T, 1, 128] grid."""
        a = jnp.asarray(per_row, jnp.float32)
        a = jnp.pad(a, (0, self._tiled.num_rows - a.shape[0]))
        if reshape:
            a = a.reshape(self._tiled.num_tiles, 1, ROWS_PER_TILE)
        return a

    def initialize_model(self) -> FixedEffectModel:
        d = self._base_batch.num_features
        return FixedEffectModel(
            coefficients=jnp.zeros((d,), self._base_batch.dtype),
            shard_name=self.shard_name,
        )

    def update_model(
        self, model: FixedEffectModel, residual_scores: Optional[Array]
    ) -> FixedEffectModel:
        w0 = model.coefficients
        norm = self.normalization
        if norm is not None:
            # models live in ORIGINAL space; the solve runs in normalized
            # space (createModel analog, GeneralizedLinearOptimizationProblem)
            w0 = norm.inverse_transform_model_coefficients(w0)
        update_index = self._update_count
        self._update_count += 1
        # damped retry (optim.guard): l2_weight is a traced leaf, so the
        # compiled solver is reused unchanged
        obj = damped_objective(self._obj, self.extra_l2)
        off_field = "offsets3" if self._use_tiled else "offsets"
        wgt_field = "weights3" if self._use_tiled else "weights"
        if self.mesh is not None:
            # DP path (FixedEffectCoordinate.scala:136-147): rows committed
            # P(batch), whole while-loop in ONE GSPMD jit, grads psum'd by
            # the compiler. Only changed per-row arrays are re-placed.
            batch = self._solve_batch
            if residual_scores is not None:
                batch = dataclasses.replace(
                    batch,
                    **{off_field: self._place_rows(
                        self._base_batch.offsets + residual_scores
                    )},
                )
            if self.config.down_sampling_rate < 1.0:
                batch = dataclasses.replace(
                    batch,
                    **{wgt_field: self._place_rows(
                        self._downsampled_weights(self._base_batch, update_index)
                    )},
                )
            res = gspmd_solve(
                self.loss_name,
                batch,
                self.config,
                w0,
                self.mesh,
                axis=self._axis,
                constraints=self._constraints,
                factors=None if norm is None else norm.factors,
                shifts=None if norm is None else norm.shifts,
                extra_l2=self.extra_l2,
            )
        elif self._use_tiled:
            batch = self._tiled
            if self.config.down_sampling_rate < 1.0:
                batch = dataclasses.replace(
                    batch,
                    weights3=self._tiled_rows(
                        self._downsampled_weights(self._base_batch, update_index)
                    ),
                )
            if residual_scores is not None:
                batch = batch.with_offsets(
                    self._tiled_rows(
                        self._base_batch.offsets + residual_scores,
                        reshape=False,
                    )
                )
            res = self._solver(obj, batch, w0, self._l1, self._constraints)
        else:
            batch = self._solve_batch
            if self.config.down_sampling_rate < 1.0:
                # weights are drawn from the HOST base batch (transfer-free
                # reads); only the fresh [n] weight vector is uploaded
                batch = dataclasses.replace(
                    batch,
                    weights=self._downsampled_weights(
                        self._base_batch, update_index
                    ),
                )
            if residual_scores is not None:
                batch = batch.with_offsets(
                    self._base_batch.offsets + residual_scores
                )
            res = self._solver(obj, batch, w0, self._l1, self._constraints)
        w = res.w
        from photon_ml_tpu.optim.trackers import FixedEffectOptimizationTracker

        self.last_tracker = FixedEffectOptimizationTracker.from_result(res)
        if norm is not None:
            w = norm.transform_model_coefficients(w)
        self.last_health = solve_health(res, w) if self.health_check else None
        return dataclasses.replace(model, coefficients=w)

    def score(self, model: FixedEffectModel) -> Array:
        if self._use_tiled:
            # the solve layout already holds the design in HBM — score
            # through it instead of uploading a second (COO) copy
            w = model.coefficients
            z = self._tiled.dot_rows(w.astype(jnp.float32))
            n_pad = self.data.shard(self.shard_name).num_rows
            if z.shape[0] >= n_pad:
                return z[:n_pad]
            return jnp.pad(z, (0, n_pad - z.shape[0]))
        return model.score(self.data)


# ---------------------------------------------------------------------------
# Random effect
# ---------------------------------------------------------------------------

# DistributedOptimizationProblem.computeVariances adds this to the Hessian
# diagonal before inverting (MathConst.HIGH_PRECISION_TOLERANCE_THRESHOLD)
_VARIANCE_EPS = 1e-12


def _make_solve_one(config: OptimizerConfig, compute_variances: bool):
    """One entity's solve (+optional Hessian-diagonal-inverse variances, the
    computeVariances path of SingleNodeOptimizationProblem.scala:57-88).
    Returns ``(SolveResult, variances-or-None)``."""

    def solve_one(obj, batch, w0, l1, constraints):
        res = dispatch_solve(
            glm_adapter(obj, batch), w0, config, l1, constraints=constraints
        )
        if not compute_variances:
            return res, None
        var = 1.0 / (obj.hessian_diagonal(res.w, batch) + _VARIANCE_EPS)
        return res, var

    return solve_one


def _adapt_solve_one(config, compute_variances: bool, packed: bool):
    """Per-entity solve body; ``packed`` reassembles a DenseBatch from the
    flat packed design inside jit (_packed_dense_batch)."""
    base_one = _make_solve_one(config, compute_variances)
    if not packed:
        return base_one

    def solve_one(obj, batch, w0, l1, constraints):
        return base_one(obj, _packed_dense_batch(batch, w0), w0, l1,
                        constraints)

    return solve_one


@lru_cache(maxsize=64)
def _re_solver(
    config: OptimizerConfig,
    loss_name: str,
    constrained: bool | str = False,
    compute_variances: bool = False,
    packed: bool = False,
):
    solve_one = _adapt_solve_one(config, compute_variances, packed)
    # obj, l1 broadcast; batch leaves, w0 (and per-entity constraint boxes,
    # when present) map over the entity axis. constrained="shared" keeps one
    # [K] box broadcast to every entity (the streaming table's dense local
    # space) instead of materializing [E, K] bounds.
    c_axis = 0 if constrained is True else None
    # multi_shape: each geometry bucket (entity count, rows, K) is its
    # own signature by construction
    return instrumented_jit(
        jax.vmap(solve_one, in_axes=(None, 0, 0, None, c_axis)),
        name="re_solve_dense" if packed else "re_solve",
        multi_shape=True,
    )


def place_entity_solve(
    mesh: Mesh,
    axis: Optional[str],
    batch,
    w0: Array,
    constraints: Optional[BoxConstraints] = None,
    shared_constraints: bool = False,
):
    """Commit one bucket/chunk solve's inputs for GSPMD entity sharding:
    batch leaves and w0 get ``entity_sharding(mesh, axis)`` on their
    leading [E] dim (already padded to the axis size), constraint boxes
    get the same placement when per-entity ([E, K]) or replication when
    shared ([K], the streaming dense space). The plain vmapped ``_re_solver``
    then runs under one jit with the lanes partitioned by the compiler —
    the EP-like strategy of SURVEY.md §2.f / RandomEffectCoordinate
    .scala:101-130, with no hand-rolled shard_map."""
    eshard = psharding.entity_sharding(mesh, axis)
    batch = jax.tree.map(lambda x: jax.device_put(x, eshard), batch)
    w0 = jax.device_put(w0, eshard)
    if constraints is not None:
        put = (
            psharding.place_replicated(constraints, mesh)
            if shared_constraints
            else jax.tree.map(lambda x: jax.device_put(x, eshard), constraints)
        )
        constraints = put
    return batch, w0, constraints


def record_entity_solve_comms(label: str, mesh: Mesh, axis: str,
                              iterations: int) -> None:
    """Static comms estimate for one entity-sharded vmapped solve: the
    per-entity problems are independent — the only cross-device traffic
    the masked while-loop needs is its one-scalar convergence test
    (all-reduce of the active mask) per iteration."""
    record_collective(
        label, "psum", int(mesh.shape[axis]), 4,
        count=max(int(iterations), 1),
    )


def _pad_entities(batch: SparseBatch, w0: Array, total: int):
    """Pad the leading entity axis to ``total`` with all-zero problems
    (weight 0 everywhere -> the padded solves converge immediately)."""
    n = w0.shape[0]
    if total == n:
        return batch, w0

    def padf(x):
        pad = jnp.zeros((total - n,) + x.shape[1:], x.dtype)
        return jnp.concatenate([x, pad], axis=0)

    return jax.tree.map(padf, batch), padf(w0)


def _pad_constraints(cons: Optional[BoxConstraints], total: int):
    """Pad per-entity constraint boxes to ``total`` entities with unbounded
    rows (padded problems are all-zero; their iterates must stay free)."""
    if cons is None or cons.lower.shape[0] == total:
        return cons

    def padv(x, fill):
        n = x.shape[0]
        pad = jnp.full((total - n,) + x.shape[1:], fill, x.dtype)
        return jnp.concatenate([x, pad], axis=0)

    return BoxConstraints(
        lower=padv(cons.lower, -jnp.inf), upper=padv(cons.upper, jnp.inf)
    )


@lru_cache(maxsize=64)
def _re_scorer():
    def score_bucket(coeffs, bucket_batch):
        # per-entity margins x.w (no offsets) -> [E, R]
        return jax.vmap(lambda w, b: b.dot_rows(w))(coeffs, bucket_batch)

    return instrumented_jit(score_bucket, name="re_score", multi_shape=True)


@lru_cache(maxsize=8)
def _re_dense_scorer():
    def score(coeffs, x_flat):
        E, K = coeffs.shape
        x = x_flat.reshape(E, -1, K)
        return jnp.einsum("erk,ek->er", x, coeffs)

    return instrumented_jit(score, name="re_score_dense", multi_shape=True)


def _packed_dense_batch(packed, w0):
    """Reassemble a DenseBatch from the PACKED per-entity design INSIDE
    jit: the design is stored flat [R*K] per entity (TPU pads a resident
    [E, R, K] array's K lanes to 128 — 128/K-fold HBM bloat; the flat
    layout is padding-free and the in-jit reshape is a transient)."""
    from photon_ml_tpu.ops.dense import DenseBatch

    x_flat, labels, offsets, weights = packed
    return DenseBatch(
        x=x_flat.reshape(-1, w0.shape[0]),
        labels=labels,
        offsets=offsets,
        weights=weights,
    )


# Route a bucket's per-entity solves through the DENSE local-design layout
# ([E, R, K] batched matmuls on the MXU — the layout the 1B streaming path
# uses) when the densified design is at most this factor of the padded-COO
# footprint; the COO gather/scatter path stays for high-dim sparse locals.
_DENSE_BYTES_FACTOR = 3.0


def _bucket_dense_design(b: EntityBucket) -> Optional[np.ndarray]:
    """Host-side densified design for a bucket as PACKED [E, R*K] rows
    (row-major per entity), or None when the COO layout is the better
    trade (K large / very sparse locals). Packed because a resident
    [E, R, K] device array pads its K lanes to 128 (128/K-fold HBM
    bloat); solvers reshape inside jit (_packed_dense_batch)."""
    E, R, K = b.num_entities, b.rows_per_entity, b.num_local_features
    nz = b.values.shape[1]
    dense_bytes = E * R * K * 4
    coo_bytes = E * nz * 12
    if dense_bytes > max(64 << 20, _DENSE_BYTES_FACTOR * coo_bytes):
        return None
    vals = np.asarray(b.values)
    rows = np.asarray(b.rows, np.int64)
    cols = np.asarray(b.cols, np.int64)
    e_idx = np.broadcast_to(
        np.arange(E, dtype=np.int64)[:, None] * (R * K), rows.shape
    )
    flat = (e_idx + rows * K + cols).ravel()
    # padded nnz carry value 0 -> accumulate harmlessly (bincount is the
    # fast vectorized scatter-add; np.add.at is unbuffered/slow)
    x = np.bincount(
        flat, weights=vals.ravel(), minlength=E * R * K
    ).astype(np.float32)
    return x.reshape(E, R * K)


@dataclasses.dataclass
class RandomEffectCoordinate:
    """Per-entity GLM blocks (RandomEffectCoordinate.scala:37-208).

    Each bucket's entities are solved by one vmapped jit-compiled optimizer
    run — the analog of Spark's mapValues-with-local-solver, with identical
    per-entity optimization configs (RandomEffectOptimizationProblem
    semantics). Passive rows are scored through the model's searchsorted
    path.
    """

    name: str
    data: GameDataset
    re_data: RandomEffectDataset
    loss_name: str
    config: OptimizerConfig
    mesh: Optional[Mesh] = None  # mesh with a model/entity axis -> GSPMD
    # entity-sharded bucket solves (place_entity_solve)
    compute_variances: bool = False  # per-coefficient Hessian-diag inverse

    def __post_init__(self):
        from photon_ml_tpu.ops.losses import get_loss

        self.config.validate(self.loss_name)
        if self.compute_variances and not get_loss(self.loss_name).has_hessian:
            raise ValueError(
                "coefficient variances need a twice-differentiable loss; "
                f"'{self.loss_name}' is not"
            )
        # dense [E, R, K] designs for small-K buckets: batched-matmul MXU
        # solves (the streaming-path layout) instead of vmapped COO
        # gather/scatter — measured ~10x on the GLMix RE coordinate; the
        # device bucket copies skip the COO arrays where dense is active
        self._dense_x = self.re_data.dense_designs()
        self._buckets = self.re_data.device_buckets_for_dense()
        # Box constraints are declared against GLOBAL feature ids
        # (OptimizerConfig constraintMap); each entity's local space is an
        # index-map renumbering (local k <-> global projection[e, k]), so the
        # global boxes gather straight through the projection into per-entity
        # [E, K] bounds — the reference threads the same map into every
        # per-entity problem (SingleNodeOptimizationProblem.scala:124-139).
        self._bucket_constraints: list = [None] * len(self.re_data.buckets)
        constrained = bool(self.config.box_constraints)
        if constrained:
            lower_g, upper_g = self.config.dense_box_bounds(
                self.re_data.num_global_features, sentinel=True
            )
            for i, b in enumerate(self.re_data.buckets):
                proj = np.asarray(b.projection)
                self._bucket_constraints[i] = BoxConstraints(
                    lower=jnp.asarray(lower_g[proj]),
                    upper=jnp.asarray(upper_g[proj]),
                )
        key_cfg = dataclasses.replace(self.config, regularization_weight=0.0)
        if self.mesh is not None:
            # GSPMD entity sharding: the same vmapped solvers serve the
            # mesh path, with inputs committed P(model) per bucket
            self._axis = psharding.model_axis(self.mesh)
            if self._axis is None:
                self.mesh = None  # batch-only mesh: no entity axis to use
        self._solver = _re_solver(
            key_cfg, self.loss_name, constrained, self.compute_variances
        )
        self._dense_solver = _re_solver(
            key_cfg, self.loss_name, constrained, self.compute_variances,
            packed=True,
        )
        self._scorer = _re_scorer()
        self._obj = make_objective(
            self.loss_name,
            l2_weight=self.config.regularization.l2_weight(
                self.config.regularization_weight
            ),
        )
        self._l1 = jnp.float32(
            self.config.regularization.l1_weight(self.config.regularization_weight)
        )
        # guarded-solve hooks (optim.guard); health reduces only when the
        # guard flips health_check on
        self.extra_l2 = 0.0
        self.health_check = False
        self.last_health = None

    def initialize_model(self) -> RandomEffectModel:
        # dtype from the HOST buckets: dense-routed device buckets carry
        # f32 placeholder stubs in `values`, not the dataset's dtype
        buckets = tuple(
            RandomEffectBucketModel(
                coefficients=jnp.zeros(
                    (b.num_entities, b.num_local_features), hb.values.dtype
                ),
                projection=b.projection,
                entity_codes=b.entity_codes,
            )
            for b, hb in zip(self._buckets, self.re_data.buckets)
        )
        return RandomEffectModel(
            id_name=self.re_data.id_name,
            shard_name=self.re_data.shard_name,
            buckets=buckets,
            entity_bucket=self.re_data.entity_bucket,
            entity_pos=self.re_data.entity_pos,
            vocab=self.data.id_columns[self.re_data.id_name].vocab,
        )

    def update_model(
        self, model: RandomEffectModel, residual_scores: Optional[Array]
    ) -> RandomEffectModel:
        from photon_ml_tpu.optim.trackers import RandomEffectOptimizationTracker

        new_buckets = []
        tracker_its = []
        tracker_reasons = []
        tracker_vals = []
        healths = []
        obj = damped_objective(self._obj, self.extra_l2)
        n_dev = (
            0 if self.mesh is None
            else psharding.axis_size(self.mesh, self._axis)
        )
        for i, (b, bm) in enumerate(zip(self._buckets, model.buckets)):
            bucket = (
                b if residual_scores is None else b.with_extra_offsets(residual_scores)
            )
            dense = self._dense_x[i] is not None
            if dense:
                # packed flat design + per-row arrays; reshaped to
                # [E, R, K] INSIDE the solver jit (_packed_dense_batch)
                bb = (
                    self._dense_x[i],
                    bucket.labels,
                    bucket.offsets,
                    bucket.weights,
                )
            else:
                bb = bucket.entity_batch()
            w0 = bm.coefficients
            cons = self._bucket_constraints[i]
            solver = self._dense_solver if dense else self._solver
            if self.mesh is None:
                res, var = solver(obj, bb, w0, self._l1, cons)
                w = res.w
            else:
                num_e = w0.shape[0]
                total = -(-num_e // n_dev) * n_dev
                bb_p, w0_p = _pad_entities(bb, w0, total)
                cons_p = _pad_constraints(cons, total)
                bb_p, w0_p, cons_p = place_entity_solve(
                    self.mesh, self._axis, bb_p, w0_p, cons_p
                )
                record_entity_solve_comms(
                    "re_solve", self.mesh, self._axis,
                    self.config.max_iterations,
                )
                res, var = solver(obj, bb_p, w0_p, self._l1, cons_p)
                w = res.w[:num_e]
                if var is not None:
                    var = var[:num_e]
            # keep only the tiny telemetry vectors (the full SolveResult
            # frees per bucket); stay ON DEVICE — each host fetch costs a
            # ~100ms tunnel round trip, so both arrays cross in ONE
            # np.asarray each after a device-side concat
            n_real = int(w0.shape[0])
            tracker_its.append(res.iterations[:n_real])
            tracker_reasons.append(res.reason[:n_real])
            tracker_vals.append(res.value[:n_real])
            if self.health_check:
                # mesh-padded entities are all-zero problems (value 0 at
                # w=0), so the full padded res passes the reduce harmlessly
                healths.append(solve_health(res, res.w))
            new_buckets.append(
                dataclasses.replace(bm, coefficients=w, variances=var)
            )
        self.last_health = (
            (jnp.all(jnp.stack(healths)) if healths else jnp.bool_(True))
            if self.health_check
            else None
        )
        self.last_tracker = RandomEffectOptimizationTracker.from_device_parts(
            tracker_its, tracker_reasons, tracker_vals
        )
        return dataclasses.replace(model, buckets=tuple(new_buckets))

    def score(self, model: RandomEffectModel) -> Array:
        """Scores on the training data: fast bucket path for active rows,
        model searchsorted path for passive rows."""
        n_pad = self.data.shard(self.re_data.shard_name).num_rows
        scores = jnp.zeros((n_pad,), jnp.float32)
        for i, (b, bm) in enumerate(zip(self._buckets, model.buckets)):
            if self._dense_x[i] is not None:
                margins = _re_dense_scorer()(bm.coefficients, self._dense_x[i])
            else:
                margins = self._scorer(bm.coefficients, b.entity_batch())  # [E, R]
            idx = b.row_index.reshape(-1)
            vals = margins.reshape(-1)
            scores = scores.at[jnp.maximum(idx, 0)].add(
                jnp.where(idx >= 0, vals, 0.0)
            )
        if len(self.re_data.passive_rows):
            passive_scores = model.score(self.data)
            mask = np.zeros(n_pad, bool)
            mask[self.re_data.passive_rows] = True
            scores = jnp.where(jnp.asarray(mask), passive_scores, scores)
        return scores
