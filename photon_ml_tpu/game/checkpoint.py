"""Checkpoint/resume for coordinate descent: atomic step snapshots,
corrupt-checkpoint fallback, and graceful-preemption plumbing.

The reference inherits fault tolerance from Spark (RDD lineage re-executes
lost partitions; the driver survives executor loss). The TPU port replaced
that substrate with long-lived device arrays, so a preemption or OOM used
to discard the whole GAME fit. This module restores durability at the
``(iteration, coordinate)`` granularity:

Layout (one directory per completed step)::

    <checkpoint_dir>/
      step-00000007/
        manifest.json        step cursor, best metric, JSON-safe history
        model/               full GAME model (model_store savers)
        best/                best-so-far model (present iff validation ran)

Atomicity: each checkpoint is assembled in a ``.tmp-step-*`` sibling and
``os.rename``d into place (readers never see a partial directory); the
manifest is written last inside the tmp dir, so a directory missing its
manifest is by definition incomplete. ``restore`` walks step directories
newest-first and falls back past corrupt or partial ones (counted in the
``checkpoint.corrupt`` telemetry counter). Retention keeps the newest
``keep_last`` checkpoints.

Graceful preemption: :class:`GracefulStop` turns SIGTERM/SIGINT into a
"finish this step, write a final checkpoint, raise
:class:`TrainingInterrupted`" request — the train CLI installs it so a
preempted run restarts with ``--resume`` instead of from scratch.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import shutil
import signal
from typing import Optional

from photon_ml_tpu import telemetry
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.utils.atomic import atomic_write_json, fsync_dir

logger = logging.getLogger("photon_ml_tpu.game.checkpoint")

_MANIFEST_FILE = "manifest.json"
_FORMAT_VERSION = 1
_STEP_RE = re.compile(r"^step-(\d{8})$")


class CheckpointError(RuntimeError):
    """A checkpoint directory is unusable (corrupt, partial, or written by
    an incompatible run)."""


class TrainingInterrupted(RuntimeError):
    """Raised after a graceful-stop request once the final checkpoint is on
    disk; carries where training stopped so drivers can report it."""

    def __init__(self, step: int, checkpoint_path: Optional[str]):
        super().__init__(
            f"training interrupted after step {step}"
            + (f"; checkpoint at {checkpoint_path}" if checkpoint_path else "")
        )
        self.step = step
        self.checkpoint_path = checkpoint_path


@dataclasses.dataclass(frozen=True)
class CheckpointSpec:
    """Checkpointing policy for a fit.

    ``every`` saves after every N completed ``(iteration, coordinate)``
    steps (a stop request always forces a final save). ``resume=False``
    is a FRESH fit into the directory: existing step checkpoints are
    cleared at manager construction (otherwise a stale run's
    higher-numbered steps would outlive this run's through retention and
    hijack a later resume).
    """

    directory: str
    every: int = 1
    keep_last: int = 3
    resume: bool = True

    def __post_init__(self):
        if self.every < 1:
            raise ValueError("checkpoint every must be >= 1")
        if self.keep_last < 1:
            raise ValueError("checkpoint keep_last must be >= 1")


@dataclasses.dataclass
class CheckpointState:
    """Everything coordinate descent needs to continue a fit: the step
    cursor (last COMPLETED global step), the live per-coordinate models,
    the best-model tracking, the JSON-safe step history, and the guard's
    rollback bookkeeping (so a resumed fit does not re-attempt solves a
    frozen coordinate already proved divergent)."""

    step: int
    model: GameModel
    best_model: Optional[GameModel]
    best_metric: Optional[float]
    history: list
    frozen: list = dataclasses.field(default_factory=list)
    consecutive_rollbacks: Optional[dict] = None


def _step_dirname(step: int) -> str:
    return f"step-{step:08d}"


class CheckpointManager:
    """Atomic save / newest-valid restore / retention over one directory."""

    def __init__(self, spec: CheckpointSpec):
        self.spec = spec
        os.makedirs(spec.directory, exist_ok=True)
        if not spec.resume:
            stale = self._step_dirs()
            if stale:
                logger.warning(
                    "resume=False: clearing %d existing checkpoint(s) "
                    "under %s for a fresh fit", len(stale), spec.directory,
                )
            for _step, path in stale:
                shutil.rmtree(path, ignore_errors=True)

    # -- save ----------------------------------------------------------------

    def should_save(self, step: int) -> bool:
        return (step + 1) % self.spec.every == 0

    def save(self, state: CheckpointState) -> str:
        """Persist ``state`` as ``step-<step>``; returns the final path."""
        from photon_ml_tpu.data.model_store import save_game_model

        final = os.path.join(self.spec.directory, _step_dirname(state.step))
        tmp = os.path.join(
            self.spec.directory, f".tmp-{_step_dirname(state.step)}"
        )
        with telemetry.span("checkpoint:save", step=state.step):
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            save_game_model(state.model, os.path.join(tmp, "model"))
            if state.best_model is not None:
                save_game_model(state.best_model, os.path.join(tmp, "best"))
            # the manifest lands LAST: its presence certifies completeness
            atomic_write_json(
                os.path.join(tmp, _MANIFEST_FILE),
                {
                    "format_version": _FORMAT_VERSION,
                    "step": state.step,
                    "coordinate_order": list(state.model.models),
                    "best_metric": state.best_metric,
                    "has_best": state.best_model is not None,
                    "history": state.history,
                    "frozen": list(state.frozen),
                    "consecutive_rollbacks": state.consecutive_rollbacks or {},
                },
                indent=2,
                sort_keys=True,
            )
            if os.path.exists(final):  # re-save of a step (resume overlap)
                shutil.rmtree(final)
            os.rename(tmp, final)
            fsync_dir(self.spec.directory)
        telemetry.counter("checkpoint.saves").inc()
        telemetry.gauge("checkpoint.last_step").set(state.step)
        # tracer-timebase save stamp: the heartbeat reports checkpoint AGE
        # (now - this) so a wedged saver is visible before the run dies
        telemetry.gauge("checkpoint.last_save_ts").set(
            telemetry.trace.TRACER.now()
        )
        self._apply_retention()
        return final

    def _apply_retention(self) -> None:
        steps = self._step_dirs()
        for step, path in steps[: -self.spec.keep_last]:
            shutil.rmtree(path, ignore_errors=True)
        for name in os.listdir(self.spec.directory):
            # abandoned tmp dirs from a crashed save
            if name.startswith(".tmp-step-"):
                shutil.rmtree(
                    os.path.join(self.spec.directory, name),
                    ignore_errors=True,
                )

    # -- restore -------------------------------------------------------------

    def _step_dirs(self) -> list[tuple[int, str]]:
        """(step, path) for every step directory, oldest first."""
        out = []
        for name in os.listdir(self.spec.directory):
            m = _STEP_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.spec.directory, name)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self._step_dirs()
        return steps[-1][0] if steps else None

    def _load(self, path: str) -> CheckpointState:
        from photon_ml_tpu.data.model_store import load_game_model

        manifest_path = os.path.join(path, _MANIFEST_FILE)
        try:
            import json

            with open(manifest_path) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            raise CheckpointError(
                f"{path}: incomplete checkpoint (no manifest)"
            ) from None
        except ValueError as e:
            raise CheckpointError(
                f"{manifest_path}: corrupt manifest ({e})"
            ) from None
        if manifest.get("format_version") != _FORMAT_VERSION:
            raise CheckpointError(
                f"{manifest_path}: unsupported format_version "
                f"{manifest.get('format_version')!r}"
            )
        model = load_game_model(os.path.join(path, "model"))
        best_model = None
        if manifest.get("has_best"):
            best_model = load_game_model(os.path.join(path, "best"))
        return CheckpointState(
            step=int(manifest["step"]),
            model=model,
            best_model=best_model,
            best_metric=manifest.get("best_metric"),
            history=list(manifest.get("history", ())),
            frozen=list(manifest.get("frozen", ())),
            consecutive_rollbacks=dict(
                manifest.get("consecutive_rollbacks") or {}
            ),
        )

    def restore(self) -> Optional[CheckpointState]:
        """Newest VALID checkpoint, or None. Corrupt/partial checkpoints
        (truncated npz, missing manifest, bad metadata) are skipped with a
        warning and counted, falling back to the next older one."""
        if not self.spec.resume:
            return None
        with telemetry.span("checkpoint:restore"):
            for step, path in reversed(self._step_dirs()):
                try:
                    state = self._load(path)
                except (CheckpointError, ValueError, OSError) as e:
                    # ModelLoadError is a ValueError; OSError covers a
                    # half-deleted directory
                    telemetry.counter("checkpoint.corrupt").inc()
                    logger.warning(
                        "skipping corrupt checkpoint %s: %s", path, e
                    )
                    continue
                telemetry.counter("checkpoint.restores").inc()
                logger.info("resuming from checkpoint %s (step %d)",
                            path, state.step)
                return state
        return None


class GracefulStop:
    """SIGTERM/SIGINT -> cooperative stop flag (the preemption handshake).

    The first signal requests a graceful stop: the training loop finishes
    its current step, writes a final checkpoint, and raises
    :class:`TrainingInterrupted`. A second signal restores the previous
    handler's behavior by re-raising KeyboardInterrupt immediately (an
    operator mashing Ctrl-C still wins).
    """

    def __init__(self):
        self.requested = False
        self.signum: Optional[int] = None
        self._installed = False

    def install(self, signums=(signal.SIGTERM, signal.SIGINT)) -> "GracefulStop":
        for s in signums:
            signal.signal(s, self._handle)
        self._installed = True
        return self

    def _handle(self, signum, frame):
        if self.requested:
            raise KeyboardInterrupt
        self.requested = True
        self.signum = signum
        logger.warning(
            "received signal %d: finishing current step, then writing a "
            "final checkpoint and exiting", signum,
        )

    def __call__(self) -> bool:
        """Stop-predicate form, passed as ``should_stop=``."""
        return self.requested


# ---------------------------------------------------------------------------
# streamed-fit checkpointing (chunk-boundary granularity)
# ---------------------------------------------------------------------------

_CHUNK_RE = re.compile(r"^chunk-(\d{8})$")


@dataclasses.dataclass
class StreamCheckpointState:
    """Everything a streamed random-effect fit needs to continue: the
    NEXT chunk index to solve (the deterministic ingest planner replays
    the same stream from that boundary) and the coefficient table rows
    solved so far."""

    next_chunk: int
    coefficients: "object"  # np.ndarray [N, K]
    variances: Optional["object"] = None


class StreamingCheckpointManager:
    """Atomic chunk-boundary checkpoints for streamed table fits.

    Same durability contract as :class:`CheckpointManager` (assemble in a
    ``.tmp-`` sibling, manifest written last, ``os.rename`` into place,
    newest-valid restore past corrupt directories, keep-last-K
    retention), but the unit of progress is a CHUNK of the deterministic
    ingest stream, not an (iteration, coordinate) step — resume replays
    from ``next_chunk`` and re-decodes exactly the rows the interrupted
    run would have seen, in the same order (ingest.planner's determinism
    contract).
    """

    def __init__(self, spec: CheckpointSpec):
        import numpy as np  # local: keep module import light

        self._np = np
        self.spec = spec
        os.makedirs(spec.directory, exist_ok=True)
        if not spec.resume:
            stale = self._chunk_dirs()
            if stale:
                logger.warning(
                    "resume=False: clearing %d existing streaming "
                    "checkpoint(s) under %s", len(stale), spec.directory,
                )
            for _c, path in stale:
                shutil.rmtree(path, ignore_errors=True)

    def should_save(self, chunk_index: int) -> bool:
        return (chunk_index + 1) % self.spec.every == 0

    def save(self, state: StreamCheckpointState) -> str:
        np = self._np
        name = f"chunk-{state.next_chunk:08d}"
        final = os.path.join(self.spec.directory, name)
        tmp = os.path.join(self.spec.directory, f".tmp-{name}")
        with telemetry.span("checkpoint:save", next_chunk=state.next_chunk):
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            coeffs = np.asarray(state.coefficients)
            np.save(os.path.join(tmp, "coefficients.npy"), coeffs)
            if state.variances is not None:
                np.save(
                    os.path.join(tmp, "variances.npy"),
                    np.asarray(state.variances),
                )
            # manifest LAST: its presence certifies the directory complete
            atomic_write_json(
                os.path.join(tmp, _MANIFEST_FILE),
                {
                    "format_version": _FORMAT_VERSION,
                    "kind": "streaming",
                    "next_chunk": int(state.next_chunk),
                    "num_entities": int(coeffs.shape[0]),
                    "dim": int(coeffs.shape[1]),
                    "has_variances": state.variances is not None,
                },
                indent=2,
                sort_keys=True,
            )
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            fsync_dir(self.spec.directory)
        telemetry.counter("checkpoint.saves").inc()
        telemetry.gauge("checkpoint.last_save_ts").set(
            telemetry.trace.TRACER.now()
        )
        self._apply_retention()
        return final

    def _apply_retention(self) -> None:
        dirs = self._chunk_dirs()
        for _c, path in dirs[: -self.spec.keep_last]:
            shutil.rmtree(path, ignore_errors=True)
        for name in os.listdir(self.spec.directory):
            if name.startswith(".tmp-chunk-"):
                shutil.rmtree(
                    os.path.join(self.spec.directory, name),
                    ignore_errors=True,
                )

    def _chunk_dirs(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.spec.directory):
            m = _CHUNK_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.spec.directory, name)))
        return sorted(out)

    def _load(self, path: str) -> StreamCheckpointState:
        import json

        np = self._np
        manifest_path = os.path.join(path, _MANIFEST_FILE)
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            raise CheckpointError(
                f"{path}: incomplete checkpoint (no manifest)"
            ) from None
        except ValueError as e:
            raise CheckpointError(
                f"{manifest_path}: corrupt manifest ({e})"
            ) from None
        if manifest.get("format_version") != _FORMAT_VERSION:
            raise CheckpointError(
                f"{manifest_path}: unsupported format_version "
                f"{manifest.get('format_version')!r}"
            )
        if manifest.get("kind") != "streaming":
            raise CheckpointError(
                f"{manifest_path}: not a streaming checkpoint "
                f"(kind={manifest.get('kind')!r})"
            )
        try:
            coeffs = np.load(os.path.join(path, "coefficients.npy"))
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"{path}: unreadable coefficients ({e})"
            ) from None
        if coeffs.shape != (
            int(manifest["num_entities"]), int(manifest["dim"])
        ):
            raise CheckpointError(
                f"{path}: coefficient shape {coeffs.shape} does not match "
                "its manifest"
            )
        variances = None
        if manifest.get("has_variances"):
            try:
                variances = np.load(os.path.join(path, "variances.npy"))
            except (OSError, ValueError) as e:
                raise CheckpointError(
                    f"{path}: unreadable variances ({e})"
                ) from None
        return StreamCheckpointState(
            next_chunk=int(manifest["next_chunk"]),
            coefficients=coeffs,
            variances=variances,
        )

    def restore(self) -> Optional[StreamCheckpointState]:
        """Newest VALID streaming checkpoint, or None; corrupt/partial
        directories are skipped with a warning (``checkpoint.corrupt``)."""
        if not self.spec.resume:
            return None
        with telemetry.span("checkpoint:restore"):
            for _c, path in reversed(self._chunk_dirs()):
                try:
                    state = self._load(path)
                except (CheckpointError, ValueError, OSError) as e:
                    telemetry.counter("checkpoint.corrupt").inc()
                    logger.warning(
                        "skipping corrupt checkpoint %s: %s", path, e
                    )
                    continue
                telemetry.counter("checkpoint.restores").inc()
                logger.info(
                    "resuming streamed fit from %s (next chunk %d)",
                    path, state.next_chunk,
                )
                return state
        return None
