"""Checkpoint/resume for coordinate descent: atomic step snapshots,
corrupt-checkpoint fallback, and graceful-preemption plumbing.

The reference inherits fault tolerance from Spark (RDD lineage re-executes
lost partitions; the driver survives executor loss). The TPU port replaced
that substrate with long-lived device arrays, so a preemption or OOM used
to discard the whole GAME fit. This module restores durability at the
``(iteration, coordinate)`` granularity:

Layout (one directory per completed step)::

    <checkpoint_dir>/
      step-00000007/
        manifest.json        step cursor, best metric, JSON-safe history
        model/               full GAME model (model_store savers)
        best/                best-so-far model (present iff validation ran)

Atomicity: each checkpoint is assembled in a ``.tmp-step-*`` sibling and
``os.rename``d into place (readers never see a partial directory); the
manifest is written last inside the tmp dir, so a directory missing its
manifest is by definition incomplete. ``restore`` walks step directories
newest-first and falls back past corrupt or partial ones (counted in the
``checkpoint.corrupt`` telemetry counter). Retention keeps the newest
``keep_last`` checkpoints.

Graceful preemption: :class:`GracefulStop` turns SIGTERM/SIGINT into a
"finish this step, write a final checkpoint, raise
:class:`TrainingInterrupted`" request — the train CLI installs it so a
preempted run restarts with ``--resume`` instead of from scratch.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import shutil
import signal
from typing import Optional

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.utils.atomic import atomic_write_json, fsync_dir

logger = logging.getLogger("photon_ml_tpu.game.checkpoint")

_MANIFEST_FILE = "manifest.json"
_FORMAT_VERSION = 1
#: streaming manifests: 2 = per-shard payload files + sharding/env record
#: (elastic restore); 1 = the legacy single coefficients.npy
_STREAM_FORMAT_VERSION = 2
_STEP_RE = re.compile(r"^step-(\d{8})$")

# The atomic-write protocol's crash seams, one per phase — the crash
# matrix (tools/chaos.py) kills a fit at each and asserts resume
# reproduces the uninterrupted model. Shared by the step and streaming
# managers: the protocol is identical.
_FP_SAVE_BEFORE_TMP = faults.register_point(
    "checkpoint.save.before_tmp", write_path=True,
    description="before the .tmp- sibling is assembled (no trace on disk)",
)
_FP_SAVE_BEFORE_MANIFEST = faults.register_point(
    "checkpoint.save.before_manifest", write_path=True,
    description="payload written, manifest absent (tmp dir incomplete)",
)
_FP_SAVE_BEFORE_RENAME = faults.register_point(
    "checkpoint.save.before_rename", write_path=True,
    description="tmp dir complete but not yet renamed into place",
)
_FP_SAVE_AFTER_RENAME = faults.register_point(
    "checkpoint.save.after_rename", write_path=True,
    description="checkpoint durable; retention/fsync not yet run",
)
_FP_MANIFEST_READ = faults.register_point(
    "checkpoint.manifest.read",
    description="manifest open/parse during restore (corrupt-skip path)",
)
# Coordinated (multi-process) saves add one more seam: a member dying
# between writing its shard payloads and landing its per-process manifest
# leaves the quorum forever incomplete — process 0 must time out and
# abandon the checkpoint (uncertified), never hang the fleet or certify a
# partial one.
_FP_PEER_MANIFEST = faults.register_point(
    "checkpoint.peer_manifest", distributed=True,
    description="before a member writes its per-process shard manifest "
    "during a coordinated save",
)


class CheckpointError(RuntimeError):
    """A checkpoint directory is unusable (corrupt, partial, or written by
    an incompatible run)."""


class TrainingInterrupted(RuntimeError):
    """Raised after a graceful-stop request once the final checkpoint is on
    disk; carries where training stopped so drivers can report it."""

    def __init__(self, step: int, checkpoint_path: Optional[str]):
        super().__init__(
            f"training interrupted after step {step}"
            + (f"; checkpoint at {checkpoint_path}" if checkpoint_path else "")
        )
        self.step = step
        self.checkpoint_path = checkpoint_path


@dataclasses.dataclass(frozen=True)
class CheckpointSpec:
    """Checkpointing policy for a fit.

    ``every`` saves after every N completed ``(iteration, coordinate)``
    steps (a stop request always forces a final save). ``resume=False``
    is a FRESH fit into the directory: existing step checkpoints are
    cleared at manager construction (otherwise a stale run's
    higher-numbered steps would outlive this run's through retention and
    hijack a later resume).

    ``quorum_timeout_s`` only matters for COORDINATED (multi-process)
    streaming saves: how long process 0 waits for every peer's manifest
    before abandoning the checkpoint uncertified (a dead peer must never
    hang the save), and how long peers wait for process 0's rendezvous /
    certification before giving up.
    """

    directory: str
    every: int = 1
    keep_last: int = 3
    resume: bool = True
    quorum_timeout_s: float = 60.0

    def __post_init__(self):
        if self.every < 1:
            raise ValueError("checkpoint every must be >= 1")
        if self.keep_last < 1:
            raise ValueError("checkpoint keep_last must be >= 1")
        if self.quorum_timeout_s <= 0:
            raise ValueError("checkpoint quorum_timeout_s must be > 0")


@dataclasses.dataclass
class CheckpointState:
    """Everything coordinate descent needs to continue a fit: the step
    cursor (last COMPLETED global step), the live per-coordinate models,
    the best-model tracking, the JSON-safe step history, and the guard's
    rollback bookkeeping (so a resumed fit does not re-attempt solves a
    frozen coordinate already proved divergent)."""

    step: int
    model: GameModel
    best_model: Optional[GameModel]
    best_metric: Optional[float]
    history: list
    frozen: list = dataclasses.field(default_factory=list)
    consecutive_rollbacks: Optional[dict] = None


def _step_dirname(step: int) -> str:
    return f"step-{step:08d}"


class CheckpointManager:
    """Atomic save / newest-valid restore / retention over one directory."""

    def __init__(self, spec: CheckpointSpec):
        self.spec = spec
        os.makedirs(spec.directory, exist_ok=True)
        if not spec.resume:
            stale = self._step_dirs()
            if stale:
                logger.warning(
                    "resume=False: clearing %d existing checkpoint(s) "
                    "under %s for a fresh fit", len(stale), spec.directory,
                )
            for _step, path in stale:
                shutil.rmtree(path, ignore_errors=True)

    # -- save ----------------------------------------------------------------

    def should_save(self, step: int) -> bool:
        return (step + 1) % self.spec.every == 0

    def save(self, state: CheckpointState) -> str:
        """Persist ``state`` as ``step-<step>``; returns the final path."""
        from photon_ml_tpu.data.model_store import save_game_model

        final = os.path.join(self.spec.directory, _step_dirname(state.step))
        tmp = os.path.join(
            self.spec.directory, f".tmp-{_step_dirname(state.step)}"
        )
        with telemetry.span("checkpoint:save", step=state.step):
            faults.fault_point(_FP_SAVE_BEFORE_TMP)
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            save_game_model(state.model, os.path.join(tmp, "model"))
            if state.best_model is not None:
                save_game_model(state.best_model, os.path.join(tmp, "best"))
            faults.fault_point(_FP_SAVE_BEFORE_MANIFEST)
            # the manifest lands LAST: its presence certifies completeness
            atomic_write_json(
                os.path.join(tmp, _MANIFEST_FILE),
                {
                    "format_version": _FORMAT_VERSION,
                    "step": state.step,
                    "coordinate_order": list(state.model.models),
                    "best_metric": state.best_metric,
                    "has_best": state.best_model is not None,
                    "history": state.history,
                    "frozen": list(state.frozen),
                    "consecutive_rollbacks": state.consecutive_rollbacks or {},
                },
                indent=2,
                sort_keys=True,
            )
            faults.fault_point(_FP_SAVE_BEFORE_RENAME)
            if os.path.exists(final):  # re-save of a step (resume overlap)
                shutil.rmtree(final)
            os.rename(tmp, final)
            faults.fault_point(_FP_SAVE_AFTER_RENAME)
            fsync_dir(self.spec.directory)
        telemetry.counter("checkpoint.saves").inc()
        telemetry.gauge("checkpoint.last_step").set(state.step)
        # tracer-timebase save stamp: the heartbeat reports checkpoint AGE
        # (now - this) so a wedged saver is visible before the run dies
        telemetry.gauge("checkpoint.last_save_ts").set(
            telemetry.trace.TRACER.now()
        )
        self._apply_retention()
        return final

    def _apply_retention(self) -> None:
        steps = self._step_dirs()
        for step, path in steps[: -self.spec.keep_last]:
            shutil.rmtree(path, ignore_errors=True)
        for name in os.listdir(self.spec.directory):
            # abandoned tmp dirs from a crashed save
            if name.startswith(".tmp-step-"):
                shutil.rmtree(
                    os.path.join(self.spec.directory, name),
                    ignore_errors=True,
                )

    # -- restore -------------------------------------------------------------

    def _step_dirs(self) -> list[tuple[int, str]]:
        """(step, path) for every step directory, oldest first."""
        out = []
        for name in os.listdir(self.spec.directory):
            m = _STEP_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.spec.directory, name)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self._step_dirs()
        return steps[-1][0] if steps else None

    def _load(self, path: str) -> CheckpointState:
        from photon_ml_tpu.data.model_store import load_game_model

        manifest_path = os.path.join(path, _MANIFEST_FILE)
        try:
            import json

            faults.fault_point(_FP_MANIFEST_READ)
            with open(manifest_path) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            raise CheckpointError(
                f"{path}: incomplete checkpoint (no manifest)"
            ) from None
        except ValueError as e:
            raise CheckpointError(
                f"{manifest_path}: corrupt manifest ({e})"
            ) from None
        if manifest.get("format_version") != _FORMAT_VERSION:
            raise CheckpointError(
                f"{manifest_path}: unsupported format_version "
                f"{manifest.get('format_version')!r}"
            )
        model = load_game_model(os.path.join(path, "model"))
        best_model = None
        if manifest.get("has_best"):
            best_model = load_game_model(os.path.join(path, "best"))
        return CheckpointState(
            step=int(manifest["step"]),
            model=model,
            best_model=best_model,
            best_metric=manifest.get("best_metric"),
            history=list(manifest.get("history", ())),
            frozen=list(manifest.get("frozen", ())),
            consecutive_rollbacks=dict(
                manifest.get("consecutive_rollbacks") or {}
            ),
        )

    def restore(self) -> Optional[CheckpointState]:
        """Newest VALID checkpoint, or None. Corrupt/partial checkpoints
        (truncated npz, missing manifest, bad metadata) are skipped with a
        warning and counted, falling back to the next older one."""
        if not self.spec.resume:
            return None
        with telemetry.span("checkpoint:restore"):
            for step, path in reversed(self._step_dirs()):
                try:
                    state = self._load(path)
                except (CheckpointError, ValueError, OSError) as e:
                    # ModelLoadError is a ValueError; OSError covers a
                    # half-deleted directory
                    telemetry.counter("checkpoint.corrupt").inc()
                    logger.warning(
                        "skipping corrupt checkpoint %s: %s", path, e
                    )
                    continue
                telemetry.counter("checkpoint.restores").inc()
                logger.info("resuming from checkpoint %s (step %d)",
                            path, state.step)
                return state
        return None


class GracefulStop:
    """SIGTERM/SIGINT -> cooperative stop flag (the preemption handshake).

    The first signal requests a graceful stop: the training loop finishes
    its current step, writes a final checkpoint, and raises
    :class:`TrainingInterrupted`. A REPEATED signal is the escape hatch:
    the process hard-exits with ``hard_exit_code`` (default 75, the same
    "incomplete, restart me" code the graceful path uses) instead of
    blocking behind a slow final-checkpoint write — a scheduler that
    escalates SIGTERM gets its worker back immediately, and the
    half-written ``.tmp-`` directory is skipped by the next restore.
    """

    def __init__(self, hard_exit_code: int = 75):
        self.requested = False
        self.signum: Optional[int] = None
        self.hard_exit_code = hard_exit_code
        self._installed = False

    def install(self, signums=(signal.SIGTERM, signal.SIGINT)) -> "GracefulStop":
        for s in signums:
            signal.signal(s, self._handle)
        self._installed = True
        return self

    def _handle(self, signum, frame):
        if self.requested:
            # ASYNC-SIGNAL-SAFE path only: the process is very possibly
            # wedged behind the slow save this escape hatch exists for,
            # and logger.warning/logging.shutdown can block on a handler
            # lock held by a stuck background thread — which would turn
            # "hard exit now" back into the hang we're escaping. A raw
            # write(2) and _exit are the whole budget.
            try:
                os.write(
                    2,
                    b"second signal during graceful stop: hard exit "
                    + str(self.hard_exit_code).encode()
                    + b" (in-flight checkpoint write abandoned; its .tmp "
                    b"directory is skipped on restore)\n",
                )
            except OSError:
                pass
            os._exit(self.hard_exit_code)
        self.requested = True
        self.signum = signum
        logger.warning(
            "received signal %d: finishing current step, then writing a "
            "final checkpoint and exiting", signum,
        )

    def __call__(self) -> bool:
        """Stop-predicate form, passed as ``should_stop=``."""
        return self.requested


# ---------------------------------------------------------------------------
# streamed-fit checkpointing (chunk-boundary granularity)
# ---------------------------------------------------------------------------

_CHUNK_RE = re.compile(r"^chunk-(\d{8})$")


@dataclasses.dataclass
class StreamCheckpointState:
    """Everything a streamed random-effect fit needs to continue: the
    NEXT chunk index to solve (the deterministic ingest planner replays
    the same stream from that boundary) and the coefficient table solved
    so far.

    ``coefficients``/``variances`` may be host numpy arrays OR device
    ``jax.Array``s (possibly entity-sharded across a mesh) — pass the
    table's live device array and the manager saves it SHARD BY SHARD,
    never assembling the full table on the host."""

    next_chunk: int
    coefficients: "object"  # np.ndarray or jax.Array, [N, K]
    variances: Optional["object"] = None


@dataclasses.dataclass
class ElasticRestore:
    """A streaming checkpoint re-placed for THIS run's device topology.

    ``coefficients``/``variances`` are device arrays placed via
    ``parallel.sharding.place_entity_rows`` for whatever mesh the caller
    passed — which need not match the mesh that wrote the checkpoint
    (``elastic`` is True when it didn't: a mesh-shrunken resume after
    device loss, or a single-device debug restore of a sharded run)."""

    next_chunk: int
    coefficients: "object"
    variances: Optional["object"]
    saved_sharding: Optional[dict]  # the writing run's manifest record
    saved_env: Optional[dict]
    elastic: bool


def _environment_record() -> dict:
    """The decode/topology environment a streaming checkpoint was written
    under — recorded so a restore under a DIFFERENT environment (native
    decoder toggled, fewer devices after a failure) can report the delta
    instead of failing mysteriously."""
    try:
        import jax

        backend = jax.default_backend()
        device_count = int(jax.device_count())
    except Exception:  # pragma: no cover - jax always present in-tree
        backend, device_count = "unknown", 0
    return {
        "no_native": os.environ.get("PHOTON_NO_NATIVE") == "1",
        "backend": backend,
        "device_count": device_count,
    }


def _entity_shard_parts(array) -> list:
    """``(row_start, part)`` per DISTINCT addressable row range of an
    entity-leading array, sorted by row start. ``part`` is a
    ``jax.Array`` shard (``.data``) or the array itself (host/unsharded)
    — callers fetch one part at a time, so peak host residency during a
    sharded save is ONE shard, not the table."""
    shards = getattr(array, "addressable_shards", None)
    if not shards:
        return [(0, array)]
    by_start: dict[int, object] = {}
    for s in shards:
        lo = s.index[0].start or 0
        # replicated placements repeat every range on every device;
        # one copy per distinct range is the whole array
        by_start.setdefault(int(lo), s)
    return [(lo, by_start[lo]) for lo in sorted(by_start)]


def _sharding_record(array) -> Optional[dict]:
    """JSON-safe record of a device array's NamedSharding (mesh axis
    sizes + partition spec), or None for host/unsharded arrays."""
    sharding = getattr(array, "sharding", None)
    mesh = getattr(sharding, "mesh", None)
    spec = getattr(sharding, "spec", None)
    if mesh is None or spec is None:
        return None
    try:
        axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except (TypeError, ValueError):
        return None
    return {
        "mesh_axes": axes,
        "spec": [None if s is None else str(s) for s in spec],
    }


class StreamingCheckpointManager:
    """Atomic chunk-boundary checkpoints for streamed table fits.

    Same durability contract as :class:`CheckpointManager` (assemble in a
    ``.tmp-`` sibling, manifest written last, ``os.rename`` into place,
    newest-valid restore past corrupt directories, keep-last-K
    retention), but the unit of progress is a CHUNK of the deterministic
    ingest stream, not an (iteration, coordinate) step — resume replays
    from ``next_chunk`` and re-decodes exactly the rows the interrupted
    run would have seen, in the same order (ingest.planner's determinism
    contract).

    **Sharding-aware**: a mesh-sharded coefficient table is saved one
    payload file PER addressable shard (``coefficients-NNNN.npy``,
    fetched one shard at a time — the 40 GB entity-sharded table from the
    ``game_10B`` regime never exists on the host), and the manifest
    records each file's row range plus the writing run's mesh shape,
    partition spec, and environment. Restore is **elastic**:
    :meth:`restore_placed` re-slices the entity axis onto ANY target mesh
    (or none), so losing devices means a mesh-shrunken resume instead of
    a dead run.
    """

    def __init__(self, spec: CheckpointSpec, read_only: bool = False):
        import numpy as np  # local: keep module import light

        self._np = np
        self.spec = spec
        self.read_only = read_only
        if read_only:
            # the restore-to-serving path: never create, never clear —
            # a typo'd directory is an error, not a fresh empty one
            if not os.path.isdir(spec.directory):
                raise CheckpointError(
                    f"no streamed checkpoint directory at {spec.directory}"
                )
            return
        os.makedirs(spec.directory, exist_ok=True)
        if not spec.resume:
            stale = self._chunk_dirs()
            if stale:
                logger.warning(
                    "resume=False: clearing %d existing streaming "
                    "checkpoint(s) under %s", len(stale), spec.directory,
                )
            for _c, path in stale:
                shutil.rmtree(path, ignore_errors=True)

    @classmethod
    def open_for_restore(cls, directory: str) -> "StreamingCheckpointManager":
        """A READ-ONLY manager over an existing checkpoint directory —
        the restore-to-serving path (:meth:`restore_placed` onto a
        serving mesh). It never writes, never clears, and :meth:`save`
        refuses: a serving process must not be able to mutate a training
        run's checkpoint history."""
        return cls(CheckpointSpec(directory=directory), read_only=True)

    def should_save(self, chunk_index: int) -> bool:
        return (chunk_index + 1) % self.spec.every == 0

    def _write_entity_array(self, tmp: str, prefix: str, array) -> list[dict]:
        """Write ``array`` as one .npy per distinct shard row range;
        returns the manifest shard descriptors. Per-shard host fetches
        only — counted so the no-full-gather property is assertable."""
        np = self._np
        descriptors = []
        max_bytes = 0
        for i, (row_start, part) in enumerate(_entity_shard_parts(array)):
            data = np.asarray(getattr(part, "data", part))
            fname = f"{prefix}-{i:04d}.npy"
            np.save(os.path.join(tmp, fname), data)
            descriptors.append(
                {
                    "file": fname,
                    "row_start": int(row_start),
                    "rows": int(data.shape[0]),
                }
            )
            telemetry.counter("checkpoint.shard_saves").inc()
            max_bytes = max(max_bytes, int(data.nbytes))
        # the largest single host fetch this save performed — a sharded
        # table must stay at table_bytes / n_shards (the telemetry check
        # the no-host-gather acceptance rides on)
        telemetry.gauge("checkpoint.max_shard_fetch_bytes").set(max_bytes)
        return descriptors

    def save(self, state: StreamCheckpointState) -> Optional[str]:
        """Persist ``state`` as ``chunk-<next_chunk>``; the final path.

        In a multi-process fleet this is the COORDINATED protocol
        (:meth:`_save_coordinated` — every member must call save at the
        same boundary); it may return None when the quorum never formed
        (a peer died mid-save) — the directory is left uncertified and
        restore falls back past it."""
        import jax

        if self.read_only:
            raise CheckpointError(
                f"checkpoint manager over {self.spec.directory} is "
                "read-only (open_for_restore): serving must not write "
                "into a training run's checkpoint history"
            )
        if jax.process_count() > 1:
            return self._save_coordinated(state)
        name = f"chunk-{state.next_chunk:08d}"
        final = os.path.join(self.spec.directory, name)
        tmp = os.path.join(self.spec.directory, f".tmp-{name}")
        coeffs = state.coefficients
        num_entities, dim = (int(d) for d in coeffs.shape)
        with telemetry.span("checkpoint:save", next_chunk=state.next_chunk):
            faults.fault_point(_FP_SAVE_BEFORE_TMP)
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            shard_files = self._write_entity_array(tmp, "coefficients", coeffs)
            variance_files = None
            if state.variances is not None:
                variance_files = self._write_entity_array(
                    tmp, "variances", state.variances
                )
            faults.fault_point(_FP_SAVE_BEFORE_MANIFEST)
            # manifest LAST: its presence certifies the directory complete
            atomic_write_json(
                os.path.join(tmp, _MANIFEST_FILE),
                {
                    "format_version": _STREAM_FORMAT_VERSION,
                    "kind": "streaming",
                    "next_chunk": int(state.next_chunk),
                    "num_entities": num_entities,
                    "dim": dim,
                    "dtype": str(getattr(coeffs, "dtype", "float32")),
                    "shards": shard_files,
                    "variance_shards": variance_files,
                    "sharding": _sharding_record(coeffs),
                    "env": _environment_record(),
                },
                indent=2,
                sort_keys=True,
            )
            faults.fault_point(_FP_SAVE_BEFORE_RENAME)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            faults.fault_point(_FP_SAVE_AFTER_RENAME)
            fsync_dir(self.spec.directory)
        telemetry.counter("checkpoint.saves").inc()
        telemetry.gauge("checkpoint.last_save_ts").set(
            telemetry.trace.TRACER.now()
        )
        self._apply_retention()
        return final

    # -- coordinated multi-process saves -------------------------------------

    @staticmethod
    def _wait_until(predicate, timeout_s: float, poll_s: float = 0.05) -> bool:
        """Poll ``predicate`` until true or ``timeout_s`` elapses — the
        filesystem-rendezvous barrier primitive. Time-bounded by design:
        a dead peer must never hang the fleet's save."""
        import time

        deadline = time.monotonic() + timeout_s
        while True:
            if predicate():
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    def _peer_manifest_name(self, pid: int) -> str:
        return f"manifest.proc-{pid:04d}.json"

    def _save_coordinated(self, state: StreamCheckpointState) -> Optional[str]:
        """Multi-process save: every member writes its ADDRESSABLE shards
        plus a per-process manifest into a shared ``.tmp-`` directory;
        process 0 certifies the quorum manifest (``manifest.json``) only
        after every peer's manifest lands, then renames the directory
        into place. Completeness therefore has a single witness — the
        quorum manifest — and a checkpoint whose fleet lost a member
        mid-save is left uncertified (``checkpoint.quorum_timeouts``),
        exactly as restorable-past as a single-process crash's debris.

        Rendezvous is filesystem-only (requires the checkpoint directory
        to be shared across the fleet, same as restore does) and every
        wait is bounded by ``spec.quorum_timeout_s``."""
        import json

        import jax

        pid = jax.process_index()
        nproc = jax.process_count()
        name = f"chunk-{state.next_chunk:08d}"
        final = os.path.join(self.spec.directory, name)
        tmp = os.path.join(self.spec.directory, f".tmp-{name}")
        rendezvous = os.path.join(tmp, "rendezvous.json")
        timeout = self.spec.quorum_timeout_s
        coeffs = state.coefficients
        dim = int(coeffs.shape[1])
        with telemetry.span(
            "checkpoint:save", next_chunk=state.next_chunk, coordinated=True
        ):
            faults.fault_point(_FP_SAVE_BEFORE_TMP)
            if pid == 0:
                if os.path.exists(tmp):
                    # stale debris from a crashed earlier save of this
                    # chunk: move it aside ATOMICALLY so a racing peer
                    # can never mistake old contents for this rendezvous
                    trash = os.path.join(
                        self.spec.directory, f".trash-{name}"
                    )
                    shutil.rmtree(trash, ignore_errors=True)
                    os.rename(tmp, trash)
                    shutil.rmtree(trash, ignore_errors=True)
                os.makedirs(tmp)
                atomic_write_json(
                    rendezvous,
                    {"num_processes": nproc,
                     "next_chunk": int(state.next_chunk)},
                )
            else:
                def _rendezvous_matches() -> bool:
                    # content-validated, not mere existence: a STALE
                    # rendezvous from an abandoned earlier save (or a
                    # different fleet size replaying the same chunk)
                    # must not lure this member into a tmp dir process 0
                    # is about to trash
                    try:
                        with open(rendezvous, encoding="utf-8") as fh:
                            doc = json.load(fh)
                    except (OSError, ValueError):
                        return False
                    return (
                        doc.get("num_processes") == nproc
                        and doc.get("next_chunk") == int(state.next_chunk)
                    )

                if not self._wait_until(_rendezvous_matches, timeout):
                    telemetry.counter("checkpoint.quorum_timeouts").inc()
                    logger.warning(
                        "coordinated save %s: no matching rendezvous from "
                        "process 0 within %.1fs; abandoning (uncertified)",
                        name, timeout,
                    )
                    return None
            shard_files = self._write_entity_array(
                tmp, f"coefficients-p{pid:04d}", coeffs
            )
            variance_files = None
            if state.variances is not None:
                variance_files = self._write_entity_array(
                    tmp, f"variances-p{pid:04d}", state.variances
                )
            faults.fault_point(_FP_PEER_MANIFEST)
            # the per-process manifest lands LAST (atomic): its presence
            # certifies THIS member's shards complete
            atomic_write_json(
                os.path.join(tmp, self._peer_manifest_name(pid)),
                {
                    "process_id": pid,
                    "num_processes": nproc,
                    "next_chunk": int(state.next_chunk),
                    "shards": shard_files,
                    "variance_shards": variance_files,
                },
            )
            telemetry.counter("checkpoint.peer_manifests").inc()
            if pid != 0:
                # wait for certification (rename) or abandonment; either
                # way this member's save call returns — the outcome is
                # process 0's to decide
                self._wait_until(
                    lambda: os.path.exists(final) or not os.path.exists(tmp),
                    timeout,
                )
                if os.path.exists(final):
                    telemetry.counter("checkpoint.saves").inc()
                    return final
                telemetry.counter("checkpoint.quorum_timeouts").inc()
                logger.warning(
                    "coordinated save %s was never certified by process 0",
                    name,
                )
                return None
            # process 0: the quorum barrier — every peer's manifest, or bust
            peer_paths = [
                os.path.join(tmp, self._peer_manifest_name(p))
                for p in range(nproc)
            ]
            if not self._wait_until(
                lambda: all(os.path.exists(p) for p in peer_paths), timeout
            ):
                missing = [
                    p for pth, p in zip(peer_paths, range(nproc))
                    if not os.path.exists(pth)
                ]
                telemetry.counter("checkpoint.quorum_timeouts").inc()
                logger.warning(
                    "coordinated save %s: peer manifest(s) from process(es) "
                    "%s never landed within %.1fs — abandoning uncertified "
                    "(restore will fall back past it)", name, missing, timeout,
                )
                return None
            merged: list[dict] = []
            merged_var: list[dict] = []
            for path in peer_paths:
                with open(path, encoding="utf-8") as fh:
                    peer = json.load(fh)
                merged.extend(peer["shards"])
                merged_var.extend(peer.get("variance_shards") or ())
            merged.sort(key=lambda d: int(d["row_start"]))
            merged_var.sort(key=lambda d: int(d["row_start"]))
            # the merged shard set DEFINES the checkpoint's entity axis:
            # certify only a contiguous [0, N) cover (a replicated-row
            # overlap or a hole means a peer wrote rows the fleet did not
            # agree on — certifying it would hand restore a lie)
            num_entities = 0
            for d in merged:
                if int(d["row_start"]) != num_entities:
                    telemetry.counter(
                        "checkpoint.quorum_cover_violations"
                    ).inc()
                    logger.warning(
                        "coordinated save %s: merged shards do not cover "
                        "the entity axis contiguously (gap/overlap at row "
                        "%d) — abandoning uncertified", name, num_entities,
                    )
                    return None
                num_entities += int(d["rows"])
            # every payload byte a peer manifest names must actually be
            # on disk — a peer raced into a stale tmp dir (its shards
            # died with the trash) can land a manifest here, and
            # certifying on metadata alone would certify a partial
            # checkpoint
            missing_payload = [
                d["file"]
                for d in (*merged, *merged_var)
                if not os.path.exists(os.path.join(tmp, d["file"]))
            ]
            if missing_payload:
                telemetry.counter(
                    "checkpoint.quorum_cover_violations"
                ).inc()
                logger.warning(
                    "coordinated save %s: peer manifest(s) name payload "
                    "file(s) missing from the save dir (%s) — abandoning "
                    "uncertified", name, missing_payload,
                )
                return None
            faults.fault_point(_FP_SAVE_BEFORE_MANIFEST)
            # the QUORUM manifest: written only after every peer landed,
            # and the only artifact restore treats as certification
            atomic_write_json(
                os.path.join(tmp, _MANIFEST_FILE),
                {
                    "format_version": _STREAM_FORMAT_VERSION,
                    "kind": "streaming",
                    "next_chunk": int(state.next_chunk),
                    "num_entities": num_entities,
                    "dim": dim,
                    "dtype": str(getattr(coeffs, "dtype", "float32")),
                    "shards": merged,
                    "variance_shards": merged_var or None,
                    "sharding": _sharding_record(coeffs),
                    "env": _environment_record(),
                    "quorum": {"num_processes": nproc},
                },
                indent=2,
                sort_keys=True,
            )
            faults.fault_point(_FP_SAVE_BEFORE_RENAME)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            faults.fault_point(_FP_SAVE_AFTER_RENAME)
            fsync_dir(self.spec.directory)
        telemetry.counter("checkpoint.saves").inc()
        telemetry.gauge("checkpoint.last_save_ts").set(
            telemetry.trace.TRACER.now()
        )
        self._apply_retention()
        return final

    def _apply_retention(self) -> None:
        dirs = self._chunk_dirs()
        for _c, path in dirs[: -self.spec.keep_last]:
            shutil.rmtree(path, ignore_errors=True)
        for name in os.listdir(self.spec.directory):
            if name.startswith(".tmp-chunk-") or name.startswith(
                ".trash-chunk-"
            ):
                shutil.rmtree(
                    os.path.join(self.spec.directory, name),
                    ignore_errors=True,
                )

    def _chunk_dirs(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.spec.directory):
            m = _CHUNK_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.spec.directory, name)))
        return sorted(out)

    def _read_manifest(self, path: str) -> dict:
        import json

        manifest_path = os.path.join(path, _MANIFEST_FILE)
        try:
            faults.fault_point(_FP_MANIFEST_READ)
            with open(manifest_path) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            raise CheckpointError(
                f"{path}: incomplete checkpoint (no manifest)"
            ) from None
        except ValueError as e:
            raise CheckpointError(
                f"{manifest_path}: corrupt manifest ({e})"
            ) from None
        version = manifest.get("format_version")
        if version not in (1, _STREAM_FORMAT_VERSION):
            raise CheckpointError(
                f"{manifest_path}: unsupported format_version {version!r}"
            )
        if manifest.get("kind") != "streaming":
            raise CheckpointError(
                f"{manifest_path}: not a streaming checkpoint "
                f"(kind={manifest.get('kind')!r})"
            )
        return manifest

    def _shard_descriptors(
        self, path: str, manifest: dict, prefix: str
    ) -> Optional[list[dict]]:
        """Validated (file, row_start, rows) descriptors covering exactly
        [0, num_entities), for v2 manifests; v1 synthesizes the single
        legacy file. None when the payload is absent (variances)."""
        n = int(manifest["num_entities"])
        if manifest.get("format_version") == 1:
            legacy = {"coefficients": "coefficients.npy",
                      "variances": "variances.npy"}[prefix]
            if prefix == "variances" and not manifest.get("has_variances"):
                return None
            return [{"file": legacy, "row_start": 0, "rows": n}]
        key = "shards" if prefix == "coefficients" else "variance_shards"
        descriptors = manifest.get(key)
        if descriptors is None:
            if prefix == "variances":
                return None
            raise CheckpointError(f"{path}: manifest lists no shards")
        cursor = 0
        for d in descriptors:
            if int(d["row_start"]) != cursor:
                raise CheckpointError(
                    f"{path}: shard rows are not contiguous at "
                    f"{d['row_start']} (expected {cursor})"
                )
            cursor += int(d["rows"])
        if cursor != n:
            raise CheckpointError(
                f"{path}: shards cover {cursor} rows but the manifest "
                f"promises {n} entities"
            )
        return descriptors

    def _row_reader(self, path: str, manifest: dict, prefix: str):
        """A ``read_rows(lo, hi)`` over the (memory-mapped) shard files —
        the lazy source ``parallel.sharding.place_entity_rows`` re-slices
        for elastic placement. Shape/readability validated up front so a
        corrupt directory is skippable before any placement happens."""
        np = self._np
        descriptors = self._shard_descriptors(path, manifest, prefix)
        if descriptors is None:
            return None
        dim = int(manifest["dim"])
        files = []
        for d in descriptors:
            fpath = os.path.join(path, d["file"])
            try:
                arr = np.load(fpath, mmap_mode="r")
            except (OSError, ValueError) as e:
                raise CheckpointError(
                    f"{fpath}: unreadable shard ({e})"
                ) from None
            if arr.shape != (int(d["rows"]), dim):
                raise CheckpointError(
                    f"{fpath}: shard shape {arr.shape} does not match its "
                    f"manifest entry ({d['rows']}, {dim})"
                )
            files.append((int(d["row_start"]), int(d["rows"]), arr))

        def read_rows(lo: int, hi: int):
            pieces = [
                arr[max(lo - start, 0): hi - start]
                for start, rows, arr in files
                if start < hi and start + rows > lo
            ]
            if len(pieces) == 1:
                return np.asarray(pieces[0])
            return np.concatenate([np.asarray(p) for p in pieces], axis=0)

        return read_rows

    def _load(self, path: str) -> StreamCheckpointState:
        manifest = self._read_manifest(path)
        np = self._np
        n = int(manifest["num_entities"])
        read_coeffs = self._row_reader(path, manifest, "coefficients")
        read_vars = self._row_reader(path, manifest, "variances")
        # owned copies, never memory-mapped views: a single-shard read is
        # a view of the np.load(mmap_mode="r") file, and handing that to
        # a caller who device_puts it zero-copy would alias the mapping
        # (the place_entity_rows aliasing lesson — restore() callers by
        # contract hold the whole table, so the copy is what they expect)
        return StreamCheckpointState(
            next_chunk=int(manifest["next_chunk"]),
            coefficients=np.array(read_coeffs(0, n), copy=True),
            variances=(
                None if read_vars is None
                else np.array(read_vars(0, n), copy=True)
            ),
        )

    def restore(self) -> Optional[StreamCheckpointState]:
        """Newest VALID streaming checkpoint, or None; corrupt/partial
        directories are skipped with a warning (``checkpoint.corrupt``).

        NOTE: materializes the FULL table on the host — fine for tables
        that fit one process; sharded-only regimes use
        :meth:`restore_placed`, which re-places shard files straight onto
        the target mesh."""
        if not self.spec.resume:
            return None
        with telemetry.span("checkpoint:restore"):
            for _c, path in reversed(self._chunk_dirs()):
                try:
                    state = self._load(path)
                except (CheckpointError, ValueError, OSError) as e:
                    telemetry.counter("checkpoint.corrupt").inc()
                    logger.warning(
                        "skipping corrupt checkpoint %s: %s", path, e
                    )
                    continue
                telemetry.counter("checkpoint.restores").inc()
                logger.info(
                    "resuming streamed fit from %s (next chunk %d)",
                    path, state.next_chunk,
                )
                return state
        return None

    def restore_placed(
        self, mesh=None, axis: Optional[str] = None
    ) -> Optional[ElasticRestore]:
        """Newest valid checkpoint, ELASTICALLY placed for ``mesh``.

        The entity axis is re-sliced onto the target mesh's model axis
        via ``parallel.sharding.place_entity_rows`` (per-device shard
        reads over memory-mapped files — no full host materialization),
        so a checkpoint written on ``model=8`` restores onto ``model=4``
        or a single device: device loss degrades to a mesh-shrunken
        resume. Falls back past corrupt directories exactly like
        :meth:`restore`. Counts ``recovery.elastic_resumes`` when the
        target topology differs from the writing run's."""
        from photon_ml_tpu.parallel import sharding as psharding

        if not self.spec.resume:
            return None
        with telemetry.span("checkpoint:restore", elastic=True):
            for _c, path in reversed(self._chunk_dirs()):
                try:
                    manifest = self._read_manifest(path)
                    read_coeffs = self._row_reader(
                        path, manifest, "coefficients"
                    )
                    read_vars = self._row_reader(path, manifest, "variances")
                    n = int(manifest["num_entities"])
                    dim = int(manifest["dim"])
                    dtype = manifest.get("dtype", "float32")
                    coeffs = psharding.place_entity_rows(
                        read_coeffs, n, (dim,), dtype, mesh=mesh, axis=axis
                    )
                    variances = None
                    if read_vars is not None:
                        variances = psharding.place_entity_rows(
                            read_vars, n, (dim,), dtype, mesh=mesh, axis=axis
                        )
                except psharding.ElasticPlacementError:
                    # a TOPOLOGY mismatch, not corruption: every older
                    # checkpoint of this fit would fail identically, and
                    # skipping them would silently discard valid training
                    # progress behind a configuration error
                    raise
                except (CheckpointError, ValueError, OSError) as e:
                    telemetry.counter("checkpoint.corrupt").inc()
                    logger.warning(
                        "skipping corrupt checkpoint %s: %s", path, e
                    )
                    continue
                saved_sharding = manifest.get("sharding")
                saved_env = manifest.get("env")
                elastic = self._note_topology_delta(
                    path, saved_sharding, saved_env, mesh, axis
                )
                telemetry.counter("checkpoint.restores").inc()
                logger.info(
                    "resuming streamed fit from %s (next chunk %d, "
                    "elastic=%s)", path, int(manifest["next_chunk"]), elastic,
                )
                return ElasticRestore(
                    next_chunk=int(manifest["next_chunk"]),
                    coefficients=coeffs,
                    variances=variances,
                    saved_sharding=saved_sharding,
                    saved_env=saved_env,
                    elastic=elastic,
                )
        return None

    def restore_row_range(self, lo: int, hi: int):
        """Entity-code rows ``[lo, hi)`` of the newest valid checkpoint's
        coefficient table, as an owned host array — the serving-fleet
        member's restore: a member owning a contiguous code block
        (``parallel.sharding.member_row_range``) reads EXACTLY its slice
        off the mmap'd shard files, so a table no one host can hold still
        loads member-by-member. Falls back past corrupt directories like
        :meth:`restore`; returns None when no valid checkpoint exists.
        Bounds are validated against the manifest's entity count —
        a mis-sized fleet must fail loudly, never read a wrong slice."""
        np = self._np
        lo, hi = int(lo), int(hi)
        with telemetry.span("checkpoint:restore", member_rows=hi - lo):
            for _c, path in reversed(self._chunk_dirs()):
                try:
                    manifest = self._read_manifest(path)
                    n = int(manifest["num_entities"])
                    if not 0 <= lo <= hi <= n:
                        raise CheckpointError(
                            f"{path}: member row range [{lo}, {hi}) outside "
                            f"the {n}-entity table"
                        )
                    read_coeffs = self._row_reader(
                        path, manifest, "coefficients"
                    )
                except CheckpointError as e:
                    if "member row range" in str(e):
                        # a fleet-sizing error, not corruption: older
                        # checkpoints of this fit would fail identically
                        raise
                    telemetry.counter("checkpoint.corrupt").inc()
                    logger.warning(
                        "skipping corrupt checkpoint %s: %s", path, e
                    )
                    continue
                except (ValueError, OSError) as e:
                    telemetry.counter("checkpoint.corrupt").inc()
                    logger.warning(
                        "skipping corrupt checkpoint %s: %s", path, e
                    )
                    continue
                telemetry.counter("checkpoint.restores").inc()
                # owned copy, never a view of the mmap (the restore()
                # aliasing contract)
                return np.array(read_coeffs(lo, hi), copy=True)
        return None

    def _note_topology_delta(
        self, path, saved_sharding, saved_env, mesh, axis
    ) -> bool:
        """Compare the writing run's recorded topology/environment with
        THIS restore's target; log the delta and count elastic resumes."""
        from photon_ml_tpu.parallel import sharding as psharding

        if mesh is None:
            target_shards = 1
        else:
            resolved = axis or psharding.model_axis(mesh)
            target_shards = (
                psharding.axis_size(mesh, resolved) if resolved else 1
            )
        saved_shards = 1
        if saved_sharding:
            spec = [s for s in (saved_sharding.get("spec") or []) if s]
            axes = saved_sharding.get("mesh_axes") or {}
            if spec:
                saved_shards = int(axes.get(spec[0], 1))
        elastic = target_shards != saved_shards
        if elastic:
            telemetry.counter("recovery.elastic_resumes").inc()
            logger.warning(
                "elastic resume: %s was written across %d shard(s), "
                "restoring across %d", path, saved_shards, target_shards,
            )
        env_now = _environment_record()
        if saved_env and saved_env != env_now:
            deltas = {
                k: (saved_env.get(k), env_now.get(k))
                for k in set(saved_env) | set(env_now)
                if saved_env.get(k) != env_now.get(k)
            }
            logger.warning(
                "restore environment differs from the writing run's "
                "(%s: saved vs now %s) — shard files are "
                "environment-independent, continuing", path, deltas,
            )
        return elastic
