"""Random-effect datasets: per-entity grouping, size bucketing, and
per-entity feature projection — the TPU answer to the reference's
RandomEffectDataSet + RandomEffectDataSetPartitioner + IndexMapProjector
(photon-api data/RandomEffectDataSet.scala:45-435,
data/RandomEffectDataSetPartitioner.scala:42-148,
projector/IndexMapProjectorRDD.scala:27-77).

Where Spark bin-packs entities into JVM partitions and runs heterogeneous
per-entity solves, XLA needs fixed shapes: entities are grouped into
geometry buckets keyed by (rows, nnz, local-feature-count) rounded up to
powers of two. Each bucket is a stack of same-shaped per-entity sparse
problems solved by ONE vmapped optimizer call; bucket count is
O(log^3 of the size spread), bounding recompilation.

Per-entity index-map projection (the reference's key scaling trick —
projector/README.md says it reaches ~1e8 entities x ~1e3 features): each
entity's observed global feature ids become local ids 0..K-1 via the sorted
array ``projection``; the tiny K-dim local solve never touches the global
feature space.

Active-data caps use reservoir sampling with weight rescaling, matching
RandomEffectDataSet.scala:294-357; rows beyond the cap become passive data
(scored but not trained on; :368-409).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.ops.sparse import SparseBatch

Array = jax.Array


def _next_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x - 1).bit_length())


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EntityBucket:
    """A stack of E same-geometry per-entity sparse problems (LOCAL feature
    ids). Padding: rows -> R-1 with value 0; weights 0 on padded rows;
    projection -> num_global (sentinel past any feature id)."""

    values: Array  # f[E, nnz]
    rows: Array  # i32[E, nnz] local row ids
    cols: Array  # i32[E, nnz] LOCAL feature ids
    labels: Array  # f[E, R]
    offsets: Array  # f[E, R] base offsets
    weights: Array  # f[E, R]
    projection: Array  # i32[E, K] sorted global feature id per local id
    entity_codes: Array  # i32[E]; -1 padding entity
    row_index: Array  # i32[E, R] global example row; -1 padding
    num_local_features: int = dataclasses.field(metadata=dict(static=True))
    num_global_features: int = dataclasses.field(metadata=dict(static=True))

    @property
    def num_entities(self) -> int:
        return self.entity_codes.shape[0]

    @property
    def rows_per_entity(self) -> int:
        return self.labels.shape[1]

    def entity_batch(self) -> SparseBatch:
        """View as a SparseBatch with leading entity axis, for vmap."""
        return SparseBatch(
            values=self.values,
            rows=self.rows,
            cols=self.cols,
            labels=self.labels,
            offsets=self.offsets,
            weights=self.weights,
            num_features=self.num_local_features,
        )

    def with_extra_offsets(self, per_row: Array) -> "EntityBucket":
        """Add residual scores (global [n] array) to this bucket's offsets
        via row_index gather — the addScoresToOffsets analog."""
        extra = jnp.where(
            self.row_index >= 0,
            jnp.take(per_row, jnp.maximum(self.row_index, 0), fill_value=0),
            0.0,
        )
        return dataclasses.replace(self, offsets=self.offsets + extra)


@dataclasses.dataclass(frozen=True)
class RandomEffectDataset:
    """All buckets for one random-effect coordinate, plus entity placement.

    ``entity_bucket``/``entity_pos`` map entity code -> (bucket idx,
    position) for model lookup; -1 for entities with no active data.
    ``passive_rows`` are example rows excluded from training by the
    active-data cap, still scored at CD time.
    """

    id_name: str
    shard_name: str
    buckets: tuple[EntityBucket, ...]
    num_entities: int
    entity_bucket: np.ndarray  # i32[num_entities]
    entity_pos: np.ndarray  # i32[num_entities]
    passive_rows: np.ndarray  # i64[num_passive] global example rows
    num_global_features: int

    def _device_bucket_full(self, i: int) -> EntityBucket:
        """Per-bucket device-upload memo: every consumer (COO coordinates,
        the factored coordinate, stripped dense variants sharing the
        per-row leaves) resolves through ONE upload per bucket. A full
        bucket requested after its STRIPPED variant reuses the stripped
        upload's per-row leaves and only adds the COO arrays."""
        memo = self.__dict__.setdefault("_device_bucket_memo", {})
        hit = memo.get(i)
        if hit is None:
            stripped = self.__dict__.get(
                "_device_bucket_stripped_memo", {}
            ).get(i)
            b = self.buckets[i]
            if stripped is not None:
                hit = dataclasses.replace(
                    stripped,
                    values=jax.device_put(b.values),
                    rows=jax.device_put(b.rows),
                    cols=jax.device_put(b.cols),
                )
            else:
                hit = jax.device_put(b)
            memo[i] = hit
        return hit

    def device_buckets(self) -> tuple[EntityBucket, ...]:
        """Device copies of the buckets, uploaded once and cached — every
        coordinate/fit over this dataset shares one HBM copy."""
        return tuple(
            self._device_bucket_full(i) for i in range(len(self.buckets))
        )

    def dense_designs(self) -> tuple:
        """Per-bucket PACKED dense device designs as [E, R*K] rows
        (row-major per entity; solvers reshape inside jit — see
        coordinates._packed_dense_batch), or None where the COO layout
        wins — built host-side once, cached like device_buckets."""
        from photon_ml_tpu.game.coordinates import _bucket_dense_design

        cached = self.__dict__.get("_dense_designs")
        if cached is None:
            cached = tuple(
                None if x is None else jax.device_put(x)
                for x in (_bucket_dense_design(b) for b in self.buckets)
            )
            object.__setattr__(self, "_dense_designs", cached)
        return cached

    def device_buckets_for_dense(self) -> tuple[EntityBucket, ...]:
        """Device buckets with the COO arrays STRIPPED for buckets that
        solve on their dense design (the dense path never touches
        values/rows/cols — uploading them would double the HBM/transfer
        cost). Per-row leaves are SHARED with :meth:`device_buckets`'s
        uploads when those exist, so a dataset serving both a COO consumer
        (e.g. the factored coordinate) and a dense one holds one copy of
        everything and the full COO only where someone needs it."""
        cached = self.__dict__.get("_device_buckets_dense")
        if cached is None:
            dense = self.dense_designs()
            memo = self.__dict__.setdefault("_device_bucket_memo", {})
            smemo = self.__dict__.setdefault(
                "_device_bucket_stripped_memo", {}
            )
            out = []
            for i, (b, x) in enumerate(zip(self.buckets, dense)):
                if x is None:
                    out.append(self._device_bucket_full(i))
                    continue
                full = memo.get(i)
                if full is not None:
                    # COO already resident for another consumer — reuse
                    # its leaves, nothing new to upload
                    out.append(full)
                    continue
                # (1, 1) stubs: a per-entity (E, 1) placeholder would PAD
                # its lanes 1->128 on TPU — 70 MB of pure padding per stub
                # at 138K entities
                stub = np.zeros((1, 1), np.float32)
                stub_i = np.zeros((1, 1), np.int32)
                stripped = jax.device_put(
                    dataclasses.replace(
                        b, values=stub, rows=stub_i, cols=stub_i
                    )
                )
                smemo[i] = stripped  # later full requests reuse the leaves
                out.append(stripped)
            cached = tuple(out)
            object.__setattr__(self, "_device_buckets_dense", cached)
        return cached

    def to_summary_string(self) -> str:
        """RandomEffectDataSet.toSummaryString analog (:174-197): per-bucket
        geometry + active/passive split."""
        n_active = int(np.sum(self.entity_bucket >= 0))
        lines = [
            f"RandomEffectDataset(id={self.id_name}, shard={self.shard_name}, "
            f"active_entities={n_active}/{self.num_entities}, "
            f"passive_rows={len(self.passive_rows)})"
        ]
        for i, b in enumerate(self.buckets):
            lines.append(
                f"  bucket {i}: entities={b.num_entities} "
                f"rows/entity={b.rows_per_entity} "
                f"local_features={b.num_local_features} "
                f"nnz/entity={b.values.shape[1]}"
            )
        return "\n".join(lines)


_PEARSON_STD_EPS = 1e-8  # MathConst.MEDIUM_PRECISION_TOLERANCE_THRESHOLD


def _pearson_keep_mask(
    nv: np.ndarray,
    nc: np.ndarray,
    ne: np.ndarray,
    y_of_nnz: np.ndarray,
    y_act: np.ndarray,
    ent_of_row: np.ndarray,
    act_counts: np.ndarray,
    num_global: int,
    ratio: float,
) -> np.ndarray:
    """Keep mask over nnz: per entity, retain the top
    ceil(ratio * num_rows) features by |Pearson(feature, label)|.

    Vectorized analog of LocalDataSet.computePearsonCorrelationScore
    (LocalDataSet.scala:221-282) + featureSelectionOnActiveData
    (RandomEffectDataSet.scala:420-434): a near-constant feature is treated
    as the intercept — the FIRST such feature per entity scores 1, later
    duplicates 0. Sums follow the reference exactly (sparse sums; zero rows
    contribute only to the label moments).
    """
    n_ent = len(act_counts)
    # per-(entity, feature) sums over the entity's nnz
    pair_key = ne * np.int64(num_global) + nc
    uniq, inv = np.unique(pair_key, return_inverse=True)
    s_v = np.bincount(inv, weights=nv, minlength=len(uniq))
    s_vv = np.bincount(inv, weights=nv * nv, minlength=len(uniq))
    s_vy = np.bincount(inv, weights=nv * y_of_nnz, minlength=len(uniq))
    p_ent = (uniq // np.int64(num_global)).astype(np.int64)

    # per-entity label moments over ALL active rows
    n_e = act_counts.astype(np.float64)
    ly = np.bincount(ent_of_row, weights=y_act, minlength=n_ent)
    lyy = np.bincount(ent_of_row, weights=y_act * y_act, minlength=n_ent)

    n_p = n_e[p_ent]
    numerator = n_p * s_vy - s_v * ly[p_ent]
    std = np.sqrt(np.abs(n_p * s_vv - s_v * s_v))
    denominator = std * np.sqrt(
        np.maximum(n_p * lyy[p_ent] - ly[p_ent] ** 2, 0.0)
    )
    score = np.abs(numerator / (denominator + 1e-12))
    constant = std < _PEARSON_STD_EPS
    if np.any(constant):
        # first constant feature per entity acts as the intercept (score 1)
        c_idx = np.nonzero(constant)[0]
        first = np.zeros(len(uniq), bool)
        # uniq is sorted by (entity, col): the first constant per entity is
        # the one whose predecessor constant has a different entity
        is_first = np.ones(len(c_idx), bool)
        is_first[1:] = p_ent[c_idx[1:]] != p_ent[c_idx[:-1]]
        first[c_idx[is_first]] = True
        score = np.where(constant, np.where(first, 1.0, 0.0), score)

    # rank within entity by descending score; keep rank < ceil(ratio * n_e)
    order = np.lexsort((-score, p_ent))
    starts = np.searchsorted(p_ent[order], np.arange(n_ent))
    rank = np.empty(len(uniq), np.int64)
    rank[order] = np.arange(len(uniq)) - starts[p_ent[order]]
    k_e = np.ceil(ratio * n_e).astype(np.int64)
    keep_pair = rank < k_e[p_ent]
    return keep_pair[inv]


def build_random_effect_dataset(
    data: GameDataset,
    id_name: str,
    shard_name: str,
    active_rows_per_entity: Optional[int] = None,
    min_rows_per_entity: int = 1,
    features_to_samples_ratio: Optional[float] = None,
    seed: int = 0,
    dtype=jnp.float32,
) -> RandomEffectDataset:
    """Group, cap, project, and bucket one random-effect coordinate's data.

    Fully vectorized host build: sorting/searchsorted/bincount over bulk
    arrays with one small Python loop over geometry CLASSES (tens), never
    over entities — the ingest-rate answer to the reference's cluster-side
    groupByKey (RandomEffectDataSetPartitioner.scala:96-148). Builds 100K
    entities / 1M rows in seconds (tests/test_re_build.py measures).
    """
    if id_name not in data.id_columns:
        raise KeyError(f"unknown id column '{id_name}'; have {sorted(data.id_columns)}")
    idc = data.id_columns[id_name]
    batch = data.shard(shard_name)
    n = data.num_rows
    num_global = batch.num_features
    rng = np.random.default_rng(seed)

    np_dtype = np.dtype(dtype)
    vals = np.asarray(batch.values)
    rows = np.asarray(batch.rows)
    cols = np.asarray(batch.cols)
    # valid nnz only (value != 0 excludes padding); drop padded-row nnz
    live = (vals != 0) & (rows < n)
    vals, rows, cols = vals[live], rows[live], cols[live]

    codes = np.asarray(idc.codes)  # [n]

    # --- active/passive row selection (vectorized reservoir cap) ---
    # group rows by entity with a random within-group order: rank < cap keeps
    # a uniform sample per entity (the reservoir-with-rescale semantics of
    # RandomEffectDataSet.scala:294-357)
    rand_key = rng.random(n)
    grp_order = np.lexsort((rand_key, codes))  # entity-grouped, random within
    g_codes = codes[grp_order]
    uniq_codes, grp_starts, grp_counts = np.unique(
        g_codes, return_index=True, return_counts=True
    )
    ent_of_pos = np.searchsorted(uniq_codes, g_codes)
    rank_in_ent = np.arange(n) - grp_starts[ent_of_pos]

    counts_of_pos = grp_counts[ent_of_pos]
    active_pos = counts_of_pos >= min_rows_per_entity
    weights = data.weight.copy()
    cap = active_rows_per_entity
    if cap is not None:
        capped = counts_of_pos > cap
        active_pos &= ~capped | (rank_in_ent < cap)
        # weight rescale so the capped sample represents the full count
        resc = capped & (rank_in_ent < cap)
        weights[grp_order[resc]] *= counts_of_pos[resc] / cap
    act_rows_unsorted = grp_order[active_pos]
    passive_rows = np.sort(grp_order[~active_pos])

    # --- regroup active rows sorted by (entity, row id) ---
    act_codes_u = codes[act_rows_unsorted]
    o = np.lexsort((act_rows_unsorted, act_codes_u))
    act_rows = act_rows_unsorted[o]  # member rows, entity-major, row-sorted
    act_codes = act_codes_u[o]
    act_uniq, act_starts, act_counts = np.unique(
        act_codes, return_index=True, return_counts=True
    )
    n_act = len(act_rows)
    n_ent = len(act_uniq)
    ent_of_row = np.searchsorted(act_uniq, act_codes)  # [n_act]
    local_row = np.arange(n_act) - act_starts[ent_of_row]

    # per global row: its local row id and entity index (-1 if inactive)
    row_local = np.full(n, -1, np.int64)
    row_local[act_rows] = local_row
    row_ent = np.full(n, -1, np.int64)
    row_ent[act_rows] = ent_of_row

    # --- nnz of active rows, sorted by (entity, local row) ---
    keep_nnz = row_ent[rows] >= 0
    nv, nr, nc = vals[keep_nnz], rows[keep_nnz], cols[keep_nnz]
    ne = row_ent[nr]
    nlr = row_local[nr]
    o2 = np.lexsort((nlr, ne))  # segment_sum contract: rows sorted per entity
    nv, nc, ne, nlr, ngr = nv[o2], nc[o2], ne[o2], nlr[o2], nr[o2]

    if features_to_samples_ratio is not None:
        # per-entity Pearson feature selection for low-data entities
        # (RandomEffectDataSet.scala:420-434)
        keep = _pearson_keep_mask(
            nv,
            nc,
            ne,
            y_of_nnz=np.asarray(data.response)[ngr],
            y_act=np.asarray(data.response)[act_rows],
            ent_of_row=ent_of_row,
            act_counts=act_counts,
            num_global=num_global,
            ratio=float(features_to_samples_ratio),
        )
        nv, nc, ne, nlr = nv[keep], nc[keep], ne[keep], nlr[keep]

    nnz_counts = np.bincount(ne, minlength=n_ent).astype(np.int64)
    nnz_starts = np.concatenate([[0], np.cumsum(nnz_counts)[:-1]])
    slot = np.arange(len(nv)) - nnz_starts[ne]

    # --- per-entity projection: unique observed global cols ---
    pair_key = ne * np.int64(num_global) + nc
    uniq_pairs = np.unique(pair_key)
    proj_ent = uniq_pairs // num_global
    proj_col = (uniq_pairs % num_global).astype(np.int64)
    proj_counts = np.bincount(proj_ent, minlength=n_ent).astype(np.int64)
    proj_starts = np.concatenate([[0], np.cumsum(proj_counts)[:-1]])
    proj_slot = np.arange(len(uniq_pairs)) - proj_starts[proj_ent]
    # local col id of each nnz = rank of its col in its entity's projection
    local_col = np.searchsorted(uniq_pairs, pair_key) - nnz_starts_like(
        proj_starts, ne
    )

    # --- geometry classes ---
    Rs = _next_pow2_arr(act_counts)
    Ks = _next_pow2_arr(np.maximum(proj_counts, 1))
    NZs = _next_pow2_arr(np.maximum(nnz_counts, 1))
    geom = np.stack([Rs, Ks, NZs], axis=1)
    classes, class_of_ent = np.unique(geom, axis=0, return_inverse=True)
    # sort classes lexicographically by (R, K, NZ) to keep bucket order
    class_order = np.lexsort((classes[:, 2], classes[:, 1], classes[:, 0]))
    class_rank = np.empty(len(classes), np.int64)
    class_rank[class_order] = np.arange(len(classes))
    class_of_ent = class_rank[class_of_ent]
    classes = classes[class_order]

    # position of each entity within its bucket (order of appearance =
    # ascending entity code, since act_uniq is sorted)
    ent_pos = np.zeros(n_ent, np.int64)
    for b_idx in range(len(classes)):
        sel = class_of_ent == b_idx
        ent_pos[sel] = np.arange(int(sel.sum()))

    num_entities = idc.num_entities
    entity_bucket = np.full(num_entities, -1, np.int32)
    entity_pos = np.full(num_entities, -1, np.int32)
    entity_bucket[act_uniq] = class_of_ent
    entity_pos[act_uniq] = ent_pos

    response = data.response
    offset = data.offset

    buckets = []
    for b_idx, (R, K, NZ) in enumerate(classes):
        R, K, NZ = int(R), int(K), int(NZ)
        esel = class_of_ent == b_idx
        E = int(esel.sum())
        bcode = act_uniq[esel].astype(np.int32)

        bv = np.zeros((E, NZ))
        br = np.full((E, NZ), R - 1, np.int32)
        bc = np.zeros((E, NZ), np.int32)
        bl = np.zeros((E, R))
        bo = np.zeros((E, R))
        bw = np.zeros((E, R))
        bp = np.full((E, K), num_global, np.int32)
        brix = np.full((E, R), -1, np.int32)

        # rows of this class's entities
        rsel = esel[ent_of_row]
        d_e = ent_pos[ent_of_row[rsel]]
        d_r = local_row[rsel]
        src = act_rows[rsel]
        bl[d_e, d_r] = response[src]
        bo[d_e, d_r] = offset[src]
        bw[d_e, d_r] = weights[src]
        brix[d_e, d_r] = src

        # nnz of this class's entities
        zsel = esel[ne]
        z_e = ent_pos[ne[zsel]]
        z_s = slot[zsel]
        bv[z_e, z_s] = nv[zsel]
        br[z_e, z_s] = nlr[zsel]
        bc[z_e, z_s] = local_col[zsel]

        # projections of this class's entities
        psel = esel[proj_ent]
        p_e = ent_pos[proj_ent[psel]]
        p_s = proj_slot[psel]
        bp[p_e, p_s] = proj_col[psel]

        # leaves stay HOST numpy (transfer-free build; coordinates upload
        # once via RandomEffectDataset.device_buckets)
        buckets.append(
            EntityBucket(
                values=bv.astype(np_dtype),
                rows=br,
                cols=bc,
                labels=bl.astype(np_dtype),
                offsets=bo.astype(np_dtype),
                weights=bw.astype(np_dtype),
                projection=bp,
                entity_codes=bcode,
                row_index=brix,
                num_local_features=K,
                num_global_features=num_global,
            )
        )

    return RandomEffectDataset(
        id_name=id_name,
        shard_name=shard_name,
        buckets=tuple(buckets),
        num_entities=num_entities,
        entity_bucket=entity_bucket,
        entity_pos=entity_pos,
        passive_rows=passive_rows.astype(np.int64),
        num_global_features=num_global,
    )


def nnz_starts_like(starts: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Gather segment starts for each element's segment id."""
    return starts[idx]


def _next_pow2_arr(x: np.ndarray) -> np.ndarray:
    """Vectorized _next_pow2 over an int array."""
    x = np.asarray(x, np.int64)
    out = np.ones_like(x)
    nz = x > 1
    out[nz] = 1 << np.ceil(np.log2(x[nz])).astype(np.int64)
    return out
