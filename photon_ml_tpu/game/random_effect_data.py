"""Random-effect datasets: per-entity grouping, size bucketing, and
per-entity feature projection — the TPU answer to the reference's
RandomEffectDataSet + RandomEffectDataSetPartitioner + IndexMapProjector
(photon-api data/RandomEffectDataSet.scala:45-435,
data/RandomEffectDataSetPartitioner.scala:42-148,
projector/IndexMapProjectorRDD.scala:27-77).

Where Spark bin-packs entities into JVM partitions and runs heterogeneous
per-entity solves, XLA needs fixed shapes: entities are grouped into
geometry buckets keyed by (rows, nnz, local-feature-count) rounded up to
powers of two. Each bucket is a stack of same-shaped per-entity sparse
problems solved by ONE vmapped optimizer call; bucket count is
O(log^3 of the size spread), bounding recompilation.

Per-entity index-map projection (the reference's key scaling trick —
projector/README.md says it reaches ~1e8 entities x ~1e3 features): each
entity's observed global feature ids become local ids 0..K-1 via the sorted
array ``projection``; the tiny K-dim local solve never touches the global
feature space.

Active-data caps use reservoir sampling with weight rescaling, matching
RandomEffectDataSet.scala:294-357; rows beyond the cap become passive data
(scored but not trained on; :368-409).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.ops.sparse import SparseBatch

Array = jax.Array


def _next_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x - 1).bit_length())


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EntityBucket:
    """A stack of E same-geometry per-entity sparse problems (LOCAL feature
    ids). Padding: rows -> R-1 with value 0; weights 0 on padded rows;
    projection -> num_global (sentinel past any feature id)."""

    values: Array  # f[E, nnz]
    rows: Array  # i32[E, nnz] local row ids
    cols: Array  # i32[E, nnz] LOCAL feature ids
    labels: Array  # f[E, R]
    offsets: Array  # f[E, R] base offsets
    weights: Array  # f[E, R]
    projection: Array  # i32[E, K] sorted global feature id per local id
    entity_codes: Array  # i32[E]; -1 padding entity
    row_index: Array  # i32[E, R] global example row; -1 padding
    num_local_features: int = dataclasses.field(metadata=dict(static=True))
    num_global_features: int = dataclasses.field(metadata=dict(static=True))

    @property
    def num_entities(self) -> int:
        return self.entity_codes.shape[0]

    @property
    def rows_per_entity(self) -> int:
        return self.labels.shape[1]

    def entity_batch(self) -> SparseBatch:
        """View as a SparseBatch with leading entity axis, for vmap."""
        return SparseBatch(
            values=self.values,
            rows=self.rows,
            cols=self.cols,
            labels=self.labels,
            offsets=self.offsets,
            weights=self.weights,
            num_features=self.num_local_features,
        )

    def with_extra_offsets(self, per_row: Array) -> "EntityBucket":
        """Add residual scores (global [n] array) to this bucket's offsets
        via row_index gather — the addScoresToOffsets analog."""
        extra = jnp.where(
            self.row_index >= 0,
            jnp.take(per_row, jnp.maximum(self.row_index, 0), fill_value=0),
            0.0,
        )
        return dataclasses.replace(self, offsets=self.offsets + extra)


@dataclasses.dataclass(frozen=True)
class RandomEffectDataset:
    """All buckets for one random-effect coordinate, plus entity placement.

    ``entity_bucket``/``entity_pos`` map entity code -> (bucket idx,
    position) for model lookup; -1 for entities with no active data.
    ``passive_rows`` are example rows excluded from training by the
    active-data cap, still scored at CD time.
    """

    id_name: str
    shard_name: str
    buckets: tuple[EntityBucket, ...]
    num_entities: int
    entity_bucket: np.ndarray  # i32[num_entities]
    entity_pos: np.ndarray  # i32[num_entities]
    passive_rows: np.ndarray  # i64[num_passive] global example rows
    num_global_features: int


def build_random_effect_dataset(
    data: GameDataset,
    id_name: str,
    shard_name: str,
    active_rows_per_entity: Optional[int] = None,
    min_rows_per_entity: int = 1,
    seed: int = 0,
    dtype=jnp.float32,
) -> RandomEffectDataset:
    """Group, cap, project, and bucket one random-effect coordinate's data."""
    if id_name not in data.id_columns:
        raise KeyError(f"unknown id column '{id_name}'; have {sorted(data.id_columns)}")
    idc = data.id_columns[id_name]
    batch = data.shard(shard_name)
    n = data.num_rows
    num_global = batch.num_features
    rng = np.random.default_rng(seed)

    vals = np.asarray(batch.values)
    rows = np.asarray(batch.rows)
    cols = np.asarray(batch.cols)
    # valid nnz only (value != 0 excludes padding)
    live = vals != 0
    vals, rows, cols = vals[live], rows[live], cols[live]
    # keep only nnz of real (non-padded) example rows
    in_range = rows < n
    vals, rows, cols = vals[in_range], rows[in_range], cols[in_range]

    # --- group example rows by entity ---
    codes = idc.codes  # [n]
    order = np.argsort(codes, kind="stable")
    sorted_codes = codes[order]
    uniq_codes, starts = np.unique(sorted_codes, return_index=True)
    ends = np.append(starts[1:], n)

    weights = data.weight.copy()
    active_sel_per_entity: dict[int, np.ndarray] = {}
    passive: list[np.ndarray] = []
    for code, s, e in zip(uniq_codes, starts, ends):
        members = order[s:e]
        if len(members) < min_rows_per_entity:
            passive.append(members)
            continue
        cap = active_rows_per_entity
        if cap is not None and len(members) > cap:
            keep = rng.choice(members, size=cap, replace=False)
            keep_set = np.zeros(n, bool)
            keep_set[keep] = True
            dropped = members[~keep_set[members]]
            passive.append(dropped)
            # weight rescale so the capped sample represents the full count
            # (RandomEffectDataSet.scala:294-357)
            weights[keep] *= len(members) / cap
            members = np.sort(keep)
        active_sel_per_entity[int(code)] = members

    # --- per-entity projection + geometry ---
    nnz_by_row_order = np.argsort(rows, kind="stable")
    r_sorted = rows[nnz_by_row_order]
    row_nnz_starts = np.searchsorted(r_sorted, np.arange(n))
    row_nnz_ends = np.searchsorted(r_sorted, np.arange(n) + 1)

    entities = []
    for code, members in active_sel_per_entity.items():
        nnz_idx = np.concatenate(
            [nnz_by_row_order[row_nnz_starts[m]: row_nnz_ends[m]] for m in members]
        ) if len(members) else np.zeros(0, np.int64)
        g_cols = cols[nnz_idx]
        proj = np.unique(g_cols)  # sorted global ids observed by this entity
        entities.append(
            dict(
                code=code,
                members=members,
                nnz_idx=nnz_idx,
                proj=proj,
                R=_next_pow2(len(members)),
                K=_next_pow2(max(len(proj), 1)),
                NZ=_next_pow2(max(len(nnz_idx), 1)),
            )
        )

    # --- bucket by geometry class ---
    by_class: dict[tuple[int, int, int], list[dict]] = {}
    for ent in entities:
        by_class.setdefault((ent["R"], ent["K"], ent["NZ"]), []).append(ent)

    buckets = []
    num_entities = idc.num_entities
    entity_bucket = np.full(num_entities, -1, np.int32)
    entity_pos = np.full(num_entities, -1, np.int32)

    for b_idx, ((R, K, NZ), ents) in enumerate(sorted(by_class.items())):
        E = len(ents)
        bv = np.zeros((E, NZ))
        br = np.full((E, NZ), R - 1, np.int32)
        bc = np.zeros((E, NZ), np.int32)
        bl = np.zeros((E, R))
        bo = np.zeros((E, R))
        bw = np.zeros((E, R))
        bp = np.full((E, K), num_global, np.int32)
        bcode = np.zeros(E, np.int32)
        brix = np.full((E, R), -1, np.int32)
        for i, ent in enumerate(ents):
            m = ent["members"]
            nz = ent["nnz_idx"]
            local_row_of = {int(g): j for j, g in enumerate(m)}
            bv[i, : len(nz)] = vals[nz]
            br[i, : len(nz)] = [local_row_of[int(r)] for r in rows[nz]]
            bc[i, : len(nz)] = np.searchsorted(ent["proj"], cols[nz])
            bl[i, : len(m)] = data.response[m]
            bo[i, : len(m)] = data.offset[m]
            bw[i, : len(m)] = weights[m]
            bp[i, : len(ent["proj"])] = ent["proj"]
            bcode[i] = ent["code"]
            brix[i, : len(m)] = m
            entity_bucket[ent["code"]] = b_idx
            entity_pos[ent["code"]] = i
        # sort nnz within each entity by local row (segment_sum contract)
        for i in range(E):
            o = np.argsort(br[i], kind="stable")
            bv[i], br[i], bc[i] = bv[i][o], br[i][o], bc[i][o]
        buckets.append(
            EntityBucket(
                values=jnp.asarray(bv, dtype),
                rows=jnp.asarray(br),
                cols=jnp.asarray(bc),
                labels=jnp.asarray(bl, dtype),
                offsets=jnp.asarray(bo, dtype),
                weights=jnp.asarray(bw, dtype),
                projection=jnp.asarray(bp),
                entity_codes=jnp.asarray(bcode),
                row_index=jnp.asarray(brix),
                num_local_features=K,
                num_global_features=num_global,
            )
        )

    return RandomEffectDataset(
        id_name=id_name,
        shard_name=shard_name,
        buckets=tuple(buckets),
        num_entities=num_entities,
        entity_bucket=entity_bucket,
        entity_pos=entity_pos,
        passive_rows=(
            np.concatenate(passive) if passive else np.zeros(0, np.int64)
        ),
        num_global_features=num_global,
    )
