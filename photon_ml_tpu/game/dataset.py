"""GAME dataset: columnar layout of scored examples with id columns.

Reference analog: GameDatum (photon-lib data/GameDatum.scala:33-55) and the
DataFrame->RDD[(uniqueId, GameDatum)] conversion (photon-client
data/GameConverters.scala:38-110). Instead of an RDD of per-example objects,
examples live in columnar arrays indexed by a dense uniqueId = row position:
response/offset/weight vectors, one SparseBatch per feature shard (all
row-aligned), and integer-coded id columns (entity keys) with host-side
vocabularies. Scores and residuals are then plain [n] device arrays — the
KeyValueScore analog (photon-lib data/KeyValueScore.scala) is vector
addition, no joins.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu.ops.sparse import SparseBatch


@dataclasses.dataclass(frozen=True)
class IdColumn:
    """An entity-id column: dense integer codes + the value vocabulary."""

    codes: np.ndarray  # int64[n] index into vocab
    vocab: np.ndarray  # unique original values (any dtype), code -> value

    @property
    def num_entities(self) -> int:
        return len(self.vocab)

    @staticmethod
    def from_values(values: Sequence) -> "IdColumn":
        vocab, codes = np.unique(np.asarray(values), return_inverse=True)
        return IdColumn(codes=codes.astype(np.int64), vocab=vocab)


@dataclasses.dataclass(frozen=True)
class GameDataset:
    """Row-aligned columnar GAME data.

    ``feature_shards`` maps shard name -> SparseBatch whose rows align with
    the response arrays (the featureShardContainer analog); ``id_columns``
    maps id type (e.g. 'userId') -> IdColumn. Row padding conventions follow
    SparseBatch (padded rows have weight 0).
    """

    response: np.ndarray  # f64[n]
    offset: np.ndarray  # f64[n]
    weight: np.ndarray  # f64[n]
    feature_shards: Mapping[str, SparseBatch]
    id_columns: Mapping[str, IdColumn]

    @property
    def num_rows(self) -> int:
        return len(self.response)

    def shard(self, name: str) -> SparseBatch:
        if name not in self.feature_shards:
            raise KeyError(
                f"unknown feature shard '{name}'; have {sorted(self.feature_shards)}"
            )
        return self.feature_shards[name]

    def device_shard(self, name: str) -> SparseBatch:
        """Device copy of a shard, uploaded once and cached — scoring in
        the CD loop reuses one HBM copy instead of re-uploading host
        leaves every call."""
        cache = self.__dict__.setdefault("_device_shards", {})
        hit = cache.get(name)
        if hit is None:
            hit = self.shard(name).device()
            cache[name] = hit
        return hit

    def batch_for(
        self, shard_name: str, extra_offsets: Optional[np.ndarray] = None
    ) -> SparseBatch:
        """Shard batch with (response, offset [+extra], weight) attached."""
        b = self.shard(shard_name)
        off = self.offset if extra_offsets is None else self.offset + extra_offsets
        n_pad = b.num_rows

        def pad(a, fill=0.0):
            out = np.full((n_pad,), fill)
            out[: self.num_rows] = a
            return out.astype(b.dtype)  # host; consumers upload once

        return dataclasses.replace(
            b,
            labels=pad(self.response),
            offsets=pad(off),
            weights=pad(self.weight),
        )


def build_game_dataset(
    response: np.ndarray,
    feature_shards: Mapping[str, SparseBatch],
    id_columns: Optional[Mapping[str, Sequence]] = None,
    offset: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
) -> GameDataset:
    n = len(response)
    for name, b in feature_shards.items():
        if b.num_rows < n:
            raise ValueError(
                f"feature shard '{name}' has {b.num_rows} rows < {n} examples"
            )
    # All score/residual paths combine per-shard [n_pad] vectors, so every
    # shard must share one padded row count — normalize to the max.
    n_pad = max(b.num_rows for b in feature_shards.values())
    feature_shards = {
        name: (b if b.num_rows == n_pad else b.pad_rows_to(n_pad, b.nnz))
        for name, b in feature_shards.items()
    }
    return GameDataset(
        response=np.asarray(response, np.float64),
        offset=np.zeros(n) if offset is None else np.asarray(offset, np.float64),
        weight=np.ones(n) if weight is None else np.asarray(weight, np.float64),
        feature_shards=dict(feature_shards),
        id_columns={
            k: v if isinstance(v, IdColumn) else IdColumn.from_values(v)
            for k, v in (id_columns or {}).items()
        },
    )
