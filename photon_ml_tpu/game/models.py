"""GAME model containers: fixed-effect, random-effect, and the composite
GAME model whose score is the sum of sub-model scores.

Reference analog: photon-lib model/GAMEModel.scala:32-188 (sum-of-scores at
:125-127, single-task enforcement at :181-187), photon-api
model/{FixedEffectModel,RandomEffectModel}.scala. Sub-model scores are raw
margins x.w (no offsets, no link), matching DatumScoringModel semantics —
offsets enter only through training objectives and evaluator inputs.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.ops.losses import get_loss

Array = jax.Array

#: nnz processed per device dispatch in RandomEffectModel.score — module
#: level (not a local) so tests can shrink it to exercise the chunk
#: boundary without 8M-nnz fixtures.
SCORE_CHUNK = 8_000_000


def map_vocab_codes(vocab: np.ndarray, values: np.ndarray) -> np.ndarray:
    """Map raw id values to codes in a (sorted unique) vocabulary; -1 for
    values the vocabulary has never seen. Entity identity is the id VALUE,
    not a dataset-local integer code (the RDD analog joins by id string)."""
    pos = np.searchsorted(vocab, values)
    pos_c = np.minimum(pos, len(vocab) - 1)
    hit = vocab[pos_c] == values
    return np.where(hit, pos_c, -1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global GLM coefficients over one feature shard (original space)."""

    coefficients: Array  # f[num_features]
    shard_name: str = dataclasses.field(metadata=dict(static=True))

    def score(self, data: GameDataset) -> Array:
        """Raw scores x.w for every example row ([n_pad] aligned array)."""
        return data.device_shard(self.shard_name).dot_rows(self.coefficients)

    def to_summary_string(self) -> str:
        w = np.asarray(self.coefficients)
        nnz = int(np.sum(np.abs(w) > 1e-9))
        return (
            f"FixedEffectModel(shard={self.shard_name}, features={len(w)}, "
            f"nonzero={nnz}, |w|2={float(np.linalg.norm(w)):.4g})"
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RandomEffectBucketModel:
    """Per-entity coefficients for one geometry bucket, aligned with the
    bucket's sorted projection (local id k <-> global feature projection[k]).

    ``variances`` (optional) are per-coefficient posterior variances from the
    Hessian-diagonal inverse at each entity's optimum — the computeVariances
    path of SingleNodeOptimizationProblem.scala:57-88; entries for padded
    local features (projection == sentinel) are meaningless.
    """

    coefficients: Array  # f[E, K]
    projection: Array  # i32[E, K] sorted global ids; sentinel = num_global
    entity_codes: Array  # i32[E]
    variances: Optional[Array] = None  # f[E, K] when computed


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """All per-entity models for one random-effect coordinate.

    The coefficient table is sharded across buckets exactly as the training
    data was (model co-located with its entity's data — the bin-packing
    co-partitioning analog, RandomEffectOptimizationProblem.scala:28-131).
    """

    id_name: str
    shard_name: str
    buckets: tuple[RandomEffectBucketModel, ...]
    entity_bucket: np.ndarray  # host: TRAINING entity code -> bucket (-1 none)
    entity_pos: np.ndarray
    vocab: np.ndarray  # training id vocabulary (sorted unique values)

    def _codes_for(self, data: GameDataset) -> np.ndarray:
        """Map a dataset's entity VALUES to training codes (-1 if unseen)."""
        return self._grouping_for(data)[0]

    def _grouping_for(
        self, data: GameDataset
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(codes, row_bucket, row_pos) host arrays for ``data`` — the
        O(n log V) vocabulary join and bucket/position placement.

        Memoized per (model, dataset): repeated scoring of the same
        dataset (validation every CD iteration, the serving registry's
        parity checks) must not redo the host-side numpy work. The cache
        lives on the dataset (like ``device_shard``) keyed by id column,
        and is validated by TABLE IDENTITY — a different model object with
        its own vocab/placement recomputes instead of reusing stale
        arrays. Hits/misses are ``scoring.code_cache.{hits,misses}``.
        """
        cache = data.__dict__.setdefault("_re_group_cache", {})
        # keyed by (id column, vocab identity) so two coordinates sharing
        # an id column keep separate entries instead of thrashing one; the
        # entry pins the vocab object, so its id() cannot be recycled
        key = (self.id_name, id(self.vocab))
        entry = cache.get(key)
        if (
            entry is not None
            and entry["vocab"] is self.vocab
            and entry["entity_bucket"] is self.entity_bucket
            and entry["entity_pos"] is self.entity_pos
        ):
            telemetry.counter("scoring.code_cache.hits").inc()
            return entry["codes"], entry["row_bucket"], entry["row_pos"]
        telemetry.counter("scoring.code_cache.misses").inc()
        idc = data.id_columns[self.id_name]
        codes = map_vocab_codes(self.vocab, idc.vocab[idc.codes])
        known = codes >= 0
        safe_codes = np.where(known, codes, 0)
        row_bucket = np.where(known, self.entity_bucket[safe_codes], -1)
        row_pos = np.where(known, self.entity_pos[safe_codes], -1)
        cache[key] = {
            "vocab": self.vocab,
            "entity_bucket": self.entity_bucket,
            "entity_pos": self.entity_pos,
            "codes": codes,
            "row_bucket": row_bucket,
            "row_pos": row_pos,
        }
        return codes, row_bucket, row_pos

    def to_summary_string(self) -> str:
        n_models = int(np.sum(self.entity_bucket >= 0))
        dims = [int(b.coefficients.shape[1]) for b in self.buckets]
        return (
            f"RandomEffectModel(id={self.id_name}, shard={self.shard_name}, "
            f"entities={n_models}/{len(self.vocab)}, "
            f"buckets={len(self.buckets)}, local_dims={dims})"
        )

    def score(self, data: GameDataset) -> Array:
        """Scores for every example row; entities without a model score 0.

        Device kernel per bucket: rows are grouped by entity bucket on host,
        then each nnz looks up its coefficient by binary search over the
        entity's sorted projection (searchsorted), multiplies and
        segment-sums. Entities unseen in training contribute nothing —
        matching the reference's behavior of scoring only entities with
        models (RandomEffectModel joins by entity id).
        """
        if data.id_columns.get(self.id_name) is None:
            raise KeyError(f"scoring data lacks id column '{self.id_name}'")
        batch = data.shard(self.shard_name)
        n = data.num_rows
        # host [n] arrays, -1 for unseen entities; memoized per dataset
        _codes, row_bucket, row_pos = self._grouping_for(data)

        vals = np.asarray(batch.values)
        rows = np.asarray(batch.rows)
        cols = np.asarray(batch.cols)
        live = (vals != 0) & (rows < n)

        # nnz are processed in bounded chunks: the per-nnz [*, K] / [K, *]
        # gathers otherwise materialize O(total_nnz x 128)-padded fusion
        # outputs (a 20M-row shard measured a 51 GB allocation attempt)
        scores = jnp.zeros((batch.num_rows,), dtype=batch.dtype)
        for b_idx, bm in enumerate(self.buckets):
            sel = live & (row_bucket[np.minimum(rows, n - 1)] == b_idx)
            if not np.any(sel):
                continue
            sel_idx = np.nonzero(sel)[0]
            K = bm.projection.shape[1]
            for lo in range(0, len(sel_idx), SCORE_CHUNK):
                part = sel_idx[lo:lo + SCORE_CHUNK]
                v = jnp.asarray(vals[part], batch.dtype)
                r = jnp.asarray(rows[part], jnp.int32)
                g = jnp.asarray(cols[part], jnp.int32)
                pos = jnp.asarray(row_pos[rows[part]], jnp.int32)

                if K <= 64:
                    # TRANSPOSED compare-scan: [K, m] keeps the long nnz
                    # dim in lanes (a [m, K] gather pads lanes 128/K-fold
                    # — at K=4 that is 32x pure padding); each column
                    # matches at most one projection slot, so the masked
                    # sum IS the lookup
                    proj_t = jnp.asarray(bm.projection).T[:, pos]  # [K, m]
                    coef_t = bm.coefficients.T[:, pos]  # [K, m]
                    w = jnp.sum(
                        jnp.where(proj_t == g[None, :], coef_t, 0.0),
                        axis=0,
                    )
                else:
                    proj_rows = bm.projection[pos]  # [m, K]
                    k = jax.vmap(jnp.searchsorted)(proj_rows, g)  # [m]
                    k = jnp.minimum(k, K - 1)
                    hit = (
                        jnp.take_along_axis(
                            proj_rows, k[:, None], axis=1
                        )[:, 0]
                        == g
                    )
                    w = jnp.where(
                        hit,
                        jnp.take_along_axis(
                            bm.coefficients[pos], k[:, None], axis=1
                        )[:, 0],
                        0.0,
                    )
                scores = scores.at[r].add(v * w)
        return scores


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Named sub-models; score = sum of sub-model scores (GAMEModel:125-127).
    All coordinates share one task type (GAMEModel.scala:181-187)."""

    task: str
    models: Mapping[str, object]  # name -> FixedEffectModel | RandomEffectModel

    def __post_init__(self):
        get_loss(self.task)

    def score(self, data: GameDataset) -> Array:
        total = None
        for model in self.models.values():
            s = model.score(data)
            total = s if total is None else total + s
        if total is None:
            raise ValueError("GAME model has no sub-models")
        return total

    def predict_mean(self, data: GameDataset) -> Array:
        raw = self.score(data)
        scores = raw + jnp.asarray(
            np.pad(data.offset, (0, raw.shape[0] - data.num_rows))
        ).astype(jnp.float32)
        name = get_loss(self.task).name
        if name == "logistic":
            return jax.nn.sigmoid(scores)
        if name == "poisson":
            return jnp.exp(scores)
        return scores

    def with_model(self, name: str, model) -> "GameModel":
        new = dict(self.models)
        new[name] = model
        return dataclasses.replace(self, models=new)

    def to_summary_string(self) -> str:
        """Structured one-summary-per-sub-model log string (the reference's
        toSummaryString protocol, e.g. GAMEModel/RandomEffectDataSet
        .toSummaryString)."""
        lines = [f"GameModel(task={self.task}, coordinates={len(self.models)})"]
        for name, sub in self.models.items():
            summary = (
                sub.to_summary_string()
                if hasattr(sub, "to_summary_string")
                else repr(sub)
            )
            lines.append(f"  {name}: {summary}")
        return "\n".join(lines)
