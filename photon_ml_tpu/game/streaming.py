"""Billion-coefficient random-effect training: resident sharded coefficient
tables + streamed entity chunks.

The reference's defining scale claim is "hundreds of billions of
coefficients" across per-entity models (/root/reference/README.md:73;
projection envelope ~1e8 entities x ~1e3 features/entity,
photon-ml projector/README.md:8-12), held as RDD partitions across a Spark
cluster. The TPU-native answer:

  - The COEFFICIENT TABLE [N, K] is HBM-resident for the whole fit (4 GB
    per 1e9 f32 coefficients — one v5e chip holds ~2-3e9 alongside its
    working set; a mesh shards the entity axis so capacity scales linearly
    with devices, the multi-host path to 1e11).
  - The TRAINING DATA does not fit (a dense [N, R, K] stack is R*4 bytes
    per coefficient) and never has to: per-entity problems are
    independent, so entities stream through in CHUNKS. Chunk i+1's data is
    enqueued (host `device_put` or an on-device generator) before chunk
    i's solve is awaited — JAX's async dispatch overlaps the transfer with
    the compute, the streaming analog of Spark pipelining a partition
    fetch behind a partition solve.
  - Each chunk is ONE vmapped optimizer call on the dense local-design
    layout (ops/dense.DenseBatch — pure MXU-batched matmul sweeps, no
    random access); under a mesh the chunk is committed with
    ``parallel.sharding.entity_sharding`` (the reusable P("model")
    primitive shared with the RE bucket solves and, per ROADMAP item 4,
    sharded serving) and GSPMD partitions the vmap lanes — the only
    collective is the one-scalar convergence test per iteration
    (RandomEffectCoordinate.scala:101-130 semantics).

``bench_scale.py`` drives this at ~1e9 coefficients on one chip;
``__graft_entry__.dryrun_multichip`` runs the sharded-table path on the
virtual CPU mesh.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import lru_cache, partial
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.parallel import sharding as psharding
from photon_ml_tpu.telemetry.xla import record_collective
from photon_ml_tpu.telemetry import memory as telemetry_memory
from photon_ml_tpu.ops.dense import DenseBatch
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optim.factory import OptimizerConfig
from photon_ml_tpu.optim.guard import GuardSpec, damped_objective, solve_health

Array = jax.Array

logger = logging.getLogger("photon_ml_tpu.game.streaming")

# fault-injection seams (photon_ml_tpu.faults): a chunk solve whose result
# can be NaN-poisoned on demand (drives the guard's damped-retry/rollback
# machinery deterministically) and the chunk boundary where checkpoint +
# stop handling runs (an injected raise here must leave a resumable
# directory behind)
_FP_SOLVE_RESULT = faults.register_point(
    "streaming.solve.result",
    description="chunk solve output (nan action poisons w for the guard)",
)
_FP_CHUNK_BOUNDARY = faults.register_point(
    "streaming.chunk.boundary",
    description="between a chunk solve and its checkpoint/stop handling",
)
# the fleet seam shared with the GSPMD solve dispatch: the last host-side
# instruction before a chunk solve's cross-process collective program
from photon_ml_tpu.parallel.distributed import FP_COLLECTIVE_ENTRY  # noqa: E402
from photon_ml_tpu.parallel.multihost import collective_wait  # noqa: E402


@lru_cache(maxsize=16)
def _chunk_writer(donate: bool):
    def write(table, w, start):
        return jax.lax.dynamic_update_slice(
            table, w.astype(table.dtype), (start, 0)
        )

    # multi_shape: the tail chunk is legitimately smaller than the rest
    return telemetry.instrumented_jit(
        write,
        name="streaming_chunk_write",
        multi_shape=True,
        donate_argnums=(0,) if donate else (),
    )


def _read_chunk(table, start: int, size: int) -> Array:
    return jax.lax.dynamic_slice(table, (start, 0), (size, table.shape[1]))


class ShardedCoefficientTable:
    """HBM-resident [N, K] coefficient table, chunk-updated in place.

    Updates donate the table buffer, so the table is never duplicated in
    HBM. With ``mesh`` the entity axis is sharded (NamedSharding P(axis))
    — per-device residency N*K*4/n_devices bytes. The per-entity SOLVES
    are collective-free (independent problems under shard_map); chunk
    read/write slices may reshard between the chunk's P(axis) layout and
    the table's, which XLA lowers to the minimal ICI exchange.
    """

    def __init__(
        self,
        num_entities: int,
        dim: int,
        mesh: Optional[Mesh] = None,
        axis: Optional[str] = None,
        dtype=jnp.float32,
    ):
        self.num_entities = int(num_entities)
        self.dim = int(dim)
        self.mesh = mesh
        if mesh is None:
            self.axis = axis
            self.sharding = None
            self.coefficients = jnp.zeros((num_entities, dim), dtype)
        else:
            # the ONE entity-sharding definition (parallel.sharding):
            # training tables, bucket solves and sharded serving all place
            # through it, so their shards line up across the mesh
            self.sharding = psharding.entity_sharding(mesh, axis)
            self.axis = self.sharding.spec[0]
            n_dev = psharding.axis_size(mesh, self.axis)
            if num_entities % n_dev:
                raise ValueError(
                    f"num_entities={num_entities} must divide over the "
                    f"{n_dev}-device '{self.axis}' axis (pad the entity "
                    "count)"
                )
            # jit-with-out_shardings materializes the zeros directly in
            # their sharded layout — no host/full-device copy, and it is
            # multi-controller-safe (every process runs the same program
            # and owns only its shards).
            # multi_shape: each table instance is its own executable by
            # design (a fresh closure per table) — not a recompile storm
            self.coefficients = telemetry.instrumented_jit(
                partial(jnp.zeros, (num_entities, dim), dtype),
                name="streaming_table_init",
                multi_shape=True,
                out_shardings=self.sharding,
            )()

    @classmethod
    def from_coefficients(
        cls,
        coefficients: Array,
        mesh: Optional[Mesh] = None,
        axis: Optional[str] = None,
    ) -> "ShardedCoefficientTable":
        """Wrap an ALREADY-PLACED [N, K] device array (e.g. an elastic
        checkpoint restore via
        ``StreamingCheckpointManager.restore_placed``) without the zero
        init + overwrite a construct-then-write resume would pay."""
        table = cls.__new__(cls)
        table.num_entities = int(coefficients.shape[0])
        table.dim = int(coefficients.shape[1])
        table.mesh = mesh
        if mesh is None:
            table.axis = axis
            table.sharding = None
        else:
            table.sharding = psharding.entity_sharding(mesh, axis)
            table.axis = table.sharding.spec[0]
            n_dev = psharding.axis_size(mesh, table.axis)
            if table.num_entities % n_dev:
                raise ValueError(
                    f"num_entities={table.num_entities} must divide over "
                    f"the {n_dev}-device '{table.axis}' axis"
                )
            if coefficients.sharding != table.sharding:
                coefficients = jax.device_put(coefficients, table.sharding)
        table.coefficients = coefficients
        return table

    @property
    def nbytes(self) -> int:
        return self.num_entities * self.dim * self.coefficients.dtype.itemsize

    def _check_bounds(self, start: int, size: int) -> None:
        # dynamic_(update_)slice silently CLAMPS an out-of-range start, which
        # would read/write the wrong entity rows — fail loudly instead.
        if start < 0 or size < 0 or start + size > self.num_entities:
            raise ValueError(
                f"chunk [{start}, {start + size}) out of bounds for table "
                f"of {self.num_entities} entities"
            )

    def write_chunk(self, start: int, w: Array) -> None:
        self._check_bounds(start, int(w.shape[0]))
        self.coefficients = _chunk_writer(True)(
            self.coefficients, w, jnp.int32(start)
        )

    def read_chunk(self, start: int, size: int) -> Array:
        self._check_bounds(start, size)
        return _read_chunk(self.coefficients, jnp.int32(start), size)

    def to_numpy(self) -> np.ndarray:
        """Full table on the host — models/summaries/tests only. At
        scale the table never belongs on the host: checkpointing hands
        ``coefficients`` to ``StreamingCheckpointManager``, which saves
        one addressable shard at a time."""
        from photon_ml_tpu.parallel.multihost import gather_to_host

        return gather_to_host(self.coefficients)


@dataclasses.dataclass
class LocalChunk:
    """A chunk supplied as PROCESS-LOCAL rows in a multi-host fleet.

    Each process passes only the entities it ingested (its
    ``process_slice`` of the chunk's global [start, start+global_size)
    range); the trainer assembles the global sharded batch with
    ``make_array_from_process_local_data`` — no host ever holds the whole
    chunk. This is the executor-local-partition analog
    (RandomEffectDataSet.scala:209-246 reads per-partition on executors).
    """

    batch: DenseBatch  # numpy leaves, leading dim = this process's rows
    global_size: int  # entities in the chunk across ALL processes


@dataclasses.dataclass
class ChunkResult:
    """Per-chunk telemetry, kept ON DEVICE until summarized."""

    start: int
    size: int
    iterations: Array  # i32[E_c]
    values: Array  # f32[E_c]
    reasons: Array  # i32[E_c] convergence reason codes


@dataclasses.dataclass
class StreamingTrainStats:
    total_entities: int
    total_coefficients: int
    num_chunks: int
    mean_iterations: float
    total_final_value: float
    # full per-entity solve telemetry (iterations/reasons/values, one
    # packed host fetch) — the RandomEffectOptimizationTracker the bucket
    # path reports, at streaming scale
    tracker: Optional["RandomEffectOptimizationTracker"] = None


class StreamingRandomEffectTrainer:
    """Drive a :class:`ShardedCoefficientTable` through streamed chunks.

    ``chunks`` yields ``(start, batch_source)`` where ``batch_source`` is
    either a DenseBatch of HOST (numpy) arrays — uploaded with
    ``device_put`` one chunk ahead of the solve — or a zero-arg callable
    returning a device DenseBatch (an on-device generator; used by the 1B
    bench because the tunnel link makes bulk H2D impractical, and by any
    caller whose features are computed rather than stored).
    """

    def __init__(
        self,
        loss_name: str,
        config: OptimizerConfig,
        mesh: Optional[Mesh] = None,
        axis: Optional[str] = None,
        compute_variances: bool = False,
        prefetch: bool = True,
        prefetch_depth: int = 1,
        guard: Optional[GuardSpec] = None,
        feed_retries: int = 2,
    ):
        # the vmapped per-entity solver builder is shared with
        # RandomEffectCoordinate — one lru_cache entry serves both, and
        # the SAME compiled family serves mesh and single-device calls
        # (sharded dispatch signatures are distinct registry entries)
        from photon_ml_tpu.game.coordinates import _re_solver
        from photon_ml_tpu.ops.losses import get_loss

        config.validate(loss_name)
        if compute_variances and not get_loss(loss_name).has_hessian:
            raise ValueError(
                "coefficient variances need a twice-differentiable loss; "
                f"'{loss_name}' is not"
            )
        self.loss_name = loss_name
        self.config = config
        self.mesh = mesh
        self.compute_variances = compute_variances
        # chunk feeding runs through ingest.double_buffered: a background
        # feeder thread prepares (decodes/uploads) up to ``prefetch_depth``
        # chunks ahead of the solve behind a bounded queue — host-side feed
        # work AND the H2D transfer overlap the solve. False = fully
        # synchronous, the control arm for measuring the overlap win
        # (bench_overlap.py)
        self.prefetch = prefetch
        if prefetch_depth < 1:
            raise ValueError("prefetch_depth must be >= 1")
        self.prefetch_depth = int(prefetch_depth)
        # per-chunk divergence guard (optim.guard). NOTE: the health check is
        # one scalar fetch per chunk, which serializes the chunk pipeline —
        # enable it for robustness, not for peak-throughput benches.
        self._guard = guard
        # bounded retry around host->device chunk feeding (a flaky tunnel /
        # storage read should not kill a billion-coefficient run)
        if feed_retries < 0:
            raise ValueError("feed_retries must be >= 0")
        self._feed_retries = feed_retries
        # the streaming table trains DENSE per-entity models: a global box
        # constraint on local dim k applies identically to every entity
        # (the bucket path gathers the same bounds through each entity's
        # projection; here the projection is the identity)
        self._constrained = bool(config.box_constraints)
        constrained_mode = "shared" if self._constrained else False
        if mesh is None:
            self._sharding = None
            self._axis = axis
            self._n_dev = 1
        else:
            self._sharding = psharding.entity_sharding(mesh, axis)
            self._axis = self._sharding.spec[0]
            self._n_dev = psharding.axis_size(mesh, self._axis)
        key_cfg = dataclasses.replace(config, regularization_weight=0.0)
        self._solver = _re_solver(
            key_cfg, loss_name, constrained_mode, compute_variances
        )
        self._obj = make_objective(
            loss_name,
            l2_weight=config.regularization.l2_weight(
                config.regularization_weight
            ),
        )
        self._l1 = jnp.float32(
            config.regularization.l1_weight(config.regularization_weight)
        )

    def _prepare(self, source) -> DenseBatch:
        if callable(source):
            generated = source()
            if self._sharding is None:
                return generated
            # an on-device generator may have produced the chunk on the
            # default device; commit it to the entity sharding so the
            # solver program sees the mesh layout
            return jax.tree.map(
                lambda x: jax.device_put(x, self._sharding), generated
            )
        if isinstance(source, LocalChunk):
            if self._sharding is None:
                return jax.tree.map(jax.device_put, source.batch)
            gsize = int(source.global_size)

            def put_local(x):
                return jax.make_array_from_process_local_data(
                    self._sharding, np.asarray(x),
                    global_shape=(gsize,) + tuple(np.shape(x))[1:],
                )

            return jax.tree.map(put_local, source.batch)
        if isinstance(source, DenseBatch):
            leaves = jax.tree.leaves(source)
            if leaves and isinstance(leaves[0], np.ndarray):
                put = (
                    jax.device_put
                    if self._sharding is None
                    else partial(jax.device_put, device=self._sharding)
                )
                return jax.tree.map(put, source)
            return source
        raise TypeError(f"chunk source {type(source).__name__}")

    # retryable feed failures: storage/tunnel I/O and runtime transfer
    # errors (jax surfaces device/transfer faults as RuntimeError
    # subclasses). Deterministic programming errors (TypeError/ValueError/
    # KeyError/shape bugs) raise immediately — re-running cannot help.
    _TRANSIENT_FEED_ERRORS = (OSError, RuntimeError, ConnectionError,
                              TimeoutError)

    def _feed(self, source) -> DenseBatch:
        """_prepare with bounded retry: transient host->device feed failures
        (generator I/O, tunnel hiccups) re-attempt up to ``feed_retries``
        times before surfacing; programming errors raise immediately.

        Host-supplied chunks get a pre-upload HBM headroom check: the
        chunk's leaf bytes are known before device_put, so a chunk
        predicted to exceed free HBM warns (log + counter) instead of
        OOMing the run (no-op on statless backends)."""
        if not callable(source):
            predicted = telemetry_memory.estimate_batch_bytes(source)
            if predicted:
                telemetry_memory.check_headroom(
                    predicted, label="streaming chunk upload"
                )
        last_err: Optional[Exception] = None
        for attempt in range(self._feed_retries + 1):
            if attempt:
                telemetry.counter("streaming.feed_retries").inc()
                logger.warning(
                    "chunk feed failed (%s); retry %d/%d",
                    last_err, attempt, self._feed_retries,
                )
            try:
                return self._prepare(source)
            except self._TRANSIENT_FEED_ERRORS as e:
                last_err = e
        assert last_err is not None
        raise last_err

    def _chunk_constraints(self, dim: int):
        """ONE [dim] box shared by every entity (vmap broadcasts it) — the
        [E, K] materialization the bucket path needs for per-entity
        projections would be dim*entities floats at streaming scale."""
        if not self._constrained:
            return None
        from photon_ml_tpu.optim.common import BoxConstraints

        lower, upper = self.config.dense_box_bounds(dim)
        cons = BoxConstraints(
            lower=jnp.asarray(lower), upper=jnp.asarray(upper)
        )
        if self.mesh is not None:
            cons = psharding.place_replicated(cons, self.mesh)
        return cons

    def _solve(
        self,
        table,
        start: int,
        batch: DenseBatch,
        variance_table: Optional[ShardedCoefficientTable] = None,
    ) -> ChunkResult:
        size = batch.labels.shape[0]
        if self.mesh is not None and size % self._n_dev:
            # fail with intent, not a shard-shape error deep inside jax
            raise ValueError(
                f"chunk of {size} entities must divide over the "
                f"{self._n_dev}-device mesh (pad the chunk)"
            )
        w0 = table.read_chunk(start, size)
        if self._sharding is not None:
            # chunk reads slice the sharded table; commit the slice (and
            # the warm-start layout the solver sees) to the entity axis
            w0 = jax.device_put(w0, self._sharding)
            # static comms estimate: per-entity solves are independent —
            # the masked while-loop's one-scalar convergence test is the
            # only collective, once per iteration
            record_collective(
                "streaming_chunk_solve", "psum", self._n_dev, 4,
                count=max(int(self.config.max_iterations), 1),
            )
        cons = self._chunk_constraints(table.dim)
        rolled_back = False
        with telemetry.span("streaming_chunk", start=start, size=int(size)):
            attempt = 0
            while True:
                obj = self._obj
                if attempt:
                    telemetry.counter("solves.retried").inc()
                    obj = damped_objective(
                        obj, self._guard.damping_for(attempt)
                    )
                faults.fault_point(FP_COLLECTIVE_ENTRY)
                # per-member collective-wait attribution (no-op single
                # process): the window the fleet report sums per member
                with collective_wait("streaming_chunk_solve"):
                    res, var = self._solver(obj, batch, w0, self._l1, cons)
                # injection seam: a `nan` rule here poisons the solve
                # result, driving the guard's retry/rollback path on demand
                w = faults.corrupt_array(_FP_SOLVE_RESULT, res.w)
                if self._guard is None:
                    break
                ok = bool(
                    telemetry.sync_fetch(
                        solve_health(res, w), label="streaming_guard"
                    )
                )
                if ok:
                    break
                telemetry.counter("solves.diverged").inc()
                if attempt >= self._guard.max_retries:
                    # rollback: the chunk's table rows keep their pre-solve
                    # coefficients; telemetry values are sanitized so the
                    # run summary stays finite
                    telemetry.counter("solves.rolled_back").inc()
                    logger.warning(
                        "chunk [%d, %d) still diverging after %d damped "
                        "retries; keeping previous coefficients",
                        start, start + size, self._guard.max_retries,
                    )
                    rolled_back = True
                    break
                attempt += 1
            if not rolled_back:
                table.write_chunk(start, w)
        telemetry.counter("streaming_chunks").inc()
        telemetry.counter("streaming_entities").inc(int(size))
        # heartbeat rate sources: streamed example-rows and the chunk's
        # slice of the coefficient table count as processed work
        telemetry.counter("progress.rows").inc(
            int(np.prod(batch.labels.shape))
        )
        telemetry.counter("progress.coeffs").inc(int(size) * table.dim)
        telemetry_memory.record_phase_memory("streaming_chunk")
        if var is not None and not rolled_back:
            if variance_table is None:
                raise ValueError(
                    "compute_variances=True needs a variance_table to "
                    "write into (train(..., variance_table=...))"
                )
            variance_table.write_chunk(start, var)
        values = res.value
        if rolled_back:
            values = jnp.where(jnp.isfinite(values), values, 0.0)
        return ChunkResult(
            start=start,
            size=size,
            iterations=res.iterations,
            values=values,
            reasons=res.reason,
        )

    def _after_chunk(
        self,
        chunk_index: int,
        table: ShardedCoefficientTable,
        variance_table: Optional[ShardedCoefficientTable],
        checkpointer,
        should_stop,
        final: bool,
    ) -> None:
        """Chunk-boundary bookkeeping: periodic checkpoint, and the
        graceful-preemption handshake (save-then-raise on a stop
        request — the deterministic ingest order makes ``next_chunk``
        sufficient resume state).

        Checkpoints receive the LIVE device arrays: the manager saves a
        sharded table one addressable shard at a time, so no chunk
        boundary ever assembles the full table on the host (the old
        ``local_shard()`` gather was a host-OOM time bomb at the
        ``game_10B`` 40 GB-table scale)."""
        faults.fault_point(_FP_CHUNK_BOUNDARY)
        if checkpointer is None:
            if should_stop is not None and should_stop():
                from photon_ml_tpu.game.checkpoint import TrainingInterrupted

                raise TrainingInterrupted(chunk_index, None)
            return
        from photon_ml_tpu.game.checkpoint import (
            StreamCheckpointState,
            TrainingInterrupted,
        )

        stop = should_stop is not None and should_stop()
        path = None
        if stop or (not final and checkpointer.should_save(chunk_index)):
            path = checkpointer.save(
                StreamCheckpointState(
                    next_chunk=chunk_index + 1,
                    coefficients=table.coefficients,
                    variances=(
                        None if variance_table is None
                        else variance_table.coefficients
                    ),
                )
            )
        if stop:
            raise TrainingInterrupted(chunk_index, path)

    def train(
        self,
        table: ShardedCoefficientTable,
        chunks: Iterable[tuple[int, DenseBatch | Callable[[], DenseBatch]]],
        variance_table: Optional[ShardedCoefficientTable] = None,
        with_tracker: bool = False,
        should_stop: Optional[Callable[[], bool]] = None,
        checkpointer=None,
        start_chunk: int = 0,
    ) -> StreamingTrainStats:
        """Solve every chunk into ``table``; feeding (decode + host->device
        upload) runs ``prefetch_depth`` chunks ahead of the solve in a
        background thread (``ingest.double_buffered`` — the trainer is a
        CONSUMER of the pipeline, not an ingestion implementation).

        ``variance_table``: required when ``compute_variances``; receives
        the per-coefficient Hessian-diagonal-inverse variances
        (SingleNodeOptimizationProblem.scala:57-88 at streaming scale).
        ``with_tracker``: also return the full per-entity
        RandomEffectOptimizationTracker (costs one extra packed
        device->host fetch of 3 x total_entities values).

        Fault tolerance: with a ``checkpointer``
        (:class:`~photon_ml_tpu.game.checkpoint.StreamingCheckpointManager`)
        the table is snapshotted every ``every`` chunk boundaries, and a
        ``should_stop`` request (e.g. :class:`GracefulStop` on SIGTERM)
        finishes the current chunk, saves a final checkpoint, and raises
        ``TrainingInterrupted``. Resume by restoring the table and passing
        the restored ``next_chunk`` as ``start_chunk`` — chunk ordering is
        deterministic, so the replayed stream is exactly the remainder.
        """
        if self.compute_variances and variance_table is None:
            raise ValueError(
                "compute_variances=True needs a variance_table"
            )
        if start_chunk < 0:
            raise ValueError("start_chunk must be >= 0")
        results: list[ChunkResult] = []
        chunk_iter = iter(chunks)
        if start_chunk:
            # replay: skip already-solved chunks WITHOUT feeding them
            import itertools

            chunk_iter = itertools.islice(chunk_iter, start_chunk, None)
        index = start_chunk - 1
        if self.prefetch:
            from photon_ml_tpu.ingest.prefetch import double_buffered

            for (start, _source), batch in double_buffered(
                chunk_iter,
                lambda item: self._feed(item[1]),
                depth=self.prefetch_depth,
                name="streaming_chunk",
            ):
                index += 1
                results.append(
                    self._solve(
                        table, start, batch, variance_table=variance_table
                    )
                )
                self._after_chunk(
                    index, table, variance_table, checkpointer,
                    should_stop, final=False,
                )
        else:
            # control arm: serialize transfer and compute completely — a
            # 1-element fetch is the only true sync through the tunnel
            # (block_until_ready is a no-op there, tools/check.py L007)
            for start, source in chunk_iter:
                index += 1
                results.append(
                    self._solve(
                        table,
                        start,
                        self._feed(source),
                        variance_table=variance_table,
                    )
                )
                telemetry.sync_fetch(
                    table.coefficients[start, 0], label="streaming_sync"
                )
                self._after_chunk(
                    index, table, variance_table, checkpointer,
                    should_stop, final=False,
                )
        if checkpointer is not None and results:
            # terminal checkpoint: a crash AFTER the stream finishes must
            # not replay the tail chunks (sharded per-shard save — no
            # host gather, same as the boundary saves)
            from photon_ml_tpu.game.checkpoint import StreamCheckpointState

            checkpointer.save(
                StreamCheckpointState(
                    next_chunk=index + 1,
                    coefficients=table.coefficients,
                    variances=(
                        None if variance_table is None
                        else variance_table.coefficients
                    ),
                )
            )
        if not results:
            return StreamingTrainStats(0, 0, 0, 0.0, 0.0)
        # ONE device->host fetch for the scalar summaries
        sums = telemetry.sync_fetch(
            jnp.stack(
                [
                    jnp.sum(
                        jnp.stack(
                            [jnp.sum(r.iterations.astype(jnp.float32))
                             for r in results]
                        )
                    ),
                    jnp.sum(jnp.stack([jnp.sum(r.values) for r in results])),
                ]
            ),
            label="streaming_summary",
        )
        tracker = None
        if with_tracker:
            from photon_ml_tpu.optim.trackers import (
                RandomEffectOptimizationTracker,
            )

            tracker = RandomEffectOptimizationTracker.from_device_parts(
                [r.iterations for r in results],
                [r.reasons for r in results],
                [r.values for r in results],
            )
        total_e = sum(r.size for r in results)
        return StreamingTrainStats(
            total_entities=total_e,
            total_coefficients=total_e * table.dim,
            num_chunks=len(results),
            mean_iterations=float(sums[0]) / max(total_e, 1),
            total_final_value=float(sums[1]),
            tracker=tracker,
        )
