"""Block coordinate descent over named GAME coordinates.

Reference analog: photon-lib algorithm/CoordinateDescent.scala:93-271. Per
iteration, per coordinate (in update-sequence order): the coordinate's
training offsets become base_offset + sum of OTHER coordinates' scores (the
residual trick, :152-156), its sub-model is retrained warm-started, its
scores are recomputed, and the full model is validated; the best model by
the FIRST validation evaluator is tracked across full-model states only
(:130-137).

Scores live as [n_pad] device arrays keyed by coordinate name — the
KeyValueScore analog, where "+" is vector addition instead of an RDD join.
The loop itself is host-side Python (as in the reference); all per-step
compute is jit-compiled device work.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.evaluation import EVALUATORS, better_than, sharded_auc, sharded_precision_at_k
from photon_ml_tpu.evaluation.evaluators import parse_evaluator
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import GameModel

logger = logging.getLogger("photon_ml_tpu.game")


@dataclasses.dataclass
class ValidationSpec:
    data: GameDataset
    evaluators: Sequence[str]  # first one selects the best model


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    best_model: GameModel
    best_metric: Optional[float]
    history: list[dict]  # per (iteration, coordinate) telemetry


def _evaluate(model: GameModel, spec: ValidationSpec) -> dict[str, float]:
    scores = model.score(spec.data)
    n = spec.data.num_rows
    n_pad = scores.shape[0]

    def pad(a, fill=0.0):
        out = np.full((n_pad,), fill)
        out[:n] = a
        return jnp.asarray(out, jnp.float32)

    labels = pad(spec.data.response)
    weights = pad(spec.data.weight)  # padded rows weight 0
    full_scores = scores + pad(spec.data.offset)

    out = {}
    for spec_str in spec.evaluators:
        kind, group_col, k = parse_evaluator(spec_str)
        if kind in EVALUATORS:
            out[spec_str] = float(EVALUATORS[kind](full_scores, labels, weights))
            continue
        col = next(
            (c for c in spec.data.id_columns if c.lower() == group_col), None
        )
        if col is None:
            raise KeyError(
                f"evaluator '{spec_str}' needs id column '{group_col}'; "
                f"have {sorted(spec.data.id_columns)}"
            )
        idc = spec.data.id_columns[col]
        gids = jnp.asarray(
            np.pad(idc.codes, (0, n_pad - n)), jnp.int32
        )
        if kind == "sharded_auc":
            out[spec_str] = float(
                sharded_auc(full_scores, labels, weights, gids, idc.num_entities)
            )
        else:
            out[spec_str] = float(
                sharded_precision_at_k(
                    full_scores, labels, weights, gids, idc.num_entities, k
                )
            )
    return out


def run_coordinate_descent(
    coordinates: Mapping[str, object],
    task: str,
    num_iterations: int,
    validation: Optional[ValidationSpec] = None,
    initial_models: Optional[Mapping[str, object]] = None,
    on_step=None,
) -> CoordinateDescentResult:
    """Train all coordinates for ``num_iterations`` outer sweeps.

    ``coordinates`` is ordered (the updating sequence). ``initial_models``
    enables warm-starting whole coordinates from a previous run.
    ``on_step(entry)`` fires after every (iteration, coordinate) update
    with that step's telemetry dict (the event-bus hook).
    """
    names = list(coordinates)
    models = {
        name: (
            initial_models[name]
            if initial_models and name in initial_models
            else coordinates[name].initialize_model()
        )
        for name in names
    }
    scores = {name: coordinates[name].score(models[name]) for name in names}

    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    history: list[dict] = []

    for it in range(num_iterations):
        with telemetry.span("cd_iteration", iteration=it):
            for name in names:
                coord = coordinates[name]
                with telemetry.span(f"coordinate:{name}", iteration=it) as sp:
                    residual = None
                    if len(names) > 1:
                        residual = sum(
                            (scores[o] for o in names if o != name),
                            start=jnp.zeros_like(scores[name]),
                        )
                    models[name] = coord.update_model(models[name], residual)
                    scores[name] = coord.score(models[name])
                    # force execution before stopping the clock —
                    # block_until_ready is a no-op on the tunnel TPU; a
                    # 1-element fetch truly syncs (and is accounted)
                    telemetry.sync_fetch(
                        scores[name][0], label=f"coordinate:{name}"
                    )

                    entry = {
                        "iteration": it,
                        "coordinate": name,
                        "seconds": telemetry.trace.TRACER.now() - sp.ts,
                    }
                    tracker = getattr(coord, "last_tracker", None)
                    if tracker is not None:
                        # per-update optimization telemetry (the reference's
                        # OptimizationTracker surfaced in CD logs)
                        entry["tracker"] = tracker.to_summary_string()
                    if validation is not None:
                        game_model = GameModel(task=task, models=dict(models))
                        metrics = _evaluate(game_model, validation)
                        entry["metrics"] = metrics
                        primary = validation.evaluators[0]
                        value = metrics[primary]
                        if best_metric is None or better_than(
                            primary, value, best_metric
                        ):
                            best_metric = value
                            best_model = game_model
                        logger.info(
                            "CD iter %d coord %s: %s (%.2fs)", it, name,
                            metrics, entry["seconds"],
                        )
                    sp.set_attr(seconds=round(entry["seconds"], 6))
                history.append(entry)
                if on_step is not None:
                    on_step(entry)

    final = GameModel(task=task, models=dict(models))
    if best_model is None:
        best_model = final
    return CoordinateDescentResult(
        model=final, best_model=best_model, best_metric=best_metric, history=history
    )
