"""Block coordinate descent over named GAME coordinates.

Reference analog: photon-lib algorithm/CoordinateDescent.scala:93-271. Per
iteration, per coordinate (in update-sequence order): the coordinate's
training offsets become base_offset + sum of OTHER coordinates' scores (the
residual trick, :152-156), its sub-model is retrained warm-started, its
scores are recomputed, and the full model is validated; the best model by
the FIRST validation evaluator is tracked across full-model states only
(:130-137).

Scores live as [n_pad] device arrays keyed by coordinate name — the
KeyValueScore analog, where "+" is vector addition instead of an RDD join.
The loop itself is host-side Python (as in the reference); all per-step
compute is jit-compiled device work.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.telemetry import memory as telemetry_memory
from photon_ml_tpu.evaluation import EVALUATORS, better_than, sharded_auc, sharded_precision_at_k
from photon_ml_tpu.evaluation.evaluators import parse_evaluator
from photon_ml_tpu.game.checkpoint import (
    CheckpointError,
    CheckpointManager,
    CheckpointState,
    TrainingInterrupted,
)
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.optim.guard import (
    FP_SOLVE_HEALTH,
    GuardSpec,
    model_is_finite,
)

logger = logging.getLogger("photon_ml_tpu.game")

# Injection seam between a completed (iteration, coordinate) step and its
# checkpoint/stop handling — an injected raise here must leave the last
# step's checkpoint intact and resumable.
_FP_STEP_BOUNDARY = faults.register_point(
    "cd.step.boundary",
    description="after a CD step completes, before checkpoint/stop logic",
)


@dataclasses.dataclass
class ValidationSpec:
    data: GameDataset
    evaluators: Sequence[str]  # first one selects the best model


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    best_model: GameModel
    best_metric: Optional[float]
    history: list[dict]  # per (iteration, coordinate) telemetry


def padded_validation_arrays(
    data: GameDataset, n_pad: int
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(labels, weights, offsets) as [n_pad] f32 device arrays with
    weight-0 padding rows — the evaluator input layout. Shared by the CD
    validation path below and the sweep selector (sweep/select.py), so
    both score against identical padded arrays."""

    def pad(a, fill=0.0):
        out = np.full((n_pad,), fill)
        out[: data.num_rows] = a
        return jnp.asarray(out, jnp.float32)

    return pad(data.response), pad(data.weight), pad(data.offset)


def _evaluate(model: GameModel, spec: ValidationSpec) -> dict[str, float]:
    scores = model.score(spec.data)
    n = spec.data.num_rows
    n_pad = scores.shape[0]
    labels, weights, offsets = padded_validation_arrays(spec.data, n_pad)
    full_scores = scores + offsets

    out = {}
    for spec_str in spec.evaluators:
        kind, group_col, k = parse_evaluator(spec_str)
        if kind in EVALUATORS:
            out[spec_str] = float(EVALUATORS[kind](full_scores, labels, weights))
            continue
        col = next(
            (c for c in spec.data.id_columns if c.lower() == group_col), None
        )
        if col is None:
            raise KeyError(
                f"evaluator '{spec_str}' needs id column '{group_col}'; "
                f"have {sorted(spec.data.id_columns)}"
            )
        idc = spec.data.id_columns[col]
        gids = jnp.asarray(
            np.pad(idc.codes, (0, n_pad - n)), jnp.int32
        )
        if kind == "sharded_auc":
            out[spec_str] = float(
                sharded_auc(full_scores, labels, weights, gids, idc.num_entities)
            )
        else:
            out[spec_str] = float(
                sharded_precision_at_k(
                    full_scores, labels, weights, gids, idc.num_entities, k
                )
            )
    return out


def _num_coefficients(model) -> int:
    """Coefficient count of a coordinate model — shape metadata only, no
    device transfer. Feeds the ``progress.coeffs`` counter the heartbeat
    and run report turn into coeffs/s."""
    if model is None:
        return 0
    coeffs = getattr(model, "coefficients", None)
    if coeffs is not None:
        return int(getattr(coeffs, "size", 0))
    buckets = getattr(model, "buckets", None)
    if buckets is not None:
        return sum(_num_coefficients(b) for b in buckets)
    models = getattr(model, "models", None)
    if isinstance(models, Mapping):
        return sum(_num_coefficients(m) for m in models.values())
    return sum(
        int(getattr(leaf, "size", 0)) for leaf in jax.tree.leaves(model)
    )


def _record_step_progress(coord, model, name: str, seconds: float) -> None:
    """Publish per-step progress + memory telemetry: the rows/coeffs
    counters (heartbeat rate sources), the rows/s / coeffs/s gauges (run
    report key metrics), and the per-coordinate HBM phase peak."""
    rows = int(getattr(getattr(coord, "data", None), "num_rows", 0) or 0)
    coeffs = _num_coefficients(model)
    if rows:
        telemetry.counter("progress.rows").inc(rows)
    if coeffs:
        telemetry.counter("progress.coeffs").inc(coeffs)
    if seconds > 0:
        if rows:
            telemetry.gauge("progress.rows_per_sec").set(rows / seconds)
        if coeffs:
            telemetry.gauge("progress.coeffs_per_sec").set(coeffs / seconds)
    telemetry_memory.record_phase_memory(f"coordinate:{name}")


def _guarded_update(coord, model, residual, guard: GuardSpec, name: str):
    """One guarded coordinate update: solve, health-check, damped retries,
    rollback. Returns ``(model', attempts_used, rolled_back)``.

    Coordinates exposing ``extra_l2`` get damped retries (the l2 leaf is
    traced, so retries reuse the compiled solver); others — whose re-run
    would be bit-identical — roll straight back after the first divergence.
    """
    supports_damping = hasattr(coord, "extra_l2")
    if hasattr(coord, "health_check"):
        coord.health_check = True  # opt the coordinate into health reduces
    max_attempts = (guard.max_retries if supports_damping else 0) + 1
    for attempt in range(max_attempts):
        if attempt:
            telemetry.counter("solves.retried").inc()
            logger.warning(
                "coordinate %s diverged; retrying with extra L2 damping %g",
                name, guard.damping_for(attempt),
            )
        if supports_damping:
            coord.extra_l2 = guard.damping_for(attempt)
        try:
            new_model = coord.update_model(model, residual)
        finally:
            if supports_damping:
                coord.extra_l2 = 0.0
        health = getattr(coord, "last_health", None)
        if health is None:
            health = model_is_finite(new_model)
        # injection seam: a `nan` rule marks THIS solve diverged,
        # exercising the damped-retry/rollback path deterministically
        health = faults.corrupt_health(FP_SOLVE_HEALTH, health)
        if bool(telemetry.sync_fetch(health, label=f"guard:{name}")):
            return new_model, attempt, False
        telemetry.counter("solves.diverged").inc()
    telemetry.counter("solves.rolled_back").inc()
    logger.warning(
        "coordinate %s still diverging after %d attempt(s); rolling back "
        "to the pre-solve model", name, max_attempts,
    )
    return model, max_attempts - 1, True


def run_coordinate_descent(
    coordinates: Mapping[str, object],
    task: str,
    num_iterations: int,
    validation: Optional[ValidationSpec] = None,
    initial_models: Optional[Mapping[str, object]] = None,
    on_step=None,
    guard: Optional[GuardSpec] = None,
    checkpoint: Optional[CheckpointManager] = None,
    should_stop=None,
) -> CoordinateDescentResult:
    """Train all coordinates for ``num_iterations`` outer sweeps.

    ``coordinates`` is ordered (the updating sequence). ``initial_models``
    enables warm-starting whole coordinates from a previous run.
    ``on_step(entry)`` fires after every (iteration, coordinate) update
    with that step's telemetry dict (the event-bus hook).

    Fault tolerance (game.checkpoint / optim.guard):

    - ``checkpoint``: a CheckpointManager. On entry the newest valid
      checkpoint is restored — models reloaded, completed steps skipped,
      scores recomputed; after each completed step (per the spec's
      ``every``) the full state is atomically persisted.
    - ``guard``: a GuardSpec; every coordinate solve is health-checked and
      diverging solves are retried with escalating L2 damping, then rolled
      back. A coordinate rolling back ``freeze_after`` consecutive times is
      frozen (skipped; its last good model keeps scoring).
    - ``should_stop``: zero-arg predicate polled after every step; when it
      turns true a final checkpoint is written and TrainingInterrupted is
      raised (the graceful-preemption handshake).
    """
    names = list(coordinates)
    models = {
        name: (
            initial_models[name]
            if initial_models and name in initial_models
            else coordinates[name].initialize_model()
        )
        for name in names
    }

    best_model: Optional[GameModel] = None
    best_metric: Optional[float] = None
    history: list[dict] = []
    start_step = 0
    if checkpoint is not None:
        restored = checkpoint.restore()
        if restored is not None:
            if list(restored.model.models) != names:
                raise CheckpointError(
                    f"checkpoint at {checkpoint.spec.directory} was written "
                    f"by a fit with coordinates "
                    f"{list(restored.model.models)}, not {names}"
                )
            models = dict(restored.model.models)
            best_model = restored.best_model
            best_metric = restored.best_metric
            history = list(restored.history)
            start_step = restored.step + 1
    # scores recomputed from the (possibly restored) models — checkpoints
    # persist models only; scores are derived state
    scores = {name: coordinates[name].score(models[name]) for name in names}

    # guard bookkeeping survives resume: a coordinate already proved
    # divergent must not re-burn its retries every remaining iteration.
    # Restored ONLY when a guard is active — resuming with guard=None is
    # an explicit request to train every coordinate again.
    frozen: set[str] = set()
    consecutive_rollbacks = {name: 0 for name in names}
    if guard is not None and checkpoint is not None and restored is not None:
        frozen = {n for n in restored.frozen if n in consecutive_rollbacks}
        for n, count in (restored.consecutive_rollbacks or {}).items():
            if n in consecutive_rollbacks:
                consecutive_rollbacks[n] = int(count)
    last_ckpt_path: Optional[str] = None

    for it in range(num_iterations):
        with telemetry.span("cd_iteration", iteration=it):
            for idx, name in enumerate(names):
                step = it * len(names) + idx
                if step < start_step:
                    continue  # completed before the restored checkpoint
                if name in frozen:
                    continue  # divergent coordinate: last good model stands
                coord = coordinates[name]
                with telemetry.span(f"coordinate:{name}", iteration=it) as sp:
                    residual = None
                    if len(names) > 1:
                        residual = sum(
                            (scores[o] for o in names if o != name),
                            start=jnp.zeros_like(scores[name]),
                        )
                        if guard is not None:
                            # a NaN-scoring coordinate (e.g. rolled back to
                            # zeros over NaN features) must not poison its
                            # neighbors' solves through the residual
                            residual = jnp.nan_to_num(
                                residual, nan=0.0, posinf=0.0, neginf=0.0
                            )
                    rolled_back = False
                    attempts = 0
                    if guard is None:
                        models[name] = coord.update_model(models[name], residual)
                    else:
                        models[name], attempts, rolled_back = _guarded_update(
                            coord, models[name], residual, guard, name
                        )
                    if not rolled_back:
                        # a rolled-back model is unchanged; its scores stand
                        scores[name] = coord.score(models[name])
                    # force execution before stopping the clock —
                    # block_until_ready is a no-op on the tunnel TPU; a
                    # 1-element fetch truly syncs (and is accounted)
                    telemetry.sync_fetch(
                        scores[name][0], label=f"coordinate:{name}"
                    )

                    entry = {
                        "iteration": it,
                        "coordinate": name,
                        "seconds": telemetry.trace.TRACER.now() - sp.ts,
                    }
                    if guard is not None and (attempts or rolled_back):
                        entry["solve_retries"] = attempts
                        entry["rolled_back"] = rolled_back
                    tracker = getattr(coord, "last_tracker", None)
                    if tracker is not None and not rolled_back:
                        # per-update optimization telemetry (the reference's
                        # OptimizationTracker surfaced in CD logs)
                        entry["tracker"] = tracker.to_summary_string()
                    if validation is not None:
                        game_model = GameModel(task=task, models=dict(models))
                        metrics = _evaluate(game_model, validation)
                        entry["metrics"] = metrics
                        primary = validation.evaluators[0]
                        value = metrics[primary]
                        if best_metric is None or better_than(
                            primary, value, best_metric
                        ):
                            best_metric = value
                            best_model = game_model
                        logger.info(
                            "CD iter %d coord %s: %s (%.2fs)", it, name,
                            metrics, entry["seconds"],
                        )
                    sp.set_attr(seconds=round(entry["seconds"], 6))
                    _record_step_progress(
                        coord, models[name], name, entry["seconds"]
                    )
                history.append(entry)
                if on_step is not None:
                    on_step(entry)

                if rolled_back:
                    consecutive_rollbacks[name] += 1
                    if consecutive_rollbacks[name] >= guard.freeze_after:
                        frozen.add(name)
                        telemetry.counter("solves.frozen").inc()
                        logger.warning(
                            "coordinate %s frozen after %d consecutive "
                            "rollbacks; its last good model keeps scoring",
                            name, consecutive_rollbacks[name],
                        )
                else:
                    consecutive_rollbacks[name] = 0

                faults.fault_point(_FP_STEP_BOUNDARY)
                stop = should_stop is not None and should_stop()
                if checkpoint is not None and (
                    stop or checkpoint.should_save(step)
                ):
                    last_ckpt_path = checkpoint.save(
                        CheckpointState(
                            step=step,
                            model=GameModel(task=task, models=dict(models)),
                            best_model=best_model,
                            best_metric=best_metric,
                            history=history,
                            frozen=sorted(frozen),
                            consecutive_rollbacks=dict(consecutive_rollbacks),
                        )
                    )
                if stop:
                    raise TrainingInterrupted(step, last_ckpt_path)

    final = GameModel(task=task, models=dict(models))
    if best_model is None:
        best_model = final
    return CoordinateDescentResult(
        model=final, best_model=best_model, best_metric=best_metric, history=history
    )
