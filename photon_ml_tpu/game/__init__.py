from photon_ml_tpu.game.checkpoint import (  # noqa: F401
    CheckpointError,
    CheckpointManager,
    CheckpointSpec,
    CheckpointState,
    ElasticRestore,
    GracefulStop,
    StreamCheckpointState,
    StreamingCheckpointManager,
    TrainingInterrupted,
)
from photon_ml_tpu.game.coordinate_descent import (  # noqa: F401
    CoordinateDescentResult,
    ValidationSpec,
    run_coordinate_descent,
)
from photon_ml_tpu.game.coordinates import (  # noqa: F401
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.dataset import (  # noqa: F401
    GameDataset,
    IdColumn,
    build_game_dataset,
)
from photon_ml_tpu.game.models import (  # noqa: F401
    FixedEffectModel,
    GameModel,
    RandomEffectBucketModel,
    RandomEffectModel,
)
from photon_ml_tpu.game.random_effect_data import (  # noqa: F401
    EntityBucket,
    RandomEffectDataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.game.factored import (  # noqa: F401
    FactoredRandomEffectCoordinate,
    FactoredRandomEffectModel,
    MatrixFactorizationModel,
)
from photon_ml_tpu.game.estimator import (  # noqa: F401
    FactoredRandomEffectConfig,
    FixedEffectConfig,
    GameConfig,
    GameEstimator,
    GameFitResult,
    RandomEffectConfig,
)
