"""GameEstimator: typed-config end-to-end GAME training.

Reference analog: photon-client estimators/GameEstimator.scala:53-472 (the
programmatic fit surface) and GameParams.scala:215-492 (the flag system).
One typed config replaces both (SURVEY.md §5 "Config / flag system"): it
names the coordinates in updating-sequence order, their shards/optimizers/
normalization, the evaluators, and the CD schedule; ``fit`` builds the
datasets and coordinates, runs coordinate descent, and returns the final +
best models, optionally persisting them (the training driver's
"best/" output layout, cli/game/training/Driver.scala:262-312).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Optional, Sequence

from jax.sharding import Mesh

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import memory as telemetry_memory
from photon_ml_tpu.data.normalization import (
    NormalizationContext,
    NormalizationType,
    build_normalization_context,
)
from photon_ml_tpu.data.stats import summarize
from photon_ml_tpu.game.coordinate_descent import (
    CoordinateDescentResult,
    ValidationSpec,
    run_coordinate_descent,
)
from photon_ml_tpu.game.coordinates import (
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.game.random_effect_data import build_random_effect_dataset
from photon_ml_tpu.optim.factory import OptimizerConfig
from photon_ml_tpu.parallel.mesh import DATA_AXIS, ENTITY_AXIS


@dataclasses.dataclass(frozen=True)
class FixedEffectConfig:
    """One global GLM coordinate (FixedEffectDataConfiguration +
    GLMOptimizationConfiguration analog)."""

    shard_name: str
    optimizer: OptimizerConfig = OptimizerConfig()
    normalization: NormalizationType | str = NormalizationType.NONE
    intercept_index: Optional[int] = None
    down_sampling_seed: int = 0
    # training layout: "auto" picks the tiled one-hot-matmul pallas fast
    # path on TPU and padded-COO elsewhere; "tiled"/"coo" force it
    layout: str = "auto"


@dataclasses.dataclass(frozen=True)
class RandomEffectConfig:
    """One per-entity coordinate (RandomEffectDataConfiguration analog:
    randomEffectType = id_name, featureShardId = shard_name, active-data
    caps as in RandomEffectDataSet.scala:294-357, projectorType, and the
    numFeaturesToSamplesRatio Pearson bound of :420-434)."""

    shard_name: str
    id_name: str
    optimizer: OptimizerConfig = OptimizerConfig()
    active_rows_per_entity: Optional[int] = None
    min_rows_per_entity: int = 1
    # cap each entity's feature count at ceil(ratio * its row count), picked
    # by |Pearson(feature, label)| (numFeaturesToSamplesRatioUpperBound)
    features_to_samples_ratio: Optional[float] = None
    # "index_map": per-entity observed-feature reindexing (default);
    # "random": shared Gaussian random projection into projected_dim dims
    # (ProjectorType.{INDEX_MAP,RANDOM}_PROJECTION analog)
    projector: str = "index_map"
    projected_dim: Optional[int] = None
    projection_seed: int = 0
    projection_intercept_index: Optional[int] = None
    # per-coefficient posterior variances via Hessian-diagonal inverse at
    # each entity's optimum (SingleNodeOptimizationProblem.scala:57-88)
    compute_variances: bool = False

    def __post_init__(self):
        if self.projector == "random" and self.compute_variances:
            raise ValueError(
                "compute_variances needs the index_map projector: under a "
                "Gaussian random projection the local coordinates are mixtures "
                "of global features, so per-coefficient variances have no "
                "original-space meaning"
            )
        if self.projector not in ("index_map", "random"):
            raise ValueError(f"unknown projector '{self.projector}'")
        if self.projector == "random" and not self.projected_dim:
            raise ValueError("projector='random' requires projected_dim")


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectConfig:
    """One factored (matrix-factorization) random-effect coordinate
    (FactoredRandomEffectOptimizationProblem + MFOptimizationConfiguration
    analog: latent_dim = numLatentFactors, mf_iterations = numIterations)."""

    shard_name: str
    id_name: str
    latent_dim: int
    mf_iterations: int = 1
    re_optimizer: OptimizerConfig = OptimizerConfig()
    latent_optimizer: OptimizerConfig = OptimizerConfig()
    active_rows_per_entity: Optional[int] = None
    min_rows_per_entity: int = 1
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class GameConfig:
    """Full training configuration (GameParams analog).

    ``coordinates`` is ordered: iteration order IS the updating sequence
    (GameEstimator.scala updatingSequence). The first evaluator selects the
    best model (CoordinateDescent.scala:130-137).
    """

    task: str
    coordinates: Mapping[
        str, FixedEffectConfig | RandomEffectConfig | FactoredRandomEffectConfig
    ]
    num_iterations: int = 1
    evaluators: Sequence[str] = ()

    def __post_init__(self):
        if not self.coordinates:
            raise ValueError("GameConfig needs at least one coordinate")


@dataclasses.dataclass
class GameFitResult:
    model: GameModel
    best_model: GameModel
    best_metric: Optional[float]
    history: list


@dataclasses.dataclass
class SweepFitResult:
    """A finished vmapped λ sweep: the selection, the winning model, and
    the full per-config record (sweep.runner.GameSweepResult)."""

    model: GameModel  # the selected winner
    selection: "SweepSelection"
    sweep: "GameSweepResult"
    published_version: Optional[str] = None  # registry path when exported


@dataclasses.dataclass
class GridFitEntry:
    """One combination of a fit_grid sweep: the per-coordinate optimizer
    configs used and the resulting fit (the reference's (config, model,
    evaluation) triple)."""

    optimizer_configs: Mapping[str, OptimizerConfig]
    result: GameFitResult


def _record_table_estimate(name: str, red, dim=None) -> None:
    """Publish the predicted HBM residency of one random-effect
    coordinate's coefficient table (``memory.table_bytes.<name>`` gauge)
    and pre-check headroom BEFORE the solve allocates it — the warning
    lands in the log and run report instead of an XLA OOM mid-fit.

    ``dim``: per-entity coefficient dim for projected/factored tables
    (projected_dim / latent_dim); None = the index-map layout, whose table
    is the per-bucket [entities, local_features] stacks."""
    if dim is not None:
        table_bytes = telemetry_memory.estimate_table_bytes(
            red.num_entities, dim
        )
    else:
        table_bytes = sum(
            telemetry_memory.estimate_table_bytes(
                b.num_entities, b.num_local_features
            )
            for b in red.buckets
        )
    telemetry.gauge(f"memory.table_bytes.{name}").set(table_bytes)
    telemetry_memory.check_headroom(
        table_bytes, label=f"coordinate:{name} coefficient table"
    )


class GameEstimator:
    """Builds datasets + coordinates from a GameConfig and trains via CD."""

    def __init__(self, config: GameConfig):
        from photon_ml_tpu.utils.events import EventEmitter

        self.config = config
        self._re_datasets: dict = {}
        self._coordinates: dict = {}
        # lifecycle event bus (EventEmitter.scala analog); register
        # listeners before fit() to observe setup/start/step/finish events
        self.events = EventEmitter()

    def _re_dataset(self, data: GameDataset, c) -> "RandomEffectDataset":
        """Build (or reuse) the grouped/bucketed RE dataset for a config.

        Keyed by the DATA-side parameters only, so a grid sweep over
        optimizer configs shares one dataset build per coordinate
        (prepareTrainingDataSet is outside the config loop in the
        reference, GameEstimator.scala:135-187 vs :279-398)."""
        ratio = getattr(c, "features_to_samples_ratio", None)
        key = (
            id(data), c.id_name, c.shard_name, c.active_rows_per_entity,
            c.min_rows_per_entity, ratio,
        )
        hit = self._re_datasets.get(key)
        # the cached entry pins a strong reference to its dataset, so the
        # id() in the key cannot be recycled while the entry lives; the
        # identity check guards the (impossible-by-construction) mismatch
        if hit is not None and hit[0] is data:
            return hit[1]
        if len(self._re_datasets) >= 8:  # bound growth on long-lived estimators
            self._re_datasets.pop(next(iter(self._re_datasets)))
        red = build_random_effect_dataset(
            data,
            c.id_name,
            c.shard_name,
            active_rows_per_entity=c.active_rows_per_entity,
            min_rows_per_entity=c.min_rows_per_entity,
            features_to_samples_ratio=ratio,
        )
        self._re_datasets[key] = (data, red)
        return red

    def _build_coordinates(
        self,
        data: GameDataset,
        mesh: Optional[Mesh],
        opt_overrides: Optional[Mapping[str, OptimizerConfig]] = None,
        only: Optional[set] = None,
    ) -> dict:
        # Meshes with named batch/model axes (the GSPMD vocabulary,
        # parallel.sharding; `--mesh batch=N,model=M`) are used AS GIVEN:
        # each coordinate resolves its own axis, so FE rows shard over
        # 'batch' and RE entity state over 'model' on one physical mesh.
        # A legacy 1-D mesh still becomes two logical 1-D views over the
        # same devices ('data' for FE rows, 'entity' for RE batches,
        # SURVEY.md §2.f). Views are free — no data movement.
        data_mesh = entity_mesh = None
        if mesh is not None:
            from photon_ml_tpu.parallel.sharding import BATCH_AXIS, MODEL_AXIS

            named = set(mesh.axis_names) & {BATCH_AXIS, MODEL_AXIS}
            if named or len(mesh.axis_names) > 1:
                from photon_ml_tpu.parallel.sharding import data_axis, model_axis

                if data_axis(mesh) is None and model_axis(mesh) is None:
                    # every coordinate would silently drop the mesh and the
                    # user's N provisioned devices would train single-device
                    raise ValueError(
                        f"mesh axes {mesh.axis_names} name neither a "
                        "batch/data nor a model/entity axis — nothing would "
                        "shard; use --mesh batch=N,model=M (or a 1-D mesh)"
                    )
                data_mesh = entity_mesh = mesh
            else:
                devices = mesh.devices.reshape(-1)
                data_mesh = Mesh(devices, (DATA_AXIS,))
                entity_mesh = Mesh(devices, (ENTITY_AXIS,))
        overrides = opt_overrides or {}
        # the caches serve REPEATED fits over the same data (benchmarks,
        # grid sweeps, warm-started re-fits); entries for other datasets are
        # dropped so device-resident design matrices never pin old data
        self._coordinates = {
            k: v for k, v in self._coordinates.items() if v[0] is data
        }
        self._re_datasets = {
            k: v for k, v in self._re_datasets.items() if v[0] is data
        }
        coords = {}
        for name, c in self.config.coordinates.items():
            if only is not None and name not in only:
                continue
            opt = overrides.get(name)
            # reuse a coordinate built for the SAME (data, config, mesh):
            # FE construction in particular re-tiles and re-uploads the full
            # design matrix, which dominates repeated fit() calls
            mesh_key = None if mesh is None else tuple(mesh.devices.reshape(-1))
            cache_key = (id(data), name, opt or "default", mesh_key)
            hit = self._coordinates.get(cache_key)
            if hit is not None and hit[0] is data:
                coord = hit[1]
                # fresh-fit semantics: reset per-fit mutable state so a
                # cached coordinate behaves exactly like a new one (the
                # down-sampling rng salt restarts, stale trackers clear)
                if hasattr(coord, "_update_count"):
                    coord._update_count = 0
                if hasattr(coord, "last_tracker"):
                    coord.last_tracker = None
                if hasattr(coord, "health_check"):
                    # guard state is per-fit: re-opted-in by _guarded_update
                    coord.health_check = False
                    coord.extra_l2 = 0.0
                    coord.last_health = None
                coords[name] = coord
                continue
            if isinstance(c, FixedEffectConfig):
                norm = self._normalization_for(data, c)
                coords[name] = FixedEffectCoordinate(
                    name=name,
                    data=data,
                    shard_name=c.shard_name,
                    loss_name=self.config.task,
                    config=opt or c.optimizer,
                    seed=c.down_sampling_seed,
                    normalization=norm,
                    mesh=data_mesh,
                    layout=c.layout,
                )
            elif isinstance(c, RandomEffectConfig):
                red = self._re_dataset(data, c)
                _record_table_estimate(
                    name, red, dim=c.projected_dim
                    if c.projector == "random" else None,
                )
                if c.projector == "random":
                    # fixed Gaussian projection: per-entity solves in the
                    # shared projected space (RandomEffectCoordinateIn
                    # ProjectedSpace + ProjectorType.RANDOM analog)
                    coords[name] = FactoredRandomEffectCoordinate(
                        name=name,
                        data=data,
                        re_data=red,
                        loss_name=self.config.task,
                        re_config=opt or c.optimizer,
                        latent_config=opt or c.optimizer,
                        latent_dim=c.projected_dim,
                        refit_projection=False,
                        projection_intercept_index=c.projection_intercept_index,
                        seed=c.projection_seed,
                        mesh=entity_mesh,
                    )
                else:
                    coords[name] = RandomEffectCoordinate(
                        name=name,
                        data=data,
                        re_data=red,
                        loss_name=self.config.task,
                        config=opt or c.optimizer,
                        mesh=entity_mesh,
                        compute_variances=c.compute_variances,
                    )
            elif isinstance(c, FactoredRandomEffectConfig):
                red = self._re_dataset(data, c)
                _record_table_estimate(name, red, dim=c.latent_dim)
                coords[name] = FactoredRandomEffectCoordinate(
                    name=name,
                    data=data,
                    re_data=red,
                    loss_name=self.config.task,
                    re_config=opt or c.re_optimizer,
                    latent_config=c.latent_optimizer,
                    latent_dim=c.latent_dim,
                    mf_iterations=c.mf_iterations,
                    seed=c.seed,
                    mesh=entity_mesh,
                )
            else:
                raise TypeError(
                    f"coordinate '{name}': unknown config {type(c).__name__}"
                )
            if len(self._coordinates) >= 16:
                self._coordinates.pop(next(iter(self._coordinates)))
            self._coordinates[cache_key] = (data, coords[name])
        return coords

    @staticmethod
    def _normalization_for(
        data: GameDataset, c: FixedEffectConfig
    ) -> Optional[NormalizationContext]:
        ntype = NormalizationType(c.normalization)
        if ntype == NormalizationType.NONE:
            return None
        summary = summarize(data.batch_for(c.shard_name))
        return build_normalization_context(
            ntype, summary, intercept_index=c.intercept_index
        )

    def fit(
        self,
        data: GameDataset,
        validation_data: Optional[GameDataset] = None,
        initial_models: Optional[Mapping[str, object]] = None,
        output_dir: Optional[str] = None,
        mesh: Optional[Mesh] = None,
        checkpoint_spec: Optional["CheckpointSpec"] = None,
        guard: Optional["GuardSpec"] = None,
        should_stop=None,
    ) -> GameFitResult:
        """Train; optionally save final + best models under ``output_dir``.

        With ``mesh`` (any device mesh; its flattened device list is used),
        fixed-effect solves shard examples over the devices (DP via
        distributed_solve) and random-effect bucket solves shard the entity
        axis (shard_map, no cross-entity comms) — the GAME analog of the
        reference's cluster mode. Results match the single-device fit.

        Fault tolerance: ``checkpoint_spec`` (game.checkpoint.CheckpointSpec)
        persists coordinate-descent state after each step and resumes from
        the newest valid checkpoint; ``guard`` (optim.guard.GuardSpec)
        health-checks every solve with damped-retry/rollback recovery;
        ``should_stop`` is polled per step — when true, a final checkpoint
        is written and game.checkpoint.TrainingInterrupted raised.

        Output layout mirrors the reference training driver
        (cli/game/training/Driver.scala:262-312): ``<output_dir>/final`` and
        ``<output_dir>/best`` model directories.
        """
        from photon_ml_tpu.game.checkpoint import CheckpointManager
        from photon_ml_tpu.utils.events import (
            OptimizationLogEvent,
            SetupEvent,
            TrainingFinishEvent,
            TrainingStartEvent,
        )
        from photon_ml_tpu.utils.timing import Timer

        t = Timer().start()
        self.events.send(SetupEvent(config=_config_metadata(self.config)))
        with telemetry.span(
            "fit",
            task=self.config.task,
            num_coordinates=len(self.config.coordinates),
        ):
            with telemetry.span("build_coordinates"):
                coordinates = self._build_coordinates(data, mesh)
            telemetry_memory.record_phase_memory("build_coordinates")
            validation = None
            if validation_data is not None:
                if not self.config.evaluators:
                    raise ValueError(
                        "validation data provided but no evaluators"
                    )
                validation = ValidationSpec(
                    data=validation_data,
                    evaluators=list(self.config.evaluators),
                )
            self.events.send(TrainingStartEvent(num_rows=data.num_rows))
            result: CoordinateDescentResult = run_coordinate_descent(
                coordinates,
                task=self.config.task,
                num_iterations=self.config.num_iterations,
                validation=validation,
                initial_models=initial_models,
                on_step=lambda entry: self.events.send(
                    OptimizationLogEvent(
                        iteration=entry["iteration"],
                        coordinate=entry["coordinate"],
                        seconds=entry["seconds"],
                        metrics=entry.get("metrics"),
                    )
                ),
                guard=guard,
                checkpoint=(
                    None if checkpoint_spec is None
                    else CheckpointManager(checkpoint_spec)
                ),
                should_stop=should_stop,
            )
            telemetry_memory.record_phase_memory("fit")
        self.events.send(
            TrainingFinishEvent(
                best_metric=result.best_metric,
                seconds=t.stop(),
                metrics_snapshot=telemetry.snapshot(),
            )
        )
        fit = GameFitResult(
            model=result.model,
            best_model=result.best_model,
            best_metric=result.best_metric,
            history=result.history,
        )
        if output_dir is not None:
            # local import: model_store imports game.models, which would be
            # circular through game/__init__ at module load time
            from photon_ml_tpu.data.model_store import save_game_model

            meta = {
                "config": _config_metadata(self.config),
                "best_metric": result.best_metric,
            }
            save_game_model(
                result.model, os.path.join(output_dir, "final"),
                extra_metadata=meta,
            )
            save_game_model(
                result.best_model, os.path.join(output_dir, "best"),
                extra_metadata=meta,
            )
        return fit

    def fit_incremental(
        self,
        data: GameDataset,
        warm_start,
        delta=None,
        validation_data: Optional[GameDataset] = None,
        output_dir: Optional[str] = None,
        mesh: Optional[Mesh] = None,
        num_iterations: Optional[int] = None,
        lambda_factors=None,
        metric: Optional[str] = None,
        policy: str = "best",
        rel_tol: float = 0.01,
        guard: Optional["GuardSpec"] = None,
        checkpoint_spec: Optional["CheckpointSpec"] = None,
        should_stop=None,
        bootstrap_samples: int = 0,
        bootstrap_seed: int = 0,
    ):
        """Delta-aware warm-start refresh over the COMBINED data.

        ``warm_start`` (:func:`photon_ml_tpu.incremental.load_warm_start`)
        seeds every coordinate from the base model — per-entity rows
        re-homed by entity value, so vocabulary growth zero-inits only
        genuinely new entities. With ``delta``
        (:func:`photon_ml_tpu.incremental.scan_delta`), random-effect
        coordinates re-solve ONLY the touched entities' lanes (untouched
        rows stay bit-identical; zero-touched bucket solves are skipped
        entirely) while the fixed effect refreshes over all rows.

        ``lambda_factors`` (descending multipliers, e.g. from
        :func:`photon_ml_tpu.incremental.local_lambda_factors`) runs a
        small local λ sweep around the incumbent regularization, each
        lane path-warm-started from its more-regularized neighbor, and
        selects with the ``sweep.select`` policies (needs
        ``validation_data``).

        Returns :class:`photon_ml_tpu.incremental.IncrementalFitResult`.
        """
        from photon_ml_tpu.incremental.refit import run_incremental_fit

        result = run_incremental_fit(
            self,
            data,
            warm_start,
            delta=delta,
            validation_data=validation_data,
            mesh=mesh,
            num_iterations=num_iterations,
            lambda_factors=lambda_factors,
            metric=metric,
            policy=policy,
            rel_tol=rel_tol,
            guard=guard,
            checkpoint_spec=checkpoint_spec,
            should_stop=should_stop,
            bootstrap_samples=bootstrap_samples,
            bootstrap_seed=bootstrap_seed,
        )
        if output_dir is not None:
            from photon_ml_tpu.data.model_store import save_game_model
            from photon_ml_tpu.incremental.publish import lineage_record

            meta = {
                "config": _config_metadata(self.config),
                "best_metric": result.best_metric,
                "lineage": lineage_record(result.lineage,
                                          delta=result.delta),
            }
            save_game_model(
                result.model, os.path.join(output_dir, "final"),
                extra_metadata=meta,
            )
            save_game_model(
                result.best_model, os.path.join(output_dir, "best"),
                extra_metadata=meta,
            )
        return result

    def fit_sweep(
        self,
        data: GameDataset,
        validation_data: GameDataset,
        grid: "SweepGrid",
        metric: Optional[str] = None,
        policy: str = "best",
        rel_tol: float = 0.01,
        num_iterations: Optional[int] = None,
        warm_start: bool = True,
        output_dir: Optional[str] = None,
        registry_dir: Optional[str] = None,
        index_maps: Optional[Mapping] = None,
    ) -> SweepFitResult:
        """Train EVERY λ of ``grid`` simultaneously and ship the best.

        The vmapped multi-config path (sweep.runner.sweep_game): one
        batched executable per coordinate update covers all G configs,
        unconverged lanes warm-start from their more-regularized
        neighbor, every lane is scored on device against
        ``validation_data``, and the winner is selected by ``metric``
        (default: the task's ModelSelection metric) under ``policy``.

        With ``output_dir`` the winner is saved under ``<output_dir>/best``
        (the training driver's best/ layout); with ``registry_dir`` (+
        ``index_maps`` pinning the feature space) it is published through
        ``serving.registry.publish_version`` for live hot-swap.
        """
        from photon_ml_tpu.sweep.runner import sweep_game
        from photon_ml_tpu.sweep.select import export_winner, run_selection

        result = sweep_game(
            self.config,
            data,
            grid,
            num_iterations=num_iterations,
            warm_start=warm_start,
        )
        selection = run_selection(
            result, validation_data, metric=metric, policy=policy,
            rel_tol=rel_tol,
        )
        model = result.model_for(selection.index)
        meta = {
            "config": _config_metadata(self.config),
            "sweep_grid": grid.to_json(),
        }
        if output_dir is not None:
            from photon_ml_tpu.data.model_store import save_game_model

            save_game_model(
                model,
                os.path.join(output_dir, "best"),
                extra_metadata={**meta,
                                "sweep_selection": selection.to_json()},
            )
        published = None
        if registry_dir is not None:
            if not index_maps:
                raise ValueError(
                    "publishing a sweep winner to a registry requires "
                    "index_maps (the registry refuses versions without a "
                    "pinned feature space)"
                )
            published = export_winner(
                model, index_maps, registry_dir,
                selection=selection, extra_metadata=meta,
            )
        return SweepFitResult(
            model=model,
            selection=selection,
            sweep=result,
            published_version=published,
        )

    def fit_grid(
        self,
        data: GameDataset,
        validation_data: GameDataset,
        grid: Mapping[str, Sequence[OptimizerConfig]],
        mesh: Optional[Mesh] = None,
    ) -> list["GridFitEntry"]:
        """Sweep the cartesian product of per-coordinate optimizer configs.

        The reference trains one CoordinateDescent run per combination of
        FE x RE x factored-RE optimization configs and returns (config,
        model, evaluation) triples (GameEstimator.scala:279-398). Datasets
        are built once and shared across combinations; compiled solvers are
        shared whenever two combinations agree on a coordinate's config
        (lru-cached jit programs). Entries come back sorted best-first by
        the primary evaluator.
        """
        if not self.config.evaluators:
            raise ValueError("fit_grid needs evaluators to rank combinations")
        unknown = set(grid) - set(self.config.coordinates)
        if unknown:
            raise ValueError(f"grid names unknown coordinates: {sorted(unknown)}")
        import itertools

        from photon_ml_tpu.evaluation import better_than
        from photon_ml_tpu.utils.events import (
            OptimizationLogEvent,
            SetupEvent,
            TrainingFinishEvent,
            TrainingStartEvent,
        )

        names = list(grid)
        combos = list(itertools.product(*(grid[n] for n in names)))
        validation = ValidationSpec(
            data=validation_data, evaluators=list(self.config.evaluators)
        )
        primary = self.config.evaluators[0]
        self.events.send(SetupEvent(config=_config_metadata(self.config)))

        # coordinates whose config doesn't vary in a combo are reused (the
        # FE tiled/sharded layout build is the dominant per-coordinate setup
        # cost); keyed per (name, effective config) within this sweep
        coord_cache: dict = {}

        def coordinates_for(overrides):
            missing = {
                n for n in self.config.coordinates
                if (n, overrides.get(n)) not in coord_cache
            }
            built = (
                self._build_coordinates(data, mesh, overrides, only=missing)
                if missing
                else {}
            )
            out = {}
            for n in self.config.coordinates:
                key = (n, overrides.get(n))
                if key not in coord_cache:
                    coord_cache[key] = built[n]
                out[n] = coord_cache[key]
            return out

        from photon_ml_tpu.utils.timing import Timer

        entries: list[GridFitEntry] = []
        for i, combo in enumerate(combos):
            overrides = dict(zip(names, combo))
            t = Timer().start()
            self.events.send(TrainingStartEvent(num_rows=data.num_rows))
            with telemetry.span("fit", task=self.config.task, combination=i):
                result = run_coordinate_descent(
                    coordinates_for(overrides),
                    task=self.config.task,
                    num_iterations=self.config.num_iterations,
                    validation=validation,
                    on_step=lambda entry: self.events.send(
                        OptimizationLogEvent(
                            iteration=entry["iteration"],
                            coordinate=entry["coordinate"],
                            seconds=entry["seconds"],
                            metrics=entry.get("metrics"),
                        )
                    ),
                )
            self.events.send(
                TrainingFinishEvent(
                    best_metric=result.best_metric,
                    seconds=t.stop(),
                    metrics_snapshot=telemetry.snapshot(),
                )
            )
            entries.append(
                GridFitEntry(
                    optimizer_configs=overrides,
                    result=GameFitResult(
                        model=result.model,
                        best_model=result.best_model,
                        best_metric=result.best_metric,
                        history=result.history,
                    ),
                )
            )
        return sorted(
            entries,
            key=lambda e: e.result.best_metric,
            reverse=better_than(primary, 1.0, 0.0),  # True iff maximizing
        )


def _config_metadata(config: GameConfig) -> dict:
    """JSON-safe description of the training config (model-metadata analog)."""

    def describe_opt(opt):
        out = {
            "type": str(opt.optimizer_type.value),
            "max_iterations": opt.max_iterations,
            "tolerance": opt.tolerance,
            "regularization": str(opt.regularization.reg_type.value),
            "alpha": opt.regularization.alpha,
            "regularization_weight": opt.regularization_weight,
            "lbfgs_history": opt.lbfgs_history,
            "down_sampling_rate": opt.down_sampling_rate,
        }
        if opt.box_constraints:
            out["box_constraints"] = [
                [
                    i,
                    None if lo == float("-inf") else lo,
                    None if hi == float("inf") else hi,
                ]
                for i, lo, hi in opt.box_constraints
            ]
        return out

    def describe(c):
        out = {"shard_name": c.shard_name}
        if isinstance(c, RandomEffectConfig):
            out["type"] = "random_effect"
            out["id_name"] = c.id_name
            out["active_rows_per_entity"] = c.active_rows_per_entity
            out["min_rows_per_entity"] = c.min_rows_per_entity
            out["features_to_samples_ratio"] = c.features_to_samples_ratio
            out["projector"] = c.projector
            out["projected_dim"] = c.projected_dim
            out["projection_seed"] = c.projection_seed
            out["projection_intercept_index"] = c.projection_intercept_index
            out["compute_variances"] = c.compute_variances
            out["optimizer"] = describe_opt(c.optimizer)
        elif isinstance(c, FactoredRandomEffectConfig):
            out["type"] = "factored_random_effect"
            out["id_name"] = c.id_name
            out["active_rows_per_entity"] = c.active_rows_per_entity
            out["min_rows_per_entity"] = c.min_rows_per_entity
            out["latent_dim"] = c.latent_dim
            out["mf_iterations"] = c.mf_iterations
            out["seed"] = c.seed
            out["optimizer"] = describe_opt(c.re_optimizer)
            out["latent_optimizer"] = describe_opt(c.latent_optimizer)
        else:
            out["type"] = "fixed_effect"
            out["normalization"] = str(NormalizationType(c.normalization).value)
            out["intercept_index"] = c.intercept_index
            out["layout"] = c.layout
            out["down_sampling_seed"] = c.down_sampling_seed
            out["optimizer"] = describe_opt(c.optimizer)
        return out

    return {
        "task": config.task,
        "num_iterations": config.num_iterations,
        "evaluators": list(config.evaluators),
        "coordinates": {n: describe(c) for n, c in config.coordinates.items()},
    }
