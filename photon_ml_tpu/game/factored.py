"""Factored random effects (the matrix-factorization coordinate) and the
standalone matrix-factorization scoring model.

Reference analog: photon-api algorithm/FactoredRandomEffectCoordinate.scala
:39-287 and model/MatrixFactorizationModel.scala:35-64. The factored
coordinate represents each entity's model as a K-dim latent vector c_e plus
a SHARED latent projection matrix A [K, d]; a row of entity e scores
(A x) . c_e. Training alternates (numIterations times):

  1. latent-space RE solve: project each entity's data through A and run the
     per-entity GLM solves in R^K (reusing RandomEffectCoordinate.updateModel
     in the reference, :111-130; here the vmapped bucket solver),
  2. latent matrix refit: fix the c_e and refit vec(A) as ONE distributed
     GLM over kronecker(x, c_e) features (updateLatentProjectionMatrix
     :226-255, kroneckerProductFeaturesAndCoefficients :269-287).

TPU-first shape trick: the kronecker-expanded design has STATIC structure —
for nnz (row i, col j, value v) of entity e, the expanded entries are
(i, j*K + l, v * c_e[l]) for l < K. The (rows, cols) index arrays are built
once at coordinate construction; each refit only recomputes the VALUES by a
[m, K] gather of the current latent table — no data movement, no reshuffle,
one jit-compiled solve per refit (vs the reference's regenerated + reshuffled
RDD per iteration). The reference's sparsityToleranceThreshold (drop tiny
products) does not apply: XLA needs static shapes, and zero values are inert.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from photon_ml_tpu.data.projection import (
    ProjectionMatrix,
    build_gaussian_projection_matrix,
)
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.models import map_vocab_codes
from photon_ml_tpu.game.random_effect_data import RandomEffectDataset
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.factory import OptimizerConfig, dispatch_solve
from photon_ml_tpu.parallel.distributed import distributed_solve
from photon_ml_tpu.telemetry.xla import instrumented_jit

Array = jax.Array


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectModel:
    """Latent per-entity vectors + the shared projection matrix.

    ``latent`` is one flat [n_active_entities, K] table (entities of every
    geometry bucket concatenated); ``entity_flat`` maps a TRAINING entity
    code to its row (-1 = entity unseen / inactive). Models always score in
    projected space: score = (A x) . c_e (FactoredRandomEffectModel
    .toRandomEffectModel + RandomEffectCoordinate.score in the reference).
    """

    id_name: str
    shard_name: str
    projection: ProjectionMatrix  # A: [K, d]
    latent: Array  # f[n_flat, K]
    entity_flat: np.ndarray  # host i64[num_entities] code -> flat row | -1
    vocab: np.ndarray  # training id vocabulary

    @property
    def latent_dim(self) -> int:
        return self.latent.shape[1]

    def score(self, data: GameDataset) -> Array:
        """[n_pad] scores; entities without a latent vector score 0."""
        if data.id_columns.get(self.id_name) is None:
            raise KeyError(f"scoring data lacks id column '{self.id_name}'")
        batch = data.shard(self.shard_name)
        n = data.num_rows
        idc = data.id_columns[self.id_name]
        codes = map_vocab_codes(self.vocab, idc.vocab[idc.codes])
        flat_of_row = np.where(codes >= 0, self.entity_flat[np.maximum(codes, 0)], -1)

        vals = np.asarray(batch.values)
        rows = np.asarray(batch.rows)
        cols = np.asarray(batch.cols)
        live_idx = np.nonzero((vals != 0) & (rows < n))[0]

        # TRANSPOSED per-nnz gathers in bounded chunks: [K, m] keeps the
        # long nnz dim in lanes (a [m, K] gather pads lanes 128/K-fold;
        # measured 12.3 GB of pure padding at K=2 on 16M nnz), and the
        # chunking bounds the transient at any shard size
        CHUNK = 8_000_000
        out = jnp.zeros((batch.num_rows,), batch.dtype)
        for lo in range(0, len(live_idx), CHUNK):
            part = live_idx[lo:lo + CHUNK]
            v = jnp.asarray(vals[part], batch.dtype)
            r = jnp.asarray(rows[part], jnp.int32)
            g = jnp.asarray(cols[part], jnp.int32)
            f = jnp.asarray(flat_of_row[rows[part]], jnp.int32)
            c_t = self.latent.T[:, jnp.maximum(f, 0)]  # [K, m]
            # features beyond the training dimension score 0 (a scoring
            # shard's vocabulary may be larger than training's; clamped
            # gathers would otherwise alias them onto the last training
            # column)
            known = g < self.projection.original_dim
            a_t = self.projection.matrix[
                :, jnp.minimum(g, self.projection.original_dim - 1)
            ]  # [K, m]
            contrib = jnp.where(
                (f >= 0) & known, v * jnp.sum(c_t * a_t, axis=0), 0.0
            )
            out = out.at[r].add(contrib)
        return out

    def to_summary_string(self) -> str:
        n_models = int(np.sum(self.entity_flat >= 0))
        return (
            f"FactoredRandomEffectModel(id={self.id_name}, "
            f"shard={self.shard_name}, entities={n_models}/{len(self.vocab)}, "
            f"latent_dim={self.latent_dim}, "
            f"original_dim={self.projection.original_dim})"
        )

    def effective_coefficients(self, entity_value) -> Optional[Array]:
        """Original-space d-dim coefficients A^T c_e for one entity (the
        projectCoefficients view), or None if the entity is unseen."""
        code = map_vocab_codes(self.vocab, np.asarray([entity_value]))[0]
        if code < 0 or self.entity_flat[code] < 0:
            return None
        return self.projection.project_coefficients(
            self.latent[int(self.entity_flat[code])]
        )


@dataclasses.dataclass(frozen=True)
class MatrixFactorizationModel:
    """Row/column latent-factor scoring model
    (model/MatrixFactorizationModel.scala:35-64): score(datum) =
    rowFactors[row_id] . colFactors[col_id]; rows/cols unseen in either
    vocabulary score 0."""

    row_effect: str  # id column naming matrix rows (e.g. "userId")
    col_effect: str  # id column naming matrix cols (e.g. "movieId")
    row_factors: Array  # f[n_row_entities, K]
    col_factors: Array  # f[n_col_entities, K]
    row_vocab: np.ndarray
    col_vocab: np.ndarray

    @property
    def num_latent_factors(self) -> int:
        return self.row_factors.shape[1]

    def score(self, data: GameDataset) -> Array:
        for eff in (self.row_effect, self.col_effect):
            if data.id_columns.get(eff) is None:
                raise KeyError(f"scoring data lacks id column '{eff}'")
        rc = data.id_columns[self.row_effect]
        cc = data.id_columns[self.col_effect]
        r_codes = map_vocab_codes(self.row_vocab, rc.vocab[rc.codes])
        c_codes = map_vocab_codes(self.col_vocab, cc.vocab[cc.codes])
        ok = (r_codes >= 0) & (c_codes >= 0)
        rf = self.row_factors[jnp.asarray(np.maximum(r_codes, 0), jnp.int32)]
        cf = self.col_factors[jnp.asarray(np.maximum(c_codes, 0), jnp.int32)]
        s = jnp.where(jnp.asarray(ok), jnp.sum(rf * cf, axis=1), 0.0)
        # align with the padded row count every score path uses
        n_pad = data.shard(next(iter(data.feature_shards))).num_rows
        return jnp.pad(s, (0, n_pad - s.shape[0]))


# ---------------------------------------------------------------------------
# coordinate
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _latent_design_T_fn(R: int):
    """[E]-vmapped transposed latent design X~^T [K, R].

    TPU layout note: the latent dim K is tiny (2-16) — any tensor with K
    as the TRAILING dim pads its lanes 128/K-fold (measured 64x = 12.3 GB
    of padding on a 197 MB gather at K=2). This variant keeps the long
    dims (NZ, R) in lanes throughout: the per-row reduction is a
    [K, NZ] @ [NZ, R] one-hot matmul instead of a segment_sum over
    [NZ, K] rows."""

    def one(values, rows, cols, projection, a_ext):
        K, d1 = a_ext.shape
        g = projection[cols]  # [NZ]
        # FLAT 1-D take from the flattened table: the 2-D-table gather
        # a_ext[:, g] materializes an [E*NZ, K] fusion output whose K
        # lanes pad to 128 (an 18 GB allocation at 20M rows); a 1-D-table
        # take with [K, NZ] indices keeps NZ in lanes throughout
        idx2 = g[None, :] + (jnp.arange(K, dtype=g.dtype) * d1)[:, None]
        a = jnp.take(a_ext.reshape(-1), idx2)  # [K, NZ]
        contrib = values[None, :] * a  # [K, NZ]
        onehot = (
            rows[None, :] == jnp.arange(R, dtype=rows.dtype)[:, None]
        ).astype(contrib.dtype)  # [R, NZ]
        return jax.lax.dot_general(
            contrib, onehot,
            dimension_numbers=(((1,), (1,)), ((), ())),
        )  # [K, R]

    return instrumented_jit(
        jax.vmap(one, in_axes=(0, 0, 0, 0, None)), name="factored_project"
    )


@lru_cache(maxsize=64)
def _latent_fit_solver(config: OptimizerConfig, loss_name: str):
    def run(obj, batch, w0, l1):
        return dispatch_solve(glm_adapter(obj, batch), w0, config, l1)

    return instrumented_jit(run, name="factored_latent_fit")


@instrumented_jit(name="factored_kron_values")
def _kron_values(vals_sorted, flat_idx, latent):
    """Row-sorted kron values: pre-permuted base values times a FLAT 1-D
    latent gather (see the construction comment — 2-D/tiny-trailing-dim
    gathers pad their program temps to 128 lanes at scale)."""
    return vals_sorted * jnp.take(latent.reshape(-1), flat_idx)


@dataclasses.dataclass
class FactoredRandomEffectCoordinate:
    """Alternating latent RE solve + latent-matrix GLM refit
    (FactoredRandomEffectCoordinate.scala:111-147).

    ``latent_dim`` is the latent-space dimension K and ``mf_iterations`` the
    alternation count (MFOptimizationConfiguration analog);
    ``re_config``/``latent_config`` are the per-entity and latent-matrix
    optimizer configs (FactoredRandomEffectOptimizationProblem)."""

    name: str
    data: GameDataset
    re_data: RandomEffectDataset
    loss_name: str
    re_config: OptimizerConfig
    latent_config: OptimizerConfig
    latent_dim: int
    mf_iterations: int = 1
    seed: int = 0
    mesh: Optional[Mesh] = None  # 1-D mesh: entity-shards the latent RE
    # solves (shard_map, no collectives) and data-parallels the latent
    # matrix refit (distributed_solve) over the same devices
    # refit_projection=False freezes A after random initialization: the
    # coordinate becomes RandomEffectCoordinateInProjectedSpace with a
    # Gaussian RandomProjection (ProjectorType.RANDOM analog) — per-entity
    # solves in the fixed projected space, no kron refit.
    refit_projection: bool = True
    # with refit_projection=False, optionally pass the intercept through the
    # projection untouched (buildGaussianRandomProjectionMatrix's
    # isKeepingInterceptTerm dummy row)
    projection_intercept_index: Optional[int] = None

    def __post_init__(self):
        if self.latent_dim < 1:
            raise ValueError("latent_dim must be >= 1")
        if self.mf_iterations < 1:
            raise ValueError("mf_iterations must be >= 1")
        if self.projection_intercept_index is not None and self.refit_projection:
            raise ValueError(
                "projection_intercept_index requires refit_projection=False "
                "(the MF refit would overwrite the passthrough row; the "
                "reference's MF init uses isKeepingInterceptTerm=false)"
            )
        self.re_config.validate(self.loss_name)
        self.latent_config.validate(self.loss_name)
        if self.re_config.box_constraints or self.latent_config.box_constraints:
            raise ValueError(
                "box constraints are not supported in latent/projected spaces"
            )
        k = self.latent_dim
        d = self.re_data.num_global_features
        buckets = self.re_data.buckets
        self._batch = self.data.shard(self.re_data.shard_name)
        n_pad = self._batch.num_rows
        # rows of A, including the optional intercept passthrough row
        self._proj_rows = k + (1 if self.projection_intercept_index is not None else 0)

        # flat latent-table layout: bucket entities concatenated in order
        sizes = [b.num_entities for b in buckets]
        self._flat_offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        self._n_flat = int(self._flat_offsets[-1])
        eb, ep = self.re_data.entity_bucket, self.re_data.entity_pos
        self._entity_flat = np.where(
            eb >= 0, self._flat_offsets[np.maximum(eb, 0)] + ep, -1
        ).astype(np.int64)

        if not self.refit_projection:
            # fixed projection: the kron structure is never needed
            key_re = dataclasses.replace(self.re_config, regularization_weight=0.0)
            from photon_ml_tpu.game.coordinates import _re_solver

            self._re_solver = _re_solver(key_re, self.loss_name)
            if self.mesh is not None:
                self._resolve_mesh_axis()
            self._re_obj = make_objective(
                self.loss_name,
                l2_weight=self.re_config.regularization.l2_weight(
                    self.re_config.regularization_weight
                ),
            )
            self._re_l1 = jnp.float32(
                self.re_config.regularization.l1_weight(
                    self.re_config.regularization_weight
                )
            )
            return

        # --- static kronecker structure (host, once) ---
        g_rows, g_cols, g_vals, g_ent = [], [], [], []
        for b_idx, b in enumerate(buckets):
            rows_l = np.asarray(b.rows)  # [E, NZ] local rows
            row_index = np.asarray(b.row_index)  # [E, R]
            gr = np.take_along_axis(row_index, rows_l, axis=1)  # [E, NZ]
            gc = np.take_along_axis(
                np.asarray(b.projection), np.asarray(b.cols), axis=1
            )
            vals = np.asarray(b.values)
            ent = np.broadcast_to(
                (self._flat_offsets[b_idx] + np.arange(b.num_entities))[:, None],
                gr.shape,
            )
            # padding nnz: value 0 -> contributions vanish; clamp indices
            # into range so gathers stay valid
            gr = np.where((gr < 0) | (vals == 0), n_pad - 1, gr)
            gc = np.where(gc >= d, 0, gc)
            g_rows.append(gr.reshape(-1))
            g_cols.append(gc.reshape(-1))
            g_vals.append(vals.reshape(-1))
            g_ent.append(ent.reshape(-1))
        g_rows = np.concatenate(g_rows) if g_rows else np.zeros(0, np.int64)
        g_cols = np.concatenate(g_cols) if g_cols else np.zeros(0, np.int64)
        g_vals = np.concatenate(g_vals) if g_vals else np.zeros(0)
        g_ent = np.concatenate(g_ent) if g_ent else np.zeros(0, np.int64)
        m = len(g_vals)

        kron_rows = np.repeat(g_rows, k)
        kron_cols = (g_cols[:, None] * k + np.arange(k)[None, :]).reshape(-1)

        # active-row labels/weights/base-offsets scattered from the buckets
        # (weights carry the active-data cap rescale; passive rows weight 0)
        lab = np.zeros(n_pad)
        wgt = np.zeros(n_pad)
        off = np.zeros(n_pad)
        for b in buckets:
            ri = np.asarray(b.row_index)
            valid = ri >= 0
            lab[ri[valid]] = np.asarray(b.labels)[valid]
            wgt[ri[valid]] = np.asarray(b.weights)[valid]
            off[ri[valid]] = np.asarray(b.offsets)[valid]
        self._base_offsets = off

        # order nnz by row for segment-sum friendliness. The base values
        # and flat latent-gather indices are PRE-PERMUTED on the host so
        # each matrix step is one flat 1-D take (a runtime [m*k]
        # permutation gather — or a [m, K] latent gather — lowers with
        # tiny-trailing-dim index/output temps that pad to 128 lanes:
        # measured 12+ GB of padding at north-star scale).
        o = np.argsort(kron_rows, kind="stable")
        bases = o // k
        lcol = o % k
        self._kron_vals_sorted = jnp.asarray(
            g_vals[bases], self._batch.dtype
        )
        self._kron_flat_idx = jnp.asarray(
            g_ent[bases] * k + lcol, jnp.int32
        )
        self._num_kron_features = d * k

        key_re = dataclasses.replace(self.re_config, regularization_weight=0.0)
        key_lat = dataclasses.replace(self.latent_config, regularization_weight=0.0)
        # the per-entity bucket solver is shared with RandomEffectCoordinate
        # (identical dispatch; one lru_cache entry for both coordinate types)
        from photon_ml_tpu.game.coordinates import _re_solver

        self._re_solver = _re_solver(key_re, self.loss_name)
        self._lat_solver = _latent_fit_solver(key_lat, self.loss_name)
        if self.mesh is not None:
            self._resolve_mesh_axis()
            # mesh mode never materializes the single-device kron template
            self._latent_template = None
            self._build_stacked_latent(kron_rows[o], kron_cols[o], lab, wgt)
        else:
            self._latent_template = SparseBatch(
                values=jnp.zeros((m * k,), self._batch.dtype),
                rows=jnp.asarray(kron_rows[o], jnp.int32),
                cols=jnp.asarray(kron_cols[o], jnp.int32),
                labels=jnp.asarray(lab, self._batch.dtype),
                offsets=jnp.asarray(off, self._batch.dtype),
                weights=jnp.asarray(wgt, self._batch.dtype),
                num_features=d * k,
            )
        self._re_obj = make_objective(
            self.loss_name,
            l2_weight=self.re_config.regularization.l2_weight(
                self.re_config.regularization_weight
            ),
        )
        self._re_l1 = jnp.float32(
            self.re_config.regularization.l1_weight(
                self.re_config.regularization_weight
            )
        )
        self._lat_obj = make_objective(
            self.loss_name,
            l2_weight=self.latent_config.regularization.l2_weight(
                self.latent_config.regularization_weight
            ),
        )
        self._lat_l1 = jnp.float32(
            self.latent_config.regularization.l1_weight(
                self.latent_config.regularization_weight
            )
        )

    def _resolve_mesh_axis(self) -> None:
        """Pick the ONE mesh axis this coordinate parallelizes over: the
        entity-sharded latent solves and the row-stacked kron refit both
        use it, so their shard counts agree. A model/entity axis wins
        (the latent table is per-entity state), then a batch/data axis,
        then the mesh's first axis (legacy 1-D meshes)."""
        from photon_ml_tpu.parallel import sharding as psharding

        self._axis = (
            psharding.model_axis(self.mesh)
            or psharding.data_axis(self.mesh)
            or self.mesh.axis_names[0]
        )
        self._n_dev = psharding.axis_size(self.mesh, self._axis)

    def _build_stacked_latent(self, rows_np, cols_np, lab, wgt) -> None:
        """Pre-shard the STATIC kronecker structure over the mesh: contiguous
        row blocks per device with local row ids, plus an index map so each
        refit only gathers the fresh values into place (the per-iteration
        analog of FixedEffectCoordinate._restack)."""
        n_dev = self._n_dev
        n_pad = self._batch.num_rows
        rows_per = -(-n_pad // n_dev)
        shard_of = np.minimum(rows_np // rows_per, n_dev - 1)
        counts = np.bincount(shard_of, minlength=n_dev)
        nnz_max = max(int(counts.max()), 1)

        idx_map = np.full((n_dev, nnz_max), -1, np.int64)
        srows = np.full((n_dev, nnz_max), rows_per - 1, np.int32)
        scols = np.zeros((n_dev, nnz_max), np.int32)
        for s in range(n_dev):
            sel = np.nonzero(shard_of == s)[0]
            idx_map[s, : len(sel)] = sel
            srows[s, : len(sel)] = rows_np[sel] - s * rows_per
            scols[s, : len(sel)] = cols_np[sel]

        def rowwise(a):
            out = np.zeros((n_dev * rows_per,))
            out[: len(a)] = a
            return jnp.asarray(out.reshape(n_dev, rows_per), self._batch.dtype)

        from photon_ml_tpu.parallel.mesh import put_sharded

        self._stacked_rows_per = rows_per
        self._stacked_idx = jnp.asarray(idx_map, jnp.int32)
        # place each shard's static block on its device once (the
        # FixedEffectCoordinate put_sharded pattern); refits only move values
        stacked_host = SparseBatch(
            values=jnp.zeros((n_dev, nnz_max), self._batch.dtype),
            rows=jnp.asarray(srows),
            cols=jnp.asarray(scols),
            labels=rowwise(lab),
            offsets=rowwise(self._base_offsets),
            weights=rowwise(wgt),
            num_features=self._num_kron_features,
        )
        self._stacked_template = put_sharded(stacked_host, self.mesh, self._axis)

    # -- model plumbing ------------------------------------------------------

    def initialize_model(self) -> FactoredRandomEffectModel:
        """Zero latent vectors + a Gaussian random projection
        (FactoredRandomEffectCoordinate.initializeModel:190-212, which seeds
        A with buildRandomProjectionBroadcastProjector)."""
        proj = build_gaussian_projection_matrix(
            self.latent_dim,
            self.re_data.num_global_features,
            intercept_index=self.projection_intercept_index,
            seed=self.seed,
        )
        return FactoredRandomEffectModel(
            id_name=self.re_data.id_name,
            shard_name=self.re_data.shard_name,
            projection=proj,
            latent=jnp.zeros((self._n_flat, self._proj_rows), jnp.float32),
            entity_flat=self._entity_flat,
            vocab=self.data.id_columns[self.re_data.id_name].vocab,
        )

    def _bucket_slice(self, latent: Array, b_idx: int) -> Array:
        lo = int(self._flat_offsets[b_idx])
        hi = int(self._flat_offsets[b_idx + 1])
        return latent[lo:hi]

    def _latent_re_step(
        self, latent: Array, a_ext: Array, residual: Optional[Array]
    ):
        """One pass of per-entity solves in latent space over all buckets.
        Returns ``(latent', (its, reasons, values))`` — the telemetry stays
        as DEVICE arrays so the MF alternation loop never blocks on a host
        fetch; update_model packs it once after the loop."""
        k = self._proj_rows
        parts = []
        t_its, t_reasons, t_vals = [], [], []
        for b_idx, b in enumerate(self.re_data.device_buckets()):
            bucket = b if residual is None else b.with_extra_offsets(residual)
            E, R = b.num_entities, b.rows_per_entity
            # transposed design (long dims in lanes) then one bounded
            # [E, R, K] transpose: the direct [.., K]-trailing gather pads
            # lanes 128/K-fold (12.3 GB of padding at K=2 on this bucket)
            X = _latent_design_T_fn(R)(
                b.values, b.rows, b.cols, b.projection, a_ext
            ).transpose(0, 2, 1)  # [E, R, K]
            dense = SparseBatch(
                values=X.reshape(E, R * k),
                rows=jnp.broadcast_to(
                    jnp.repeat(jnp.arange(R, dtype=jnp.int32), k), (E, R * k)
                ),
                cols=jnp.broadcast_to(
                    jnp.tile(jnp.arange(k, dtype=jnp.int32), R), (E, R * k)
                ),
                labels=bucket.labels,
                offsets=bucket.offsets,
                weights=bucket.weights,
                num_features=k,
            )
            w0 = self._bucket_slice(latent, b_idx)
            if self.mesh is None:
                res, _ = self._re_solver(
                    self._re_obj, dense, w0, self._re_l1, None
                )
                w = res.w
            else:
                total = -(-E // self._n_dev) * self._n_dev
                from photon_ml_tpu.game.coordinates import (
                    _pad_entities,
                    place_entity_solve,
                    record_entity_solve_comms,
                )

                dense_p, w0_p = _pad_entities(dense, w0, total)
                dense_p, w0_p, _ = place_entity_solve(
                    self.mesh, self._axis, dense_p, w0_p
                )
                record_entity_solve_comms(
                    "latent_re_solve", self.mesh, self._axis,
                    self.re_config.max_iterations,
                )
                res, _ = self._re_solver(
                    self._re_obj, dense_p, w0_p, self._re_l1, None
                )
                w = res.w[:E]
            parts.append(w)
            t_its.append(res.iterations[:E])
            t_reasons.append(res.reason[:E])
            t_vals.append(res.value[:E])
        new_latent = jnp.concatenate(parts, axis=0) if parts else latent
        return new_latent, (t_its, t_reasons, t_vals)

    def _latent_matrix_step(self, latent: Array, a: Array, residual: Optional[Array]):
        """Refit vec(A) as one GLM over the static kronecker structure.
        Returns ``(A', SolveResult)`` — tracker construction (4 scalar host
        fetches) is deferred past the MF loop by update_model."""
        vals = _kron_values(
            self._kron_vals_sorted, self._kron_flat_idx, latent
        )
        w0 = a.T.reshape(-1)  # vec layout matches cols j*K + l
        k = self.latent_dim
        if self.mesh is not None:
            # scatter the fresh values into the pre-sharded static layout;
            # everything else about the stacked batch is fixed
            sv = jnp.where(
                self._stacked_idx >= 0,
                vals[jnp.maximum(self._stacked_idx, 0)],
                0.0,
            )
            stacked = dataclasses.replace(self._stacked_template, values=sv)
            if residual is not None:
                off = jnp.asarray(self._base_offsets, sv.dtype) + residual
                total = self._n_dev * self._stacked_rows_per
                off = jnp.pad(off, (0, total - off.shape[0]))
                stacked = dataclasses.replace(
                    stacked, offsets=off.reshape(self._n_dev, -1)
                )
            res = distributed_solve(
                self.loss_name,
                stacked,
                self.latent_config,
                w0,
                self.mesh,
                axis=self._axis,
            )
            return res.w.reshape(-1, k).T, res
        batch = dataclasses.replace(self._latent_template, values=vals)
        if residual is not None:
            off = jnp.asarray(self._base_offsets, batch.dtype) + residual
            batch = dataclasses.replace(batch, offsets=off)
        res = self._lat_solver(self._lat_obj, batch, w0, self._lat_l1)
        return res.w.reshape(-1, k).T, res  # [K, d]

    def update_model(
        self,
        model: FactoredRandomEffectModel,
        residual_scores: Optional[Array],
    ) -> FactoredRandomEffectModel:
        from photon_ml_tpu.optim.trackers import (
            FactoredRandomEffectOptimizationTracker,
            FixedEffectOptimizationTracker,
            RandomEffectOptimizationTracker,
        )

        latent = model.latent
        a = model.projection.matrix
        if not self.refit_projection:
            # fixed random projection: per-entity solves only
            latent, re_parts = self._latent_re_step(
                latent, model.projection.extended(), residual_scores
            )
            re_t = RandomEffectOptimizationTracker.from_device_parts(*re_parts)
            self.last_tracker = FactoredRandomEffectOptimizationTracker(
                steps=((re_t, None),)
            )
            return dataclasses.replace(model, latent=latent)
        raw_steps = []
        for _ in range(self.mf_iterations):
            a_ext = ProjectionMatrix(matrix=a).extended()
            latent, re_parts = self._latent_re_step(latent, a_ext, residual_scores)
            a, lat_res = self._latent_matrix_step(latent, a, residual_scores)
            raw_steps.append((re_parts, lat_res))
        # all host fetches happen HERE, after the alternation finished, so
        # each iteration's dispatch overlaps the previous one's execution
        self.last_tracker = FactoredRandomEffectOptimizationTracker(
            steps=tuple(
                (
                    RandomEffectOptimizationTracker.from_device_parts(*rp),
                    FixedEffectOptimizationTracker.from_result(lr),
                )
                for rp, lr in raw_steps
            )
        )
        return dataclasses.replace(
            model, latent=latent, projection=ProjectionMatrix(matrix=a)
        )

    def score(self, model: FactoredRandomEffectModel) -> Array:
        """Training-data scores: bucket fast path for active rows, generic
        model path for passive rows."""
        a_ext = model.projection.extended()
        n_pad = self._batch.num_rows
        scores = jnp.zeros((n_pad,), jnp.float32)
        for b_idx, b in enumerate(self.re_data.device_buckets()):
            R = b.rows_per_entity
            # same transposed-design + transpose consumption as
            # _latent_re_step: feeding the [E, K, R] design straight into
            # an einsum made XLA materialize the inner gather as a
            # lane-padded [m, K] fusion output (18 GB at 20M rows)
            X = _latent_design_T_fn(R)(
                b.values, b.rows, b.cols, b.projection, a_ext
            ).transpose(0, 2, 1)  # [E, R, K]
            c = self._bucket_slice(model.latent, b_idx)  # [E, K]
            margins = jnp.einsum("erk,ek->er", X, c)
            idx = b.row_index.reshape(-1)
            scores = scores.at[jnp.maximum(idx, 0)].add(
                jnp.where(idx >= 0, margins.reshape(-1), 0.0)
            )
        if len(self.re_data.passive_rows):
            passive = model.score(self.data)
            mask = np.zeros(n_pad, bool)
            mask[self.re_data.passive_rows] = True
            scores = jnp.where(jnp.asarray(mask), passive, scores)
        return scores
