"""Continuous-freshness loop: delta-aware incremental warm-start retrains.

The production GLMix cadence in the reference is a slow offline Spark
batch — every retrain re-reads everything and re-solves every entity,
even when a day's delta touches 5% of them. This package composes three
landed contracts into a retrain that is minutes-shaped instead of
hours-shaped:

- deterministic ``ChunkPlan`` ordering (``ingest.planner``) makes
  "yesterday's data ∪ today's delta" a stable, replayable stream —
  appending delta shards never renumbers yesterday's chunks;
- sharded elastic checkpoints (``game.checkpoint.restore_placed``) make
  yesterday's coefficient table a warm-start artifact on ANY mesh
  (:func:`load_warm_start`);
- the masked-lane vmap pattern (the sweep's lane re-init idea) drives
  coordinate descent so that ONLY the random-effect lanes the delta
  touched re-solve (:class:`MaskedRandomEffectCoordinate`) — the
  untouched majority keeps its converged coefficients **bit-identical**,
  and bucket solves containing zero touched entities are skipped
  entirely — while the fixed effect refreshes over the combined stream.

Stages:

- :mod:`.warmstart` — :func:`load_warm_start` (step checkpoints, saved
  model dirs, AND sharded streaming checkpoints restored straight onto
  the training mesh), vocabulary-growth row expansion
  (:func:`grow_entity_rows`: new entities zero-init, existing rows
  bit-identical), and :class:`BaseLineage` recording the base artifact's
  identity for registry metadata.
- :mod:`.delta` — touched-entity detection over the interned entity-id
  columns of the delta, both the in-core reader path
  (:func:`scan_delta`) and the out-of-core ``ChunkStream`` path
  (:func:`scan_delta_stream`); telemetry
  ``incremental.touched_entities`` / ``incremental.touched_fraction``.
- :mod:`.refit` — the selective re-solve
  (:func:`run_incremental_fit`, surfaced as
  ``GameEstimator.fit_incremental``), with an optional small
  descending-λ sweep around the incumbent's regularization selected by
  the existing ``sweep.select`` policies.
- :mod:`.publish` — :func:`publish_incremental`: registry publish with
  the lineage record (``base_version`` / ``warm_start_checkpoint`` /
  delta digest) in version metadata, rendered by ``cli report`` and
  ``/healthz``.

Surfaces: ``cli train --warm-start <dir> [--delta <paths>]``, the
``cli refresh`` subcommand, ``GameEstimator.fit_incremental``, the
RunReport "Freshness" section, and ``bench_freshness.py``
(time-to-fresh-model vs full retrain at a 5% delta).
"""

from photon_ml_tpu.incremental.warmstart import (  # noqa: F401
    BaseLineage,
    WarmStart,
    WarmStartError,
    detect_warm_start_kind,
    grow_entity_rows,
    load_warm_start,
)
from photon_ml_tpu.incremental.delta import (  # noqa: F401
    CoordinateDelta,
    DeltaScan,
    delta_digest,
    scan_delta,
    scan_delta_stream,
)
from photon_ml_tpu.incremental.refit import (  # noqa: F401
    IncrementalFitResult,
    MaskedFactoredRandomEffectCoordinate,
    MaskedRandomEffectCoordinate,
    local_lambda_factors,
    run_incremental_fit,
    transplant_factored_random_effect,
    transplant_fixed_effect,
    transplant_random_effect,
)
from photon_ml_tpu.incremental.publish import (  # noqa: F401
    StaleDeltaError,
    check_delta_freshness,
    lineage_record,
    publish_incremental,
)

__all__ = [
    "BaseLineage",
    "CoordinateDelta",
    "DeltaScan",
    "IncrementalFitResult",
    "MaskedFactoredRandomEffectCoordinate",
    "MaskedRandomEffectCoordinate",
    "StaleDeltaError",
    "WarmStart",
    "WarmStartError",
    "check_delta_freshness",
    "delta_digest",
    "detect_warm_start_kind",
    "grow_entity_rows",
    "lineage_record",
    "load_warm_start",
    "local_lambda_factors",
    "publish_incremental",
    "run_incremental_fit",
    "scan_delta",
    "scan_delta_stream",
    "transplant_factored_random_effect",
    "transplant_fixed_effect",
    "transplant_random_effect",
]
