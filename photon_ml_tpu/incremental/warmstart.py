"""Warm-start loading: yesterday's checkpoint becomes today's starting
table, on whatever mesh today's run has.

Three base-artifact kinds are recognized (:func:`detect_warm_start_kind`):

- ``"step"`` — a coordinate-descent checkpoint directory
  (``step-NNNNNNNN/`` dirs from :class:`~photon_ml_tpu.game.checkpoint.
  CheckpointManager`): the full GAME model restores via the manager's
  newest-valid-fallback walk.
- ``"streaming"`` — a sharded streamed-table checkpoint
  (``chunk-NNNNNNNN/`` dirs from ``StreamingCheckpointManager``): the
  coefficient table restores ELASTICALLY straight onto the training mesh
  via ``restore_placed()`` (per-device shard reads, no host
  materialization) and is wrapped with
  ``ShardedCoefficientTable.from_coefficients`` — no zero-init +
  overwrite.
- ``"model"`` — a saved model directory (``model-metadata.json``): the
  ``final/`` / ``best/`` layout the training driver writes.

Vocabulary growth: a delta stream introduces entities the base run never
saw, so the current index map can hold MORE entities than the
checkpoint. :func:`grow_entity_rows` appends zero-initialized rows while
keeping existing rows bit-identical; an entity count that cannot divide
the target mesh's model axis raises the same typed
:class:`~photon_ml_tpu.parallel.sharding.ElasticPlacementError` elastic
restore uses, listing the legal axis sizes.

Every load records a :class:`BaseLineage` — the base checkpoint's
identity (directory, kind, cursor, content digest) — which publishing
threads into registry version metadata so a served model is traceable to
its training ancestor.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
from typing import Optional

import jax.numpy as jnp

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.parallel import sharding as psharding

logger = logging.getLogger("photon_ml_tpu.incremental")

# Injection seam: the warm-start restore entry. An `io` rule here is the
# transient flaky-read shape (the base dir lives on shared storage); a
# kill here must leave the BASE checkpoint untouched — the restore path
# only ever reads it.
FP_WARM_RESTORE = faults.register_point(
    "incremental.warm_restore",
    description="entry of a warm-start checkpoint restore (read-only: "
    "the base checkpoint is never written)",
)


class WarmStartError(RuntimeError):
    """The warm-start directory is unusable for an incremental fit; the
    message names the directory and what was expected there."""


@dataclasses.dataclass(frozen=True)
class BaseLineage:
    """Identity of the base artifact an incremental fit started from.

    ``digest`` is a sha256 over the certifying manifest/metadata file of
    the newest restored state — enough to prove later that the base was
    not mutated by the incremental run (the crash-row test keys on it),
    and to make two publishes from the same base recognizably siblings.
    """

    checkpoint_dir: str
    kind: str  # "step" | "streaming" | "model"
    step: Optional[int] = None
    next_chunk: Optional[int] = None
    digest: Optional[str] = None

    def to_json(self) -> dict:
        out = {"checkpoint_dir": self.checkpoint_dir, "kind": self.kind}
        if self.step is not None:
            out["step"] = int(self.step)
        if self.next_chunk is not None:
            out["next_chunk"] = int(self.next_chunk)
        if self.digest is not None:
            out["digest"] = self.digest
        return out


@dataclasses.dataclass
class WarmStart:
    """A loaded base artifact, ready to seed an incremental fit.

    ``model`` is set for ``step``/``model`` kinds (the full GAME model
    coordinate descent warm-starts from). ``table`` is set for the
    ``streaming`` kind — the elastically placed
    :class:`~photon_ml_tpu.game.streaming.ShardedCoefficientTable` a
    streamed trainer continues from at ``next_chunk``.
    """

    lineage: BaseLineage
    model: Optional[object] = None  # GameModel
    table: Optional[object] = None  # ShardedCoefficientTable
    variances: Optional[object] = None  # device array when checkpointed
    next_chunk: int = 0


def _digest_file(path: str) -> Optional[str]:
    try:
        with open(path, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()
    except OSError:
        return None


def detect_warm_start_kind(directory: str) -> str:
    """Classify a warm-start directory by its certifying artifacts."""
    if not os.path.isdir(directory):
        raise WarmStartError(
            f"warm-start directory does not exist: {directory}"
        )
    if os.path.exists(os.path.join(directory, "model-metadata.json")):
        return "model"
    names = os.listdir(directory)
    if any(n.startswith("step-") for n in names):
        return "step"
    if any(n.startswith("chunk-") for n in names):
        return "streaming"
    raise WarmStartError(
        f"{directory} holds neither a saved model (model-metadata.json), "
        "a step checkpoint (step-*/), nor a streamed-table checkpoint "
        "(chunk-*/) — nothing to warm-start from"
    )


def load_warm_start(
    directory: str,
    mesh=None,
    axis: Optional[str] = None,
) -> WarmStart:
    """Load the base artifact under ``directory`` for a warm start.

    The ``streaming`` kind restores the sharded table ELASTICALLY onto
    ``mesh`` (``restore_placed`` → ``ShardedCoefficientTable
    .from_coefficients``): a checkpoint written across 8 shards warm-
    starts a 4-device (or single-device) retrain with no host gather.
    ``step``/``model`` kinds return the full GAME model; both fall back
    past corrupt newest states exactly like their restore paths do.

    Read-only by construction: nothing under ``directory`` is created,
    cleared, or rewritten — the base checkpoint survives any crash of
    the incremental run.
    """
    faults.fault_point(FP_WARM_RESTORE)
    kind = detect_warm_start_kind(directory)
    with telemetry.span("incremental:warm_restore", kind=kind):
        if kind == "streaming":
            return _load_streaming(directory, mesh, axis)
        if kind == "step":
            return _load_step(directory)
        return _load_model_dir(directory)


def _load_streaming(directory: str, mesh, axis) -> WarmStart:
    from photon_ml_tpu.game.checkpoint import StreamingCheckpointManager
    from photon_ml_tpu.game.streaming import ShardedCoefficientTable

    mgr = StreamingCheckpointManager.open_for_restore(directory)
    restored = mgr.restore_placed(mesh=mesh, axis=axis)
    if restored is None:
        raise WarmStartError(
            f"{directory}: no valid streamed checkpoint to warm-start from"
        )
    table = ShardedCoefficientTable.from_coefficients(
        restored.coefficients, mesh=mesh, axis=axis
    )
    # digest the newest VALID chunk's manifest — the one restore used
    chunk_name = f"chunk-{restored.next_chunk:08d}"
    digest = _digest_file(os.path.join(directory, chunk_name,
                                       "manifest.json"))
    telemetry.counter("incremental.warm_restores").inc()
    return WarmStart(
        lineage=BaseLineage(
            checkpoint_dir=os.path.abspath(directory),
            kind="streaming",
            next_chunk=int(restored.next_chunk),
            digest=digest,
        ),
        table=table,
        variances=restored.variances,
        next_chunk=int(restored.next_chunk),
    )


def _load_step(directory: str) -> WarmStart:
    from photon_ml_tpu.game.checkpoint import (
        CheckpointManager,
        CheckpointSpec,
    )

    mgr = CheckpointManager(CheckpointSpec(directory=directory))
    state = mgr.restore()
    if state is None:
        raise WarmStartError(
            f"{directory}: no valid step checkpoint to warm-start from"
        )
    from photon_ml_tpu.game.checkpoint import _step_dirname

    digest = _digest_file(
        os.path.join(directory, _step_dirname(state.step), "manifest.json")
    )
    telemetry.counter("incremental.warm_restores").inc()
    return WarmStart(
        lineage=BaseLineage(
            checkpoint_dir=os.path.abspath(directory),
            kind="step",
            step=int(state.step),
            digest=digest,
        ),
        model=state.model,
    )


def _load_model_dir(directory: str) -> WarmStart:
    from photon_ml_tpu.data.model_store import ModelLoadError, load_game_model

    try:
        model = load_game_model(directory)
    except ModelLoadError as e:
        raise WarmStartError(
            f"{directory}: unloadable saved model ({e})"
        ) from e
    digest = _digest_file(os.path.join(directory, "model-metadata.json"))
    telemetry.counter("incremental.warm_restores").inc()
    return WarmStart(
        lineage=BaseLineage(
            checkpoint_dir=os.path.abspath(directory),
            kind="model",
            digest=digest,
        ),
        model=model,
    )


def grow_entity_rows(
    coefficients,
    num_entities: int,
    mesh=None,
    axis: Optional[str] = None,
):
    """Expand an ``[N_old, K]`` table to ``[num_entities, K]`` for a
    grown vocabulary: rows ``[0, N_old)`` stay **bit-identical**, new
    rows are zero-initialized (the same init a never-seen entity gets).

    With ``mesh`` the grown table is committed entity-sharded; a target
    entity count that does not divide the model axis raises the shared
    typed :class:`~photon_ml_tpu.parallel.sharding.ElasticPlacementError`
    naming the sizes that CAN hold it (an operator error, never a
    corrupt-skip). Shrinking is refused — dropping trained rows silently
    would be data loss.
    """
    n_old, k = (int(d) for d in coefficients.shape)
    num_entities = int(num_entities)
    if num_entities < n_old:
        raise WarmStartError(
            f"cannot shrink a warm-start table from {n_old} to "
            f"{num_entities} entities — the vocabulary may only grow"
        )
    grow = num_entities - n_old
    if mesh is None:
        if grow == 0:
            return coefficients
        return jnp.concatenate(
            [coefficients, jnp.zeros((grow, k), coefficients.dtype)], axis=0
        )
    sharding = psharding.entity_sharding(mesh, axis)
    resolved = sharding.spec[0]
    n_dev = psharding.axis_size(mesh, resolved)
    if num_entities % n_dev:
        raise psharding.entity_axis_mismatch(
            num_entities, resolved, n_dev, what="hold the grown vocabulary"
        )

    # non-donating jitted pad with the sharded out layout: GSPMD moves
    # each old row to its new owner, new rows materialize as zeros on
    # their shard — no host copy of either table. multi_shape: one fresh
    # closure per (grow, table) by design, not a recompile storm.
    def pad(w):
        return jnp.pad(w, ((0, grow), (0, 0)))

    grown = telemetry.instrumented_jit(
        pad,
        name="incremental_grow_rows",
        multi_shape=True,
        out_shardings=sharding,
    )(coefficients)
    if grow:
        telemetry.counter("incremental.grown_entities").inc(grow)
    return grown
