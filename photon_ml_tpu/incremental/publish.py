"""Registry publishing with lineage: a served version names its training
ancestor.

Nearline-published versions (serving.nearline) and sweep winners already
carry provenance fragments; incremental retrains complete the picture —
every version published here records a ``lineage`` block in its
``model-metadata.json``:

    {"lineage": {"base_version": "v-00000003",
                 "warm_start_checkpoint": "/ckpt/base",
                 "base_kind": "step", "base_step": 1,
                 "base_digest": "sha256...",
                 "delta_digest": "sha256...",
                 "delta_rows": 50000, "touched_fraction": 0.05}}

``serving.registry.publish_version(lineage=...)`` stores it, the
``ScoringEngine`` loads it, ``/healthz`` serves it, and the RunReport
"Freshness" section renders the training-side view — so "which data made
this model" is answerable from either end.
"""

from __future__ import annotations

from typing import Mapping, Optional

from photon_ml_tpu import faults, telemetry

# Injection seam: fires BEFORE the registry version assembly begins. A
# kill here (or anywhere inside publish_version's tmp-then-rename
# protocol) must leave the registry with no partial version and the
# warm-start base checkpoint untouched — the incremental crash row.
FP_PUBLISH = faults.register_point(
    "incremental.publish",
    description="before an incremental retrain assembles its registry "
    "version (tmp-then-rename; a kill leaves no partial version)",
)


def lineage_record(
    lineage,
    delta=None,
    base_version: Optional[str] = None,
) -> dict:
    """The JSON-safe lineage block registry metadata carries."""
    out: dict = {
        "warm_start_checkpoint": lineage.checkpoint_dir,
        "base_kind": lineage.kind,
    }
    if base_version is not None:
        out["base_version"] = base_version
    if lineage.step is not None:
        out["base_step"] = int(lineage.step)
    if lineage.next_chunk is not None:
        out["base_next_chunk"] = int(lineage.next_chunk)
    if lineage.digest is not None:
        out["base_digest"] = lineage.digest
    if delta is not None:
        out["delta_digest"] = delta.digest
        out["delta_rows"] = int(delta.delta_rows)
        out["delta_paths"] = list(delta.paths)
        fractions = [
            c.touched_fraction for c in delta.coordinates.values()
        ]
        if fractions:
            out["touched_fraction"] = round(max(fractions), 6)
    return out


def publish_incremental(
    registry_dir: str,
    model,
    index_maps: Mapping,
    lineage,
    delta=None,
    base_version: Optional[str] = None,
    extra_metadata: Optional[dict] = None,
    selection=None,
) -> str:
    """Atomically publish an incremental retrain's model as the next
    registry version, lineage in metadata. Returns the version path.

    ``base_version`` (optional): the registry version the base model was
    serving as, when known — closes the ancestor chain for nearline
    consumers. ``selection``: the local λ sweep's
    :class:`~photon_ml_tpu.sweep.select.SweepSelection`, recorded like
    the sweep exporter records it.
    """
    from photon_ml_tpu.serving.registry import publish_version

    faults.fault_point(FP_PUBLISH)
    meta = dict(extra_metadata or {})
    if selection is not None:
        meta["sweep_selection"] = selection.to_json()
    path = publish_version(
        registry_dir,
        model,
        index_maps,
        extra_metadata=meta,
        lineage=lineage_record(
            lineage, delta=delta, base_version=base_version
        ),
    )
    telemetry.counter("incremental.published_versions").inc()
    return path
