"""Registry publishing with lineage: a served version names its training
ancestor.

Nearline-published versions (serving.nearline) and sweep winners already
carry provenance fragments; incremental retrains complete the picture —
every version published here records a ``lineage`` block in its
``model-metadata.json``:

    {"lineage": {"base_version": "v-00000003",
                 "warm_start_checkpoint": "/ckpt/base",
                 "base_kind": "step", "base_step": 1,
                 "base_digest": "sha256...",
                 "delta_digest": "sha256...",
                 "delta_rows": 50000, "touched_fraction": 0.05}}

``serving.registry.publish_version(lineage=...)`` stores it, the
``ScoringEngine`` loads it, ``/healthz`` serves it, and the RunReport
"Freshness" section renders the training-side view — so "which data made
this model" is answerable from either end.
"""

from __future__ import annotations

from typing import Mapping, Optional

from photon_ml_tpu import faults, telemetry

# Injection seam: fires BEFORE the registry version assembly begins. A
# kill here (or anywhere inside publish_version's tmp-then-rename
# protocol) must leave the registry with no partial version and the
# warm-start base checkpoint untouched — the incremental crash row.
FP_PUBLISH = faults.register_point(
    "incremental.publish",
    description="before an incremental retrain assembles its registry "
    "version (tmp-then-rename; a kill leaves no partial version)",
)


class StaleDeltaError(ValueError):
    """A delta whose digest matches what the base already trained on.

    Re-publishing an identical delta silently produces a no-op version
    with fresh lineage — a cron job stuck on yesterday's shards would
    pollute the registry with indistinguishable versions. Typed so the
    CLI can refuse loudly (``--force`` overrides for deliberate
    republish, e.g. after a registry wipe)."""


def check_delta_freshness(
    registry_dir: str,
    delta_digest: str,
    force: bool = False,
) -> None:
    """Refuse a delta the newest published version already trained on.

    Compares ``delta_digest`` against the ``lineage.delta_digest`` the
    newest registry version recorded at publish time; a match raises
    :class:`StaleDeltaError` unless ``force``. An empty/absent registry
    or a newest version without delta lineage (full retrain, nearline)
    passes — there is nothing to be stale against."""
    import os

    if force or not registry_dir or not os.path.isdir(registry_dir):
        return
    from photon_ml_tpu.data.model_store import load_game_model_metadata
    from photon_ml_tpu.serving.registry import scan_versions

    versions = scan_versions(registry_dir)
    if not versions:
        return
    _, path = versions[-1]
    try:
        meta = load_game_model_metadata(path)
    except (OSError, ValueError, KeyError):
        return  # unreadable metadata cannot prove staleness
    recorded = ((meta.get("extra") or {}).get("lineage") or {}).get(
        "delta_digest"
    )
    if recorded is not None and recorded == delta_digest:
        raise StaleDeltaError(
            f"delta digest {delta_digest[:16]}... matches the digest "
            f"already published as {os.path.basename(path)} in "
            f"{registry_dir} — re-running on an unchanged delta would "
            "publish a no-op version; pass --force to republish anyway"
        )


def lineage_record(
    lineage,
    delta=None,
    base_version: Optional[str] = None,
    reconciliation: Optional[dict] = None,
) -> dict:
    """The JSON-safe lineage block registry metadata carries."""
    out: dict = {
        "warm_start_checkpoint": lineage.checkpoint_dir,
        "base_kind": lineage.kind,
    }
    if base_version is not None:
        out["base_version"] = base_version
    if lineage.step is not None:
        out["base_step"] = int(lineage.step)
    if lineage.next_chunk is not None:
        out["base_next_chunk"] = int(lineage.next_chunk)
    if lineage.digest is not None:
        out["base_digest"] = lineage.digest
    if delta is not None:
        out["delta_digest"] = delta.digest
        out["delta_rows"] = int(delta.delta_rows)
        out["delta_paths"] = list(delta.paths)
        fractions = [
            c.touched_fraction for c in delta.coordinates.values()
        ]
        if fractions:
            out["touched_fraction"] = round(max(fractions), 6)
    if reconciliation is not None:
        # the conductor's nearline-vs-delta decision rides the lineage
        # so causality is auditable from the registry alone (and from
        # /healthz, which serves the lineage of the running version)
        out["reconciliation"] = dict(reconciliation)
    return out


def publish_incremental(
    registry_dir: str,
    model,
    index_maps: Mapping,
    lineage,
    delta=None,
    base_version: Optional[str] = None,
    extra_metadata: Optional[dict] = None,
    selection=None,
    reconciliation: Optional[dict] = None,
    quality: Optional[dict] = None,
    gate_override: bool = False,
) -> str:
    """Atomically publish an incremental retrain's model as the next
    registry version, lineage in metadata. Returns the version path.

    ``base_version`` (optional): the registry version the base model was
    serving as, when known — closes the ancestor chain for nearline
    consumers. ``selection``: the local λ sweep's
    :class:`~photon_ml_tpu.sweep.select.SweepSelection`, recorded like
    the sweep exporter records it. ``reconciliation``: the conductor's
    nearline-vs-delta decision record, embedded in the lineage block.
    ``quality``/``gate_override`` arm the champion/challenger gate (see
    ``serving.registry.publish_version``): a candidate that regresses
    beyond the champion's bootstrap CI raises
    :class:`photon_ml_tpu.quality.gate.QualityGateRefused` and lands in
    quarantine instead of the registry proper.
    """
    from photon_ml_tpu.serving.registry import publish_version

    faults.fault_point(FP_PUBLISH)
    meta = dict(extra_metadata or {})
    if selection is not None:
        meta["sweep_selection"] = selection.to_json()
    path = publish_version(
        registry_dir,
        model,
        index_maps,
        extra_metadata=meta,
        lineage=lineage_record(
            lineage, delta=delta, base_version=base_version,
            reconciliation=reconciliation,
        ),
        quality=quality,
        gate_override=gate_override,
    )
    telemetry.counter("incremental.published_versions").inc()
    return path
