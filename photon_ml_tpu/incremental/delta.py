"""Delta detection: which entities did today's data actually touch?

Scans the interned entity-id columns of the delta stream into a
per-coordinate touched-entity set. Two paths, same answer:

- :func:`scan_delta` — the in-core reader path: a delta
  :class:`~photon_ml_tpu.game.dataset.GameDataset`'s ``IdColumn`` codes
  ARE the interned ids; one ``np.unique`` per column is the whole scan.
- :func:`scan_delta_stream` — the out-of-core path: a
  :class:`~photon_ml_tpu.ingest.ChunkStream` over the delta shards;
  touched codes accumulate per chunk from ``DeviceChunk.id_codes`` (the
  stream-global interning), and the stream's first-seen vocabulary maps
  codes back to raw id values at the end. Host-side set work only — the
  delta never needs to fit in memory at once.

Touched sets are stored as raw id VALUES (entity identity is the value,
not a dataset-local code — vocabulary growth shifts codes), and mapped
into whatever vocabulary a consumer holds via
:meth:`CoordinateDelta.touched_mask`. Telemetry:
``incremental.touched_entities`` (counter) and
``incremental.touched_fraction`` (gauge; also per-coordinate
``incremental.touched_fraction.<id>``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.game.models import map_vocab_codes

# Injection seam: the delta scan entry — an `io` rule models a flaky
# read of the delta shards; a raise must surface before any fit state
# exists (the scan is pure, nothing to roll back).
FP_DELTA_SCAN = faults.register_point(
    "incremental.delta_scan",
    description="entry of a touched-entity delta scan (pure read of the "
    "delta stream's interned id columns)",
)


@dataclasses.dataclass(frozen=True)
class CoordinateDelta:
    """The touched-entity set of one id column.

    ``touched_values`` are the raw id values the delta contains (sorted
    unique); ``new_values`` the subset absent from the BASE vocabulary
    (entities the warm-start table has no row for — zero-init on
    growth); ``base_entities`` the base vocabulary size the fraction is
    measured against.
    """

    id_name: str
    touched_values: np.ndarray
    new_values: np.ndarray
    base_entities: int

    @property
    def touched_count(self) -> int:
        return int(len(self.touched_values))

    @property
    def new_count(self) -> int:
        return int(len(self.new_values))

    @property
    def touched_fraction(self) -> float:
        return self.touched_count / max(self.base_entities, 1)

    def touched_mask(self, vocab: np.ndarray) -> np.ndarray:
        """Boolean mask over ``vocab`` (any vocabulary — base or the
        combined run's grown one) marking touched entities."""
        mask = np.zeros(len(vocab), bool)
        codes = map_vocab_codes(np.asarray(vocab),
                                np.asarray(self.touched_values))
        mask[codes[codes >= 0]] = True
        return mask

    def to_json(self) -> dict:
        return {
            "id_name": self.id_name,
            "touched_entities": self.touched_count,
            "new_entities": self.new_count,
            "base_entities": int(self.base_entities),
            "touched_fraction": round(self.touched_fraction, 6),
        }


@dataclasses.dataclass(frozen=True)
class DeltaScan:
    """All per-coordinate touched sets of one delta, plus its identity
    (``digest`` — the manifest fingerprint publishing records)."""

    coordinates: Mapping[str, CoordinateDelta]  # keyed by id column name
    delta_rows: int
    digest: str
    paths: tuple[str, ...] = ()

    def for_id(self, id_name: str) -> Optional[CoordinateDelta]:
        return self.coordinates.get(id_name)

    def to_json(self) -> dict:
        return {
            "delta_rows": int(self.delta_rows),
            "digest": self.digest,
            "paths": list(self.paths),
            "coordinates": {
                k: v.to_json() for k, v in self.coordinates.items()
            },
        }


#: per-file content sample hashed into the delta digest (head + tail) —
#: enough to catch same-size rewrites without streaming multi-GB shards
_DIGEST_SAMPLE_BYTES = 1 << 16


def delta_digest(paths: Sequence[str]) -> str:
    """Deterministic fingerprint of a delta file set: one record per
    file — basename, byte size, and a sha256 of a head+tail content
    sample — with the records themselves sorted, so the digest is a pure
    function of the FILE SET (caller order and mount prefixes are
    irrelevant, duplicate basenames across directories included).
    Changes whenever a shard is added, dropped, or rewritten — including
    a same-size rewrite, which a metadata-only fingerprint would miss —
    while never reading more than 128 KiB per shard."""
    records = []
    for p in paths:
        fh_hash = hashlib.sha256()
        try:
            size = os.path.getsize(p)
            with open(p, "rb") as fh:
                fh_hash.update(fh.read(_DIGEST_SAMPLE_BYTES))
                if size > 2 * _DIGEST_SAMPLE_BYTES:
                    fh.seek(-_DIGEST_SAMPLE_BYTES, os.SEEK_END)
                    fh_hash.update(fh.read(_DIGEST_SAMPLE_BYTES))
        except OSError:
            size = -1
        records.append(
            f"{os.path.basename(p)}:{size}:{fh_hash.hexdigest()};"
        )
    h = hashlib.sha256()
    for record in sorted(records):
        h.update(record.encode())
    return h.hexdigest()


def _record_telemetry(coords: Mapping[str, CoordinateDelta]) -> None:
    total_touched = 0
    worst = 0.0
    for name, cd in coords.items():
        total_touched += cd.touched_count
        worst = max(worst, cd.touched_fraction)
        telemetry.gauge(f"incremental.touched_fraction.{name}").set(
            cd.touched_fraction
        )
    if total_touched:
        telemetry.counter("incremental.touched_entities").inc(total_touched)
    telemetry.gauge("incremental.touched_fraction").set(worst)


def scan_delta(
    delta_data,
    base_vocabs: Mapping[str, np.ndarray],
    paths: Sequence[str] = (),
) -> DeltaScan:
    """In-core scan: touched sets from a delta ``GameDataset``'s interned
    id columns. ``base_vocabs`` maps id column name -> the BASE model's
    entity vocabulary (``RandomEffectModel.vocab``); only columns named
    there are scanned — an id column no coordinate trains on cannot
    gate any lane."""
    faults.fault_point(FP_DELTA_SCAN)
    with telemetry.span("incremental:delta_scan", rows=delta_data.num_rows):
        coords: dict[str, CoordinateDelta] = {}
        for id_name, base_vocab in base_vocabs.items():
            idc = delta_data.id_columns.get(id_name)
            if idc is None:
                raise KeyError(
                    f"delta data lacks id column '{id_name}'; have "
                    f"{sorted(delta_data.id_columns)}"
                )
            touched = idc.vocab[np.unique(idc.codes)]
            base_vocab = np.asarray(base_vocab)
            codes = map_vocab_codes(base_vocab, touched)
            coords[id_name] = CoordinateDelta(
                id_name=id_name,
                touched_values=np.sort(touched),
                new_values=np.sort(touched[codes < 0]),
                base_entities=len(base_vocab),
            )
        _record_telemetry(coords)
        return DeltaScan(
            coordinates=coords,
            delta_rows=int(delta_data.num_rows),
            digest=delta_digest(paths),
            paths=tuple(paths),
        )


def scan_delta_stream(
    paths: Sequence[str],
    base_vocabs: Mapping[str, np.ndarray],
    index_maps: Mapping,
    feature_shards: Optional[Mapping[str, Sequence[str]]] = None,
    spec=None,
) -> DeltaScan:
    """Out-of-core scan: stream the delta shards through a
    :class:`~photon_ml_tpu.ingest.ChunkStream` and accumulate touched
    interned codes chunk by chunk. Host residency is one staging ring
    regardless of delta size; the stream-global first-seen vocabulary
    maps the accumulated codes back to raw id values at the end —
    bit-identical touched sets to the in-core scan (tested)."""
    from photon_ml_tpu.ingest import ChunkStream

    faults.fault_point(FP_DELTA_SCAN)
    id_columns = tuple(base_vocabs)
    with telemetry.span("incremental:delta_scan", streamed=True):
        touched_codes: dict[str, set] = {c: set() for c in id_columns}
        rows = 0
        with ChunkStream(
            paths,
            feature_shards=feature_shards,
            index_maps=index_maps,
            id_columns=id_columns,
            spec=spec,
        ) as stream:
            for chunk in stream:
                rows += int(chunk.rows)
                for col in id_columns:
                    touched_codes[col].update(
                        np.unique(chunk.id_codes[col]).tolist()
                    )
            coords: dict[str, CoordinateDelta] = {}
            for col in id_columns:
                vocab = stream.id_vocabulary(col)
                code_arr = np.fromiter(
                    sorted(touched_codes[col]), dtype=np.int64,
                    count=len(touched_codes[col]),
                )
                touched = np.asarray(vocab[code_arr])
                base_vocab = np.asarray(base_vocabs[col])
                bcodes = map_vocab_codes(base_vocab, touched)
                coords[col] = CoordinateDelta(
                    id_name=col,
                    touched_values=np.sort(touched),
                    new_values=np.sort(touched[bcodes < 0]),
                    base_entities=len(base_vocab),
                )
        _record_telemetry(coords)
        return DeltaScan(
            coordinates=coords,
            delta_rows=rows,
            digest=delta_digest(paths),
            paths=tuple(paths),
        )
