"""Selective re-solve: coordinate descent where only touched RE lanes
re-solve.

The sweep's masked-lane idea (re-init only the lanes that need work,
PR 8's ``path_warm_start``) applied at the entity axis: per random-effect
bucket, the touched entities' sub-problems are GATHERED out of the
resident bucket stack, solved by the SAME lru-shared ``_re_solver``
executable family every other trainer uses (lanes padded to the next
power of two by repeating the last real lane — idempotent, and the
padded duplicate is already converged), and SCATTERED back into the
coefficient table. Untouched rows are never rewritten — they stay
**bit-identical** to the warm start. Buckets containing zero touched
entities are skipped entirely (no solve dispatched at all); the
fixed-effect coordinate refreshes normally over the combined stream.

Telemetry: ``incremental.lanes_solved`` / ``incremental.lanes_skipped``
(real entities re-solved vs kept), ``incremental.bucket_solves`` /
``incremental.buckets_skipped`` — the structural evidence
``bench_freshness.py`` asserts the ≥10× time-to-fresh claim on, and the
RunReport "Freshness" section renders.

Transplanting (:func:`transplant_random_effect`): the combined run's
bucket geometry is rebuilt from scratch, so the base model's per-entity
rows are re-homed by entity VALUE (vocabulary growth shifts codes) and
per-feature by GLOBAL feature id (an exact searchsorted take, so an
untouched entity's row — whose geometry cannot have changed — lands
bit-identical). Entities the base never saw zero-init, exactly like a
fresh fit would have initialized them.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.game.models import GameModel, map_vocab_codes
from photon_ml_tpu.optim.guard import damped_objective, solve_health

logger = logging.getLogger("photon_ml_tpu.incremental")


def _next_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x - 1).bit_length())


# ---------------------------------------------------------------------------
# warm-start transplanting
# ---------------------------------------------------------------------------


def transplant_fixed_effect(base, coord):
    """The base FE model, validated against the combined run's feature
    space. Incremental fits require the feature space pinned — a delta
    that grows/reorders features would silently mis-map every
    coefficient, so a dimension mismatch is a typed refusal."""
    from photon_ml_tpu.incremental.warmstart import WarmStartError

    fresh = coord.initialize_model()
    base_w = np.asarray(base.coefficients)
    if base_w.shape != tuple(fresh.coefficients.shape):
        raise WarmStartError(
            f"fixed-effect '{coord.name}': warm-start coefficients have "
            f"{base_w.shape[0]} features but the combined data has "
            f"{fresh.coefficients.shape[0]} — the feature space must stay "
            "pinned across incremental retrains (new entities are "
            "supported; new features are not)"
        )
    return dataclasses.replace(
        fresh, coefficients=jnp.asarray(base_w, fresh.coefficients.dtype)
    )


def transplant_random_effect(base, coord) -> tuple[object, np.ndarray]:
    """Re-home a base :class:`RandomEffectModel`'s per-entity rows into
    the combined run's freshly built bucket geometry.

    Returns ``(model, untransplanted_codes)`` — the combined-vocab codes
    of entities that zero-initialized because the base never trained a
    row for them (unseen value, or seen but without an active model).
    Those lanes MUST re-solve whatever the delta says: they have no
    converged coefficients to keep. Matching is by entity VALUE then
    global feature id (exact element take, bit-identical for entities
    whose geometry is unchanged — i.e. every entity the delta did not
    touch)."""
    red = coord.re_data
    fresh = coord.initialize_model()
    base_vocab = np.asarray(base.vocab)
    base_bucket = np.asarray(base.entity_bucket)
    base_pos = np.asarray(base.entity_pos)
    base_projs = [np.asarray(b.projection) for b in base.buckets]
    base_coeffs = [np.asarray(b.coefficients) for b in base.buckets]
    new_vocab = np.asarray(fresh.vocab)
    sentinel = red.num_global_features
    untransplanted: list[np.ndarray] = []

    out_buckets = []
    for bm in fresh.buckets:
        codes_new = np.asarray(bm.entity_codes)
        values = new_vocab[codes_new]
        bcodes = map_vocab_codes(base_vocab, values)  # -1 = never seen
        known = bcodes >= 0
        src_bucket = np.where(known, base_bucket[np.maximum(bcodes, 0)], -1)
        untransplanted.append(codes_new[~known | (src_bucket < 0)])
        W = np.zeros(tuple(bm.coefficients.shape), np.float64)
        tgt_proj = np.asarray(bm.projection)
        k_new = tgt_proj.shape[1]
        for src in range(len(base_projs)):
            sel = np.nonzero(src_bucket == src)[0]
            if not len(sel):
                continue
            pp = base_pos[bcodes[sel]]
            old_proj = base_projs[src][pp]  # [S, K_old]
            old_w = base_coeffs[src][pp]  # [S, K_old]
            S, k_old = old_proj.shape
            # exact per-row lookup: encode (row, global id) into one
            # sorted key space and searchsorted — a TAKE of the old
            # value, never an arithmetic reconstruction (bit-identity)
            stride = np.int64(sentinel) + 1
            base_keys = (
                np.arange(S, dtype=np.int64)[:, None] * stride
                + old_proj.astype(np.int64)
            ).ravel()
            tgt_keys = (
                np.arange(S, dtype=np.int64)[:, None] * stride
                + tgt_proj[sel].astype(np.int64)
            ).ravel()
            pos = np.searchsorted(base_keys, tgt_keys)
            pos_c = np.minimum(pos, base_keys.size - 1)
            hit = (base_keys[pos_c] == tgt_keys) & (
                tgt_proj[sel].ravel() != sentinel
            )
            w_rows = np.where(hit, old_w.ravel()[pos_c], 0.0)
            W[sel] = w_rows.reshape(len(sel), k_new)
        out_buckets.append(
            dataclasses.replace(
                bm,
                coefficients=jnp.asarray(W, bm.coefficients.dtype),
            )
        )
    return (
        dataclasses.replace(fresh, buckets=tuple(out_buckets)),
        (
            np.concatenate(untransplanted)
            if untransplanted
            else np.zeros(0, np.int64)
        ).astype(np.int64),
    )


def transplant_factored_random_effect(base, coord) -> tuple[object, np.ndarray]:
    """Re-home a base :class:`FactoredRandomEffectModel`'s latent rows
    into the combined run's flat latent table.

    Factored per-entity state is one K-vector with no per-feature
    geometry, so re-homing is a pure row move by entity VALUE —
    bit-identical for every entity the base trained. The base's shared
    projection matrix A is carried verbatim (the latent rows are only
    meaningful against the A they trained under; by construction A is
    also seed-deterministic, so base and fresh agree anyway). Returns
    ``(model, untransplanted_codes)`` like
    :func:`transplant_random_effect` — active combined-vocab codes with
    no base latent row must re-solve whatever the delta says."""
    from photon_ml_tpu.incremental.warmstart import WarmStartError

    fresh = coord.initialize_model()
    base_latent = np.asarray(base.latent)
    if base_latent.shape[1] != int(fresh.latent.shape[1]):
        raise WarmStartError(
            f"factored coordinate '{coord.name}': warm-start latent "
            f"dimension {base_latent.shape[1]} != configured "
            f"{int(fresh.latent.shape[1])} — the latent space must stay "
            "pinned across incremental retrains"
        )
    base_mat = np.asarray(base.projection.matrix)
    fresh_mat = np.asarray(fresh.projection.matrix)
    if base_mat.shape != fresh_mat.shape:
        raise WarmStartError(
            f"factored coordinate '{coord.name}': warm-start projection "
            f"is {base_mat.shape} but the combined data needs "
            f"{fresh_mat.shape} — the feature space must stay pinned "
            "across incremental retrains"
        )
    new_vocab = np.asarray(fresh.vocab)
    bcodes = map_vocab_codes(np.asarray(base.vocab), new_vocab)
    base_flat = np.asarray(base.entity_flat)
    new_flat = np.asarray(fresh.entity_flat)
    active = np.nonzero(new_flat >= 0)[0]
    src = np.where(
        bcodes[active] >= 0, base_flat[np.maximum(bcodes[active], 0)], -1
    )
    known = src >= 0
    L = np.zeros(
        (int(fresh.latent.shape[0]), base_latent.shape[1]), np.float64
    )
    L[new_flat[active[known]]] = base_latent[src[known]]
    return (
        dataclasses.replace(
            fresh,
            latent=jnp.asarray(L, fresh.latent.dtype),
            projection=base.projection,
        ),
        active[~known].astype(np.int64),
    )


# ---------------------------------------------------------------------------
# the masked coordinate
# ---------------------------------------------------------------------------


class MaskedRandomEffectCoordinate:
    """A :class:`RandomEffectCoordinate` whose ``update_model`` re-solves
    ONLY the touched entities' lanes.

    Implements the ``Coordinate`` protocol, so ``run_coordinate_descent``
    drives it unchanged (guard damping included: ``extra_l2`` /
    ``health_check`` behave exactly like the inner coordinate's). Scoring
    delegates to the inner coordinate — the full model still scores every
    row, so FE residuals see the whole table.
    """

    def __init__(self, inner, touched_mask: np.ndarray):
        self.inner = inner
        self.name = inner.name
        self.data = inner.data  # progress telemetry reads .data.num_rows
        red = inner.re_data
        mask = np.asarray(touched_mask, bool)
        if len(mask) != red.num_entities:
            raise ValueError(
                f"touched mask covers {len(mask)} entities but coordinate "
                f"'{inner.name}' has {red.num_entities}"
            )
        codes = np.nonzero(mask)[0]
        self._positions: list[np.ndarray] = []
        for i in range(len(red.buckets)):
            sel = codes[red.entity_bucket[codes] == i]
            self._positions.append(
                np.sort(red.entity_pos[sel]).astype(np.int64)
            )
        # per-fit guard hooks (the _guarded_update contract)
        self.extra_l2 = 0.0
        self.health_check = False
        self.last_health = None
        self.last_tracker = None
        # structural-speedup evidence, also mirrored into telemetry
        self.lanes_solved = 0
        self.lanes_skipped = 0
        self.bucket_solves = 0
        self.buckets_skipped = 0
        # per-bucket solve inputs from the LAST update_model pass, kept
        # so bootstrap_touched() can re-solve the exact same gathered
        # problems under resampled weights (references, not copies)
        self._last_inputs: list[dict] = []

    def initialize_model(self):
        return self.inner.initialize_model()

    def score(self, model):
        return self.inner.score(model)

    def update_model(self, model, residual_scores):
        from photon_ml_tpu.game.coordinates import (
            place_entity_solve,
            record_entity_solve_comms,
        )
        from photon_ml_tpu.optim.trackers import (
            RandomEffectOptimizationTracker,
        )
        from photon_ml_tpu.parallel import sharding as psharding

        inner = self.inner
        obj = damped_objective(inner._obj, self.extra_l2)
        n_dev = (
            0 if inner.mesh is None
            else psharding.axis_size(inner.mesh, inner._axis)
        )
        new_buckets = []
        tracker_its, tracker_reasons, tracker_vals = [], [], []
        healths = []
        self._last_inputs = []
        for i, (b, bm) in enumerate(zip(inner._buckets, model.buckets)):
            ti = self._positions[i]
            n_real = int(bm.coefficients.shape[0])
            if not len(ti):
                # zero touched entities: no solve dispatched at all —
                # the bucket's rows stand bit-identical
                self.buckets_skipped += 1
                self.lanes_skipped += n_real
                telemetry.counter("incremental.buckets_skipped").inc()
                telemetry.counter("incremental.lanes_skipped").inc(n_real)
                new_buckets.append(bm)
                continue
            T = len(ti)
            total = _next_pow2(T)
            if n_dev:
                total = -(-total // n_dev) * n_dev
            # pad by REPEATING the last touched lane: the duplicate is a
            # real already-warm problem (converges like its twin) and the
            # scatter below only writes the first T lanes
            idx = np.concatenate(
                [ti, np.full(total - T, ti[-1], np.int64)]
            )
            idx_dev = jnp.asarray(idx, jnp.int32)

            def take(x):
                return jnp.take(x, idx_dev, axis=0)

            bucket = (
                b if residual_scores is None
                else b.with_extra_offsets(residual_scores)
            )
            dense = inner._dense_x[i] is not None
            if dense:
                bb = (
                    take(inner._dense_x[i]),
                    take(bucket.labels),
                    take(bucket.offsets),
                    take(bucket.weights),
                )
            else:
                bb = jax.tree.map(take, bucket.entity_batch())
            w0 = take(bm.coefficients)
            cons = inner._bucket_constraints[i]
            if cons is not None:
                cons = jax.tree.map(take, cons)
            solver = inner._dense_solver if dense else inner._solver
            if inner.mesh is not None:
                bb, w0, cons = place_entity_solve(
                    inner.mesh, inner._axis, bb, w0, cons
                )
                record_entity_solve_comms(
                    "re_solve", inner.mesh, inner._axis,
                    inner.config.max_iterations,
                )
            res, var = solver(obj, bb, w0, inner._l1, cons)
            w = res.w[:T]
            # scatter ONLY the touched rows; untouched rows are copied
            # bit-identical by the functional .at[].set
            ti_dev = jnp.asarray(ti, jnp.int32)
            coeffs = bm.coefficients.at[ti_dev].set(
                w.astype(bm.coefficients.dtype)
            )
            variances = bm.variances
            if var is not None:
                base_var = (
                    bm.variances
                    if bm.variances is not None
                    else jnp.zeros_like(bm.coefficients)
                )
                variances = base_var.at[ti_dev].set(
                    var[:T].astype(base_var.dtype)
                )
            tracker_its.append(res.iterations[:T])
            tracker_reasons.append(res.reason[:T])
            tracker_vals.append(res.value[:T])
            if self.health_check:
                healths.append(solve_health(res, res.w))
            self.bucket_solves += 1
            self.lanes_solved += T
            self.lanes_skipped += n_real - T
            telemetry.counter("incremental.bucket_solves").inc()
            telemetry.counter("incremental.lanes_solved").inc(T)
            telemetry.counter("incremental.lanes_skipped").inc(n_real - T)
            # bootstrap_touched re-solves these gathered problems later;
            # dense-path buckets carry stripped (1, 1) COO stubs, so it
            # rebuilds the COO view from _dense_x. Only sharded solves
            # (mesh) are out of scope
            if inner.mesh is None:
                self._last_inputs.append(
                    {
                        "bucket": i,
                        "bucket_obj": bucket,
                        "idx": idx,
                        "ti": ti,
                        "w0": res.w,
                    }
                )
            new_buckets.append(
                dataclasses.replace(
                    bm, coefficients=coeffs, variances=variances
                )
            )
        self.last_health = (
            (jnp.all(jnp.stack(healths)) if healths else jnp.bool_(True))
            if self.health_check
            else None
        )
        self.last_tracker = (
            RandomEffectOptimizationTracker.from_device_parts(
                tracker_its, tracker_reasons, tracker_vals
            )
            if tracker_its
            else None
        )
        return dataclasses.replace(model, buckets=tuple(new_buckets))

    def bootstrap_touched(self, num_samples: int = 32, seed: int = 0):
        """Masked-lane bootstrap: CI exactly the RE rows the last
        ``update_model`` pass touched, reusing its gather machinery —
        B x touched lanes solve in ONE executable per bucket.

        The [B, E, R] resample weights are drawn for the FULL bucket
        from the shared seed and then gathered down to the touched
        lanes, so each touched lane sees byte-identical draws to a
        full-lane ``bootstrap_random_effect`` run over the same bucket
        — which is why masked and full CIs agree exactly on touched
        rows. Returns ``{bucket_index: {"report": ReBootstrapReport,
        "touched": positions}}``."""
        from photon_ml_tpu.diagnostics.bootstrap import (
            bootstrap_random_effect,
            bootstrap_re_weights,
        )

        inner = self.inner
        out: dict[int, dict] = {}
        for stash in self._last_inputs:
            bucket = stash["bucket_obj"]
            idx = stash["idx"]
            full_w = np.asarray(
                telemetry.sync_fetch(
                    bucket.weights, label="bootstrap_touched_weights"
                )
            )
            counts = bootstrap_re_weights(num_samples, full_w, seed)
            idx_dev = jnp.asarray(idx, jnp.int32)

            def take(x):
                return jnp.take(x, idx_dev, axis=0)

            dense_x = inner._dense_x[stash["bucket"]]
            if dense_x is not None:
                # the bucket solved on its packed dense design and its COO
                # arrays may be stripped (1, 1) stubs — rebuild an explicit
                # dense-as-COO view [P, R*K] from the design instead
                from photon_ml_tpu.ops.sparse import SparseBatch

                R = bucket.labels.shape[1]
                K = int(bucket.num_local_features)
                x = take(dense_x)
                rows = jnp.broadcast_to(
                    jnp.repeat(jnp.arange(R, dtype=jnp.int32), K),
                    x.shape,
                )
                cols = jnp.broadcast_to(
                    jnp.tile(jnp.arange(K, dtype=jnp.int32), R),
                    x.shape,
                )
                eb = SparseBatch(
                    values=x,
                    rows=rows,
                    cols=cols,
                    labels=take(bucket.labels),
                    offsets=take(bucket.offsets),
                    weights=take(bucket.weights),
                    num_features=K,
                )
            else:
                eb = jax.tree.map(take, bucket.entity_batch())
            report = bootstrap_random_effect(
                eb,
                inner.loss_name,
                inner.config,
                stash["w0"],
                num_samples=num_samples,
                seed=seed,
                lane_weights=counts[:, idx, :],
            )
            out[stash["bucket"]] = {
                "report": report,
                "touched": stash["ti"],
            }
        return out


class MaskedFactoredRandomEffectCoordinate:
    """A :class:`FactoredRandomEffectCoordinate` whose ``update_model``
    re-solves ONLY the touched entities' latent vectors.

    The shared projection matrix A is FROZEN regardless of the inner
    coordinate's ``refit_projection``: a matrix refit rewrites every
    entity's effective coefficients ``A^T c_e``, which would defeat the
    untouched-lanes-bit-identical guarantee the masked path exists for.
    Touched entities re-solve in the fixed projected space — exactly the
    ``refit_projection=False`` per-entity step, gathered down to the
    touched lanes (same pad-to-pow2 / scatter-back protocol as
    :class:`MaskedRandomEffectCoordinate`). A base whose A has drifted
    stale escalates to a full retrain — the conductor's escalation path.
    """

    def __init__(self, inner, touched_mask: np.ndarray):
        self.inner = inner
        self.name = inner.name
        self.data = inner.data
        red = inner.re_data
        mask = np.asarray(touched_mask, bool)
        if len(mask) != red.num_entities:
            raise ValueError(
                f"touched mask covers {len(mask)} entities but coordinate "
                f"'{inner.name}' has {red.num_entities}"
            )
        if inner.refit_projection:
            logger.warning(
                "masked incremental solve freezes coordinate '%s's shared "
                "projection matrix (refit_projection is configured on); "
                "escalate to a full retrain to refresh it", inner.name,
            )
        codes = np.nonzero(mask)[0]
        self._positions: list[np.ndarray] = []
        for i in range(len(red.buckets)):
            sel = codes[red.entity_bucket[codes] == i]
            self._positions.append(
                np.sort(red.entity_pos[sel]).astype(np.int64)
            )
        self.extra_l2 = 0.0
        self.health_check = False
        self.last_health = None
        self.last_tracker = None
        self.lanes_solved = 0
        self.lanes_skipped = 0
        self.bucket_solves = 0
        self.buckets_skipped = 0

    def initialize_model(self):
        return self.inner.initialize_model()

    def score(self, model):
        return self.inner.score(model)

    def update_model(self, model, residual_scores):
        from photon_ml_tpu.game.coordinates import (
            place_entity_solve,
            record_entity_solve_comms,
        )
        from photon_ml_tpu.game.factored import _latent_design_T_fn
        from photon_ml_tpu.ops.sparse import SparseBatch
        from photon_ml_tpu.optim.trackers import (
            FactoredRandomEffectOptimizationTracker,
            RandomEffectOptimizationTracker,
        )
        from photon_ml_tpu.parallel import sharding as psharding

        inner = self.inner
        obj = damped_objective(inner._re_obj, self.extra_l2)
        a_ext = model.projection.extended()
        k = inner._proj_rows
        n_dev = (
            0 if inner.mesh is None
            else psharding.axis_size(inner.mesh, inner._axis)
        )
        latent = model.latent
        tracker_its, tracker_reasons, tracker_vals = [], [], []
        healths = []
        for b_idx, b in enumerate(inner.re_data.device_buckets()):
            ti = self._positions[b_idx]
            n_real = int(b.num_entities)
            if not len(ti):
                # zero touched entities: no solve dispatched at all —
                # the bucket's latent rows stand bit-identical
                self.buckets_skipped += 1
                self.lanes_skipped += n_real
                telemetry.counter("incremental.buckets_skipped").inc()
                telemetry.counter("incremental.lanes_skipped").inc(n_real)
                continue
            T = len(ti)
            total = _next_pow2(T)
            if n_dev:
                total = -(-total // n_dev) * n_dev
            # pad by REPEATING the last touched lane (idempotent; scatter
            # below only writes the first T lanes)
            idx = np.concatenate(
                [ti, np.full(total - T, ti[-1], np.int64)]
            )
            idx_dev = jnp.asarray(idx, jnp.int32)

            def take(x):
                return jnp.take(x, idx_dev, axis=0)

            bucket = (
                b if residual_scores is None
                else b.with_extra_offsets(residual_scores)
            )
            R = b.rows_per_entity
            # gather the touched entities' raw arrays FIRST, then build
            # the transposed latent design only over them — the design
            # cost scales with touched lanes, not bucket size
            X = _latent_design_T_fn(R)(
                take(b.values), take(b.rows), take(b.cols),
                take(b.projection), a_ext,
            ).transpose(0, 2, 1)  # [total, R, K]
            dense = SparseBatch(
                values=X.reshape(total, R * k),
                rows=jnp.broadcast_to(
                    jnp.repeat(jnp.arange(R, dtype=jnp.int32), k),
                    (total, R * k),
                ),
                cols=jnp.broadcast_to(
                    jnp.tile(jnp.arange(k, dtype=jnp.int32), R),
                    (total, R * k),
                ),
                labels=take(bucket.labels),
                offsets=take(bucket.offsets),
                weights=take(bucket.weights),
                num_features=k,
            )
            flat = inner._flat_offsets[b_idx] + idx
            w0 = jnp.take(latent, jnp.asarray(flat, jnp.int32), axis=0)
            if inner.mesh is not None:
                dense, w0, _ = place_entity_solve(
                    inner.mesh, inner._axis, dense, w0
                )
                record_entity_solve_comms(
                    "latent_re_solve", inner.mesh, inner._axis,
                    inner.re_config.max_iterations,
                )
            res, _ = inner._re_solver(obj, dense, w0, inner._re_l1, None)
            w = res.w[:T]
            flat_t = jnp.asarray(
                inner._flat_offsets[b_idx] + ti, jnp.int32
            )
            latent = latent.at[flat_t].set(w.astype(latent.dtype))
            tracker_its.append(res.iterations[:T])
            tracker_reasons.append(res.reason[:T])
            tracker_vals.append(res.value[:T])
            if self.health_check:
                healths.append(solve_health(res, res.w))
            self.bucket_solves += 1
            self.lanes_solved += T
            self.lanes_skipped += n_real - T
            telemetry.counter("incremental.bucket_solves").inc()
            telemetry.counter("incremental.lanes_solved").inc(T)
            telemetry.counter("incremental.lanes_skipped").inc(n_real - T)
        self.last_health = (
            (jnp.all(jnp.stack(healths)) if healths else jnp.bool_(True))
            if self.health_check
            else None
        )
        self.last_tracker = (
            FactoredRandomEffectOptimizationTracker(
                steps=(
                    (
                        RandomEffectOptimizationTracker.from_device_parts(
                            tracker_its, tracker_reasons, tracker_vals
                        ),
                        None,
                    ),
                )
            )
            if tracker_its
            else None
        )
        return dataclasses.replace(model, latent=latent)


# ---------------------------------------------------------------------------
# the incremental fit driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class IncrementalFitResult:
    """A finished incremental refresh: the fresh model plus the evidence
    trail (what re-solved, what stood, where it came from)."""

    model: GameModel
    best_model: GameModel
    best_metric: Optional[float]
    history: list
    lineage: "BaseLineage"
    delta: Optional["DeltaScan"]
    lanes_solved: int
    lanes_skipped: int
    bucket_solves: int
    buckets_skipped: int
    new_entities: int
    seconds: float
    selection: Optional[object] = None  # SweepSelection when λ-swept
    published_version: Optional[str] = None
    # JSON-safe masked-lane bootstrap summaries per coordinate (only when
    # run with bootstrap_samples > 0) — the error bars the publish gate
    # attaches to the version's quality block
    bootstrap: Optional[dict] = None


def local_lambda_factors(points: int = 3, span: float = 4.0) -> list[float]:
    """A small DESCENDING multiplier grid around the incumbent λ (the
    sweep convention: index 0 = most regularized). ``points=3, span=4``
    → ``[4.0, 1.0, 0.25]``; the incumbent itself is always a lane."""
    if points < 1:
        raise ValueError("lambda points must be >= 1")
    if span <= 1.0:
        raise ValueError("lambda span must be > 1")
    if points == 1:
        return [1.0]
    factors = np.logspace(
        np.log10(span), -np.log10(span), points
    ).tolist()
    # the incumbent must be an exact lane, not a float-noise neighbor
    mid = min(range(points), key=lambda i: abs(np.log(factors[i])))
    factors[mid] = 1.0
    return factors


def _scaled_overrides(config, factor: float) -> dict:
    """Per-coordinate OptimizerConfig overrides with every coordinate's
    regularization weight scaled by ``factor`` (the local λ sweep)."""
    from photon_ml_tpu.game.estimator import (
        FactoredRandomEffectConfig,
        FixedEffectConfig,
        RandomEffectConfig,
    )

    overrides = {}
    for name, c in config.coordinates.items():
        if isinstance(c, (FixedEffectConfig, RandomEffectConfig)):
            opt = c.optimizer
        elif isinstance(c, FactoredRandomEffectConfig):
            opt = c.re_optimizer
        else:  # pragma: no cover - config types are closed
            continue
        overrides[name] = dataclasses.replace(
            opt, regularization_weight=opt.regularization_weight * factor
        )
    return overrides


def _wrap_masked(coords: dict, delta, data, untransplanted: dict) -> dict:
    """Wrap every RE coordinate whose id column the delta names.

    The touched mask is the delta's touched set UNIONED with the
    coordinate's untransplanted entities (combined-vocab codes the base
    had no row for): an entity that entered through a shifted base
    window rather than the delta shards still has only a zero-init row —
    skipping its lane would publish an all-zero random effect."""
    from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate

    if delta is None:
        return dict(coords)
    out = {}
    for name, coord in coords.items():
        if isinstance(coord, RandomEffectCoordinate):
            cd = delta.for_id(coord.re_data.id_name)
            masked_cls = MaskedRandomEffectCoordinate
        elif isinstance(coord, FactoredRandomEffectCoordinate):
            cd = delta.for_id(coord.re_data.id_name)
            masked_cls = MaskedFactoredRandomEffectCoordinate
        else:
            cd = None
        if cd is None:
            out[name] = coord
            continue
        vocab = data.id_columns[coord.re_data.id_name].vocab
        mask = cd.touched_mask(vocab)
        missing = untransplanted.get(name)
        if missing is not None and len(missing):
            mask[missing] = True
        out[name] = masked_cls(coord, mask)
    return out


def _transplant_models(
    coords: dict, base_model: GameModel
) -> tuple[dict, int, dict]:
    """``(initial_models, new_entities, untransplanted)`` for the
    combined-geometry coordinates, re-homed from the base model.
    ``untransplanted`` maps coordinate name -> combined-vocab codes with
    no base row (zero-init lanes that must not be mask-skipped).
    Coordinates the base lacks (or whose type the transplant does not
    support) start fresh with a warning."""
    from photon_ml_tpu.game.coordinates import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )
    from photon_ml_tpu.game.factored import (
        FactoredRandomEffectCoordinate,
        FactoredRandomEffectModel,
    )
    from photon_ml_tpu.incremental.warmstart import WarmStartError

    initial = {}
    new_entities = 0
    untransplanted: dict = {}
    for name, coord in coords.items():
        base = base_model.models.get(name)
        if base is None:
            logger.warning(
                "warm start lacks coordinate '%s'; it initializes fresh",
                name,
            )
            continue
        if isinstance(coord, FixedEffectCoordinate):
            initial[name] = transplant_fixed_effect(base, coord)
        elif isinstance(coord, RandomEffectCoordinate):
            model, missing = transplant_random_effect(base, coord)
            initial[name] = model
            new_entities += int(len(missing))
            untransplanted[name] = missing
        elif isinstance(coord, FactoredRandomEffectCoordinate):
            if not isinstance(base, FactoredRandomEffectModel):
                raise WarmStartError(
                    f"coordinate '{name}' is factored in this config but "
                    f"the warm start holds a {type(base).__name__} — the "
                    "coordinate structure must stay pinned across "
                    "incremental retrains"
                )
            model, missing = transplant_factored_random_effect(base, coord)
            initial[name] = model
            new_entities += int(len(missing))
            untransplanted[name] = missing
        else:
            logger.warning(
                "coordinate '%s' (%s) does not support warm-start "
                "transplanting; it initializes fresh",
                name, type(coord).__name__,
            )
    return initial, new_entities, untransplanted


def _primary_metric_value(model, validation_data, metric: str) -> float:
    """One validation metric for a full model — the λ-sweep scorer
    (EVALUATORS parity with sweep.select.evaluate_sweep)."""
    from photon_ml_tpu.evaluation.evaluators import EVALUATORS
    from photon_ml_tpu.game.coordinate_descent import (
        padded_validation_arrays,
    )

    scores = model.score(validation_data)
    labels, weights, offsets = padded_validation_arrays(
        validation_data, int(scores.shape[0])
    )
    return float(
        telemetry.sync_fetch(
            EVALUATORS[metric](scores + offsets, labels, weights),
            label=f"incremental_eval:{metric}",
        )
    )


def run_incremental_fit(
    estimator,
    data,
    warm_start,
    delta=None,
    validation_data=None,
    mesh=None,
    num_iterations: Optional[int] = None,
    lambda_factors: Optional[Sequence[float]] = None,
    metric: Optional[str] = None,
    policy: str = "best",
    rel_tol: float = 0.01,
    guard=None,
    checkpoint_spec=None,
    should_stop=None,
    bootstrap_samples: int = 0,
    bootstrap_seed: int = 0,
) -> IncrementalFitResult:
    """Delta-aware warm-start refresh of ``estimator``'s model over the
    COMBINED data (base ∪ delta). See ``GameEstimator.fit_incremental``
    for the public contract."""
    from photon_ml_tpu.game.checkpoint import CheckpointManager
    from photon_ml_tpu.game.coordinate_descent import (
        ValidationSpec,
        run_coordinate_descent,
    )
    from photon_ml_tpu.incremental.warmstart import WarmStartError
    from photon_ml_tpu.utils.timing import Timer

    if warm_start.model is None:
        raise WarmStartError(
            "fit_incremental needs a warm start carrying a full GAME "
            f"model (kind '{warm_start.lineage.kind}' restored a bare "
            "coefficient table; streamed tables warm-start "
            "StreamingRandomEffectTrainer via "
            "ShardedCoefficientTable.from_coefficients instead)"
        )
    if checkpoint_spec is not None and os.path.realpath(
        checkpoint_spec.directory
    ) == os.path.realpath(warm_start.lineage.checkpoint_dir):
        raise WarmStartError(
            "the incremental fit's checkpoint directory must not be its "
            "own warm-start base — a crash mid-refresh would corrupt "
            "the base checkpoint it restarts from"
        )
    config = estimator.config
    validation = None
    if validation_data is not None:
        if not config.evaluators:
            raise ValueError("validation data provided but no evaluators")
        validation = ValidationSpec(
            data=validation_data, evaluators=list(config.evaluators)
        )
    iters = num_iterations or config.num_iterations
    t = Timer().start()
    lineage = warm_start.lineage
    attrs = {
        "base": lineage.checkpoint_dir,
        "kind": lineage.kind,
    }
    if lineage.digest:
        attrs["base_digest"] = lineage.digest
    if lineage.step is not None:
        attrs["base_step"] = int(lineage.step)
    if delta is not None:
        attrs["delta_digest"] = delta.digest
        attrs["delta_rows"] = int(delta.delta_rows)
        attrs["touched_fraction"] = round(
            max(
                (c.touched_fraction for c in delta.coordinates.values()),
                default=0.0,
            ),
            6,
        )
    with telemetry.span("incremental_fit", **attrs):
        factors = list(lambda_factors) if lambda_factors else [1.0]
        if len(factors) > 1 and validation is None:
            raise ValueError(
                "a local λ sweep needs validation data to select on"
            )
        lane_results = []
        lane_wrapped: list[dict] = []
        initial = None
        new_entities = 0
        untransplanted: dict = {}
        for li, factor in enumerate(factors):
            overrides = (
                None if factor == 1.0 else _scaled_overrides(config, factor)
            )
            coords = estimator._build_coordinates(
                data, mesh, opt_overrides=overrides
            )
            if initial is None:
                initial, new_entities, untransplanted = _transplant_models(
                    coords, warm_start.model
                )
            wrapped = _wrap_masked(coords, delta, data, untransplanted)
            # path warm start: each lane starts from its more-regularized
            # neighbor's refreshed models (lane 0 from the transplant)
            result = run_coordinate_descent(
                wrapped,
                task=config.task,
                num_iterations=iters,
                validation=validation,
                initial_models=initial,
                guard=guard,
                checkpoint=(
                    None if checkpoint_spec is None or li > 0
                    else CheckpointManager(checkpoint_spec)
                ),
                should_stop=should_stop,
            )
            lane_results.append(result)
            lane_wrapped.append(wrapped)
            initial = dict(result.model.models)

        selection = None
        pick = 0
        if len(factors) > 1:
            from photon_ml_tpu.sweep.select import (
                SweepSelection,
                default_metric,
                select_best,
            )

            metric_name = metric or default_metric(config.task)
            values = np.asarray(
                [
                    _primary_metric_value(
                        r.model, validation.data, metric_name
                    )
                    for r in lane_results
                ],
                np.float64,
            )
            pick = select_best(
                values, metric_name, policy=policy, rel_tol=rel_tol
            )
            selection = SweepSelection(
                index=pick, metric=metric_name, metrics=values,
                policy=policy,
            )
            telemetry.gauge("sweep.selected_metric").set(
                float(values[pick])
            )
        result = lane_results[pick]
        bootstrap = None
        if bootstrap_samples > 0:
            # masked-lane bootstrap on the SELECTED lane: CI exactly the
            # touched rows, B resamples per bucket in one executable
            with telemetry.span(
                "incremental_bootstrap", samples=bootstrap_samples
            ):
                per_coord = {}
                for name, coord in lane_wrapped[pick].items():
                    if not hasattr(coord, "bootstrap_touched"):
                        continue
                    buckets = coord.bootstrap_touched(
                        num_samples=bootstrap_samples, seed=bootstrap_seed
                    )
                    if not buckets:
                        continue
                    agg = {}
                    for bi, entry in buckets.items():
                        summ = entry["report"].summary()
                        summ["touched_lanes"] = int(len(entry["touched"]))
                        agg[str(bi)] = summ
                    per_coord[name] = agg
                if per_coord:
                    bootstrap = {
                        "num_samples": int(bootstrap_samples),
                        "coordinates": per_coord,
                    }
                    telemetry.counter("quality.bootstrap_fits").inc()
        lanes_solved = sum(
            getattr(c, "lanes_solved", 0)
            for w in lane_wrapped for c in w.values()
        )
        lanes_skipped = sum(
            getattr(c, "lanes_skipped", 0)
            for w in lane_wrapped for c in w.values()
        )
        bucket_solves = sum(
            getattr(c, "bucket_solves", 0)
            for w in lane_wrapped for c in w.values()
        )
        buckets_skipped = sum(
            getattr(c, "buckets_skipped", 0)
            for w in lane_wrapped for c in w.values()
        )
    seconds = t.stop()
    telemetry.gauge("incremental.time_to_fresh_s").set(seconds)
    telemetry.counter("incremental.fits").inc()
    return IncrementalFitResult(
        model=result.model,
        best_model=result.best_model or result.model,
        best_metric=result.best_metric,
        history=result.history,
        lineage=lineage,
        delta=delta,
        lanes_solved=lanes_solved,
        lanes_skipped=lanes_skipped,
        bucket_solves=bucket_solves,
        buckets_skipped=buckets_skipped,
        new_entities=new_entities,
        seconds=seconds,
        selection=selection,
        bootstrap=bootstrap,
    )
