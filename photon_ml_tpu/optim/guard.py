"""Guarded solves: divergence detection, damped retries, rollback.

The Spark reference inherits per-task fault tolerance from RDD lineage; a
solve that NaNs there just fails its task and recomputes. On the TPU port a
single NaN-producing entity or an ill-conditioned residual poisons a
device array and — unguarded — the whole multi-hour GAME fit. The guard
layer restores graceful degradation:

  - after each coordinate (or streaming chunk) solve, a device-side health
    reduce checks the new coefficients and final loss for non-finite values
    and for loss regression (line searches are monotone, so a final value
    above the initial one marks a diverged solve);
  - on divergence the pre-solve model is kept and the solve retried with
    escalating extra L2 damping (the l2 weight is a traced leaf of the
    objective, so retries reuse the compiled program);
  - if every retry diverges, the previous model is rolled back and training
    continues — one bad coordinate degrades, it no longer kills the fit.

Telemetry: ``solves.diverged`` (health checks that failed),
``solves.retried`` (damped re-runs), ``solves.rolled_back`` (solves whose
result was discarded), ``solves.frozen`` (coordinates dropped from the
updating sequence after repeated rollbacks).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu import faults

Array = jax.Array

#: Injection seam for the coordinate-descent guard: the HOST-side health
#: verdict of a solve (a ``nan`` rule flips it to diverged, driving the
#: damped-retry/rollback/freeze machinery deterministically). Applied by
#: the host training loops (coordinate_descent._guarded_update) — never
#: inside a traced function, where a trace-time plan lookup would bake
#: one decision into the compiled program.
FP_SOLVE_HEALTH = faults.register_point(
    "guard.solve_health",
    description="host-side solve health verdict (nan action => diverged)",
)

# Relative slack for the loss-regression check: warm-started re-solves may
# end epsilon above f_0 from padding/reduction-order noise; only a real
# regression (or a non-finite value) should trip the guard.
_REGRESSION_RTOL = 1e-3
_REGRESSION_ATOL = 1e-6


@dataclasses.dataclass(frozen=True)
class GuardSpec:
    """Divergence-recovery policy for guarded solves.

    ``max_retries`` damped re-runs follow a diverged solve; retry ``k``
    (1-based) adds ``initial_damping * damping_factor**(k-1)`` extra L2.
    After ``freeze_after`` CONSECUTIVE rollbacks a coordinate is frozen —
    dropped from the updating sequence for the rest of the fit (its last
    good model keeps scoring).
    """

    max_retries: int = 2
    initial_damping: float = 1.0
    damping_factor: float = 10.0
    freeze_after: int = 2

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.initial_damping <= 0 or self.damping_factor < 1.0:
            raise ValueError(
                "damping must be positive and escalate (factor >= 1)"
            )
        if self.freeze_after < 1:
            raise ValueError("freeze_after must be >= 1")

    def damping_for(self, attempt: int) -> float:
        """Extra L2 weight for ``attempt`` (0 = the original solve)."""
        if attempt <= 0:
            return 0.0
        return self.initial_damping * self.damping_factor ** (attempt - 1)


def damped_objective(obj, extra_l2: float):
    """``obj`` with ``extra_l2`` added to its (traced) l2 leaf — the damped
    retry uses the same compiled program. The one place damping composes,
    shared by the coordinate, streaming, and mesh solve paths."""
    if not extra_l2:
        return obj
    return dataclasses.replace(
        obj, l2_weight=obj.l2_weight + jnp.float32(extra_l2)
    )


def solve_health(res, w: Array) -> Array:
    """Device boolean scalar: ``res`` (a SolveResult, possibly with a
    leading entity axis) produced finite coefficients ``w`` and a finite
    final loss no worse than its initial value ``res.values[..., 0]``.

    Stays on device — callers fetch it once via telemetry.sync_fetch so a
    guarded solve costs exactly one accounted scalar round trip.
    """
    finite_w = jnp.all(jnp.isfinite(w))
    v = res.value
    v0 = jnp.take(res.values, 0, axis=-1)
    budget = _REGRESSION_RTOL * jnp.abs(v0) + _REGRESSION_ATOL
    ok_v = jnp.all(jnp.isfinite(v) & (v <= v0 + budget))
    return jnp.logical_and(finite_w, ok_v)


def _coefficient_arrays(model) -> list:
    """Coefficient-like leaves of a (sub)model, duck-typed across the model
    zoo (FixedEffect / RandomEffect buckets / factored latent tables)."""
    out = []
    if hasattr(model, "coefficients"):
        out.append(model.coefficients)
    for bm in getattr(model, "buckets", ()):
        out.append(bm.coefficients)
    if hasattr(model, "latent"):
        out.append(model.latent)
    return out


def model_is_finite(model) -> Array:
    """Device boolean scalar: every coefficient array of ``model`` is
    finite. The fallback health check for coordinates that don't expose a
    per-solve ``last_health``."""
    arrays = _coefficient_arrays(model)
    if not arrays:
        return jnp.bool_(True)
    return jnp.all(
        jnp.stack([jnp.all(jnp.isfinite(a)) for a in arrays])
    )
