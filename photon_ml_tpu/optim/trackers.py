"""Optimization trackers: aggregate solve telemetry per coordinate update.

Reference analog: photon-api optimization/*Tracker.scala —
FixedEffectOptimizationTracker wraps one OptimizationStatesTracker;
RandomEffectOptimizationTracker aggregates per-entity trackers into
convergence-reason counts (countConvergenceReasons) and iteration
StatCounter stats (getNumIterationStats). Here the vmapped bucket solves
already return per-entity iteration/reason ARRAYS, so aggregation is a few
bincounts — no RDD reduce.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_ml_tpu.optim.common import CONVERGENCE_REASON_NAMES


@dataclasses.dataclass(frozen=True)
class FixedEffectOptimizationTracker:
    """One solve's terminal telemetry (FixedEffectOptimizationTracker)."""

    iterations: int
    reason: str
    final_value: float
    final_grad_norm: float

    @staticmethod
    def from_result(res) -> "FixedEffectOptimizationTracker":
        it = int(res.iterations)
        return FixedEffectOptimizationTracker(
            iterations=it,
            reason=CONVERGENCE_REASON_NAMES.get(int(res.reason), "Unknown"),
            final_value=float(res.value),
            final_grad_norm=float(res.grad_norms[it]),
        )

    def to_summary_string(self) -> str:
        return (
            f"iterations={self.iterations} reason={self.reason} "
            f"value={self.final_value:.6g} |grad|={self.final_grad_norm:.3g}"
        )


@dataclasses.dataclass(frozen=True)
class RandomEffectOptimizationTracker:
    """Per-entity solve telemetry for one coordinate update, aggregated
    across geometry buckets (RandomEffectOptimizationTracker analog)."""

    iterations: np.ndarray  # i32[n_entities]
    reasons: np.ndarray  # i32[n_entities]

    @staticmethod
    def from_results(results, entity_counts) -> "RandomEffectOptimizationTracker":
        """Concatenate per-bucket vmapped SolveResults, dropping padded
        entities (``entity_counts[i]`` = real entities of bucket i)."""
        its, rs = [], []
        for res, n in zip(results, entity_counts):
            its.append(np.asarray(res.iterations)[:n])
            rs.append(np.asarray(res.reason)[:n])
        return RandomEffectOptimizationTracker(
            iterations=(
                np.concatenate(its) if its else np.zeros(0, np.int32)
            ),
            reasons=np.concatenate(rs) if rs else np.zeros(0, np.int32),
        )

    def count_convergence_reasons(self) -> dict[str, int]:
        """countConvergenceReasons analog: reason name -> entity count."""
        out: dict[str, int] = {}
        codes, counts = np.unique(self.reasons, return_counts=True)
        for code, count in zip(codes, counts):
            name = CONVERGENCE_REASON_NAMES.get(int(code), "Unknown")
            out[name] = out.get(name, 0) + int(count)
        return out

    def iteration_stats(self) -> dict[str, float]:
        """getNumIterationStats analog (count/mean/std/min/max)."""
        it = self.iterations
        if len(it) == 0:
            return {"count": 0, "mean": 0.0, "stdev": 0.0, "min": 0.0, "max": 0.0}
        return {
            "count": int(len(it)),
            "mean": float(it.mean()),
            "stdev": float(it.std()),
            "min": float(it.min()),
            "max": float(it.max()),
        }

    def to_summary_string(self) -> str:
        s = self.iteration_stats()
        reasons = ", ".join(
            f"{k}: {v}" for k, v in sorted(self.count_convergence_reasons().items())
        )
        return (
            f"entities={s['count']} iterations(mean={s['mean']:.2f}, "
            f"std={s['stdev']:.2f}, min={s['min']:.0f}, max={s['max']:.0f}) "
            f"convergence {{{reasons}}}"
        )
