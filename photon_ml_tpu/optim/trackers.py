"""Optimization trackers: aggregate solve telemetry per coordinate update.

Reference analog: photon-api optimization/*Tracker.scala —
FixedEffectOptimizationTracker wraps one OptimizationStatesTracker;
RandomEffectOptimizationTracker aggregates per-entity trackers into
convergence-reason counts (countConvergenceReasons) and iteration
StatCounter stats (getNumIterationStats). Here the vmapped bucket solves
already return per-entity iteration/reason ARRAYS, so aggregation is a few
bincounts — no RDD reduce.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_ml_tpu.optim.common import CONVERGENCE_REASON_NAMES
from photon_ml_tpu.telemetry import metrics as _metrics


@dataclasses.dataclass(frozen=True)
class FixedEffectOptimizationTracker:
    """One solve's terminal telemetry (FixedEffectOptimizationTracker)."""

    iterations: int
    reason: str
    final_value: float
    final_grad_norm: float

    @staticmethod
    def from_result(res) -> "FixedEffectOptimizationTracker":
        it = int(res.iterations)
        _metrics.counter("fe_solves").inc()
        _metrics.histogram("fe_solve_iterations").observe(it)
        return FixedEffectOptimizationTracker(
            iterations=it,
            reason=CONVERGENCE_REASON_NAMES.get(int(res.reason), "Unknown"),
            final_value=float(res.value),
            final_grad_norm=float(res.grad_norms[it]),
        )

    def to_summary_string(self) -> str:
        return (
            f"iterations={self.iterations} reason={self.reason} "
            f"value={self.final_value:.6g} |grad|={self.final_grad_norm:.3g}"
        )


_PERCENTILES = (5, 25, 50, 75, 95)


def _pct(a: np.ndarray) -> dict[str, float]:
    if len(a) == 0:
        return {f"p{p}": 0.0 for p in _PERCENTILES}
    qs = np.percentile(a, _PERCENTILES)
    return {f"p{p}": float(q) for p, q in zip(_PERCENTILES, qs)}


@dataclasses.dataclass(frozen=True)
class RandomEffectOptimizationTracker:
    """Per-entity solve telemetry for one coordinate update, aggregated
    across geometry buckets (RandomEffectOptimizationTracker analog).

    ``final_values`` (optional) are the per-entity terminal objective values;
    together with ``iterations`` they feed the distribution summaries the
    reference aggregates per entity (RandomEffectOptimizationTracker.scala
    getNumIterationStats / per-state StatCounters)."""

    iterations: np.ndarray  # i32[n_entities]
    reasons: np.ndarray  # i32[n_entities]
    final_values: np.ndarray | None = None  # f32[n_entities]

    @staticmethod
    def from_device_parts(
        its: list, reasons: list, vals: list
    ) -> "RandomEffectOptimizationTracker":
        """Build from per-bucket DEVICE arrays (padding already sliced off)
        with ONE packed host fetch: the f32 terminal values ride the i32
        concat via bitcast — each device->host fetch costs a ~100ms tunnel
        round trip, so all three telemetry vectors cross together (and the
        crossing is accounted by telemetry.sync_fetch)."""
        import jax
        import jax.numpy as jnp

        from photon_ml_tpu.telemetry import sync_fetch

        if not its:
            z = np.zeros(0, np.int32)
            return RandomEffectOptimizationTracker(
                iterations=z, reasons=z, final_values=np.zeros(0, np.float32)
            )
        packed = sync_fetch(
            jnp.concatenate(
                [
                    jnp.concatenate(its).astype(jnp.int32),
                    jnp.concatenate(reasons).astype(jnp.int32),
                    jax.lax.bitcast_convert_type(
                        jnp.concatenate(vals).astype(jnp.float32), jnp.int32
                    ),
                ]
            ),
            label="re_tracker",
        )
        n = len(packed) // 3
        tracker = RandomEffectOptimizationTracker(
            iterations=packed[:n],
            reasons=packed[n : 2 * n],
            final_values=packed[2 * n :].view(np.float32),
        )
        _metrics.counter("re_solved_entities").inc(n)
        # per-entity solve-iteration distribution, the registry-level view
        # of getNumIterationStats (fed once per coordinate update)
        _metrics.histogram("re_solve_iterations").observe_many(
            tracker.iterations
        )
        return tracker

    def count_convergence_reasons(self) -> dict[str, int]:
        """countConvergenceReasons analog: reason name -> entity count."""
        out: dict[str, int] = {}
        codes, counts = np.unique(self.reasons, return_counts=True)
        for code, count in zip(codes, counts):
            name = CONVERGENCE_REASON_NAMES.get(int(code), "Unknown")
            out[name] = out.get(name, 0) + int(count)
        return out

    def iteration_stats(self) -> dict[str, float]:
        """getNumIterationStats analog (count/mean/std/min/max)."""
        it = self.iterations
        if len(it) == 0:
            return {"count": 0, "mean": 0.0, "stdev": 0.0, "min": 0.0, "max": 0.0}
        return {
            "count": int(len(it)),
            "mean": float(it.mean()),
            "stdev": float(it.std()),
            "min": float(it.min()),
            "max": float(it.max()),
        }

    def percentile_summary(self) -> dict[str, dict[str, float]]:
        """Distribution summaries of per-entity iterations and terminal
        objective values (p5/p25/p50/p75/p95 — the per-entity StatCounter
        aggregation of RandomEffectOptimizationTracker.scala)."""
        out = {"iterations": _pct(self.iterations)}
        if self.final_values is not None:
            out["final_loss"] = _pct(self.final_values)
        return out

    def to_summary_string(self) -> str:
        s = self.iteration_stats()
        reasons = ", ".join(
            f"{k}: {v}" for k, v in sorted(self.count_convergence_reasons().items())
        )
        pcts = self.percentile_summary()
        it_p = pcts["iterations"]
        lines = (
            f"entities={s['count']} iterations(mean={s['mean']:.2f}, "
            f"std={s['stdev']:.2f}, min={s['min']:.0f}, max={s['max']:.0f}, "
            f"p50={it_p['p50']:.0f}, p95={it_p['p95']:.0f}) "
            f"convergence {{{reasons}}}"
        )
        if "final_loss" in pcts:
            fl = pcts["final_loss"]
            lines += (
                f" final_loss(p5={fl['p5']:.4g}, p50={fl['p50']:.4g}, "
                f"p95={fl['p95']:.4g})"
            )
        return lines


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectOptimizationTracker:
    """Per-MF-iteration telemetry for the factored coordinate: each
    alternation step pairs the latent-space RE solve's per-entity tracker
    with the latent-matrix refit's tracker (the reference keeps exactly this
    pair per iteration, FactoredRandomEffectOptimizationProblem.scala's
    Array[(RandomEffectOptimizationTracker, FixedEffectOptimizationTracker)]).
    ``matrix`` is None in fixed-projection mode (no refit happens)."""

    steps: tuple  # of (RandomEffectOptimizationTracker, FE tracker | None)

    def to_summary_string(self) -> str:
        lines = []
        for i, (re_t, fe_t) in enumerate(self.steps):
            lines.append(f"MF iteration {i}:")
            lines.append("  latent RE: " + re_t.to_summary_string())
            if fe_t is not None:
                lines.append("  latent matrix: " + fe_t.to_summary_string())
        return "\n".join(lines)
