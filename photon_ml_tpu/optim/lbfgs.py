"""L-BFGS as a jit-compiled ``lax.while_loop`` — fully on device.

The reference wraps Breeze's LBFGS iterator and crosses the driver/executor
boundary twice per iteration (photon-lib optimization/LBFGS.scala:64-111;
SURVEY.md §3.4). Here the entire optimize loop — two-loop recursion, strong
Wolfe line search, convergence checks — is one XLA program; under ``vmap`` it
solves batches of independent problems (per-entity random effects) with
converged lanes frozen; under a sharded mesh the objective's psum makes it
data-parallel with no other change.

Defaults match the reference: maxIter=100, history m=10, tolerance=1e-7
(LBFGS.scala:152-156). Box constraints project every iterate into the
hypercube, as in LBFGS.BreezeOptimization.next (LBFGS.scala:72-87).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (
    NOT_CONVERGED,
    BoxConstraints,
    Objective,
    SolveResult,
    convergence_reason,
    project_or_identity,
)
from photon_ml_tpu.optim.linesearch import strong_wolfe

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class LBFGSConfig:
    max_iterations: int = 100
    tolerance: float = 1e-7
    history: int = 10
    c1: float = 1e-4
    c2: float = 0.9
    max_ls_evals: int = 20
    min_curvature: float = 1e-10  # skip history update below this s.y


class _LBFGSState(NamedTuple):
    w: Array
    value: Array
    grad: Array
    prev_value: Array
    S: Array  # [m, d] coefficient deltas (circular)
    Y: Array  # [m, d] gradient deltas (circular)
    rho: Array  # [m] 1/(s.y)
    head: Array  # i32 next write slot
    n_hist: Array  # i32 valid pairs
    gamma: Array  # H0 scaling
    iteration: Array
    reason: Array
    ls_failed: Array
    values: Array
    grad_norms: Array
    z: Array  # carried margins X'@w (margin-carrying fast path; else [0])


def two_loop_direction(
    g: Array, S: Array, Y: Array, rho: Array, head: Array, n_hist: Array, gamma: Array
) -> Array:
    """Two-loop recursion: returns approx H^{-1} g (NOT negated)."""
    m = S.shape[0]

    def bwd(i, carry):
        q, alphas = carry
        idx = (head - 1 - i) % m
        valid = i < n_hist
        a = jnp.where(valid, rho[idx] * jnp.dot(S[idx], q), 0.0)
        return q - a * Y[idx], alphas.at[idx].set(a)

    q, alphas = lax.fori_loop(0, m, bwd, (g, jnp.zeros((m,), dtype=g.dtype)))
    r = gamma * q

    def fwd(i, r):
        idx = (head - n_hist + i) % m
        valid = i < n_hist
        b = rho[idx] * jnp.dot(Y[idx], r)
        return r + jnp.where(valid, alphas[idx] - b, 0.0) * S[idx]

    return lax.fori_loop(0, m, fwd, r)


def update_history(
    S: Array,
    Y: Array,
    rho: Array,
    head: Array,
    n_hist: Array,
    gamma: Array,
    s: Array,
    y: Array,
    min_curvature: float,
) -> tuple[Array, Array, Array, Array, Array, Array]:
    """Push an (s, y) pair into the circular history if curvature is positive."""
    sy = jnp.dot(s, y)
    yy = jnp.dot(y, y)
    ok = sy > min_curvature
    m = S.shape[0]
    S = jnp.where(ok, S.at[head].set(s), S)
    Y = jnp.where(ok, Y.at[head].set(y), Y)
    rho = jnp.where(ok, rho.at[head].set(1.0 / jnp.where(ok, sy, 1.0)), rho)
    head = jnp.where(ok, (head + 1) % m, head)
    n_hist = jnp.where(ok, jnp.minimum(n_hist + 1, m), n_hist)
    gamma = jnp.where(ok & (yy > 0), sy / jnp.where(yy > 0, yy, 1.0), gamma)
    return S, Y, rho, head, n_hist, gamma


def lbfgs_solve(
    objective: Objective,
    w0: Array,
    config: LBFGSConfig = LBFGSConfig(),
    constraints: Optional[BoxConstraints] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
) -> SolveResult:
    """Minimize the objective from ``w0``; returns a :class:`SolveResult`.

    ``init_value``/``init_grad_norm`` override the convergence-check anchors
    for warm-started re-runs (isReusingPreviousInitialState semantics,
    Optimizer.scala:33-35).
    """
    m, d = config.history, w0.shape[0]
    dtype = w0.dtype
    w0 = project_or_identity(constraints, w0)
    # margin-carrying fast path: thread z = X'@w through the loop so each
    # iteration costs one gather (u = X'@p) + one scatter (gradient)
    # instead of two fused sweeps. Requires linear margin updates, so box
    # constraints (projection breaks z' = z + a*u) keep the standard path.
    use_z = (
        constraints is None
        and objective.margins is not None
        and objective.ls_prepare_z is not None
        and objective.ls_advance is not None
        and objective.value_and_grad_at is not None
    )
    if use_z:
        z0 = objective.margins(w0)
        f0, g0 = objective.value_and_grad_at(w0, z0)
    else:
        z0 = jnp.zeros((0,), dtype)
        f0, g0 = objective.value_and_grad(w0)

    anchor_f = f0 if init_value is None else jnp.asarray(init_value, dtype)
    anchor_gn = (
        jnp.linalg.norm(g0) if init_grad_norm is None else jnp.asarray(init_grad_norm, dtype)
    )

    nvals = config.max_iterations + 1
    values = jnp.full((nvals,), jnp.inf, dtype=dtype).at[0].set(f0)
    gnorms = jnp.full((nvals,), jnp.inf, dtype=dtype).at[0].set(jnp.linalg.norm(g0))

    init = _LBFGSState(
        w=w0,
        value=f0,
        grad=g0,
        prev_value=f0,
        S=jnp.zeros((m, d), dtype=dtype),
        Y=jnp.zeros((m, d), dtype=dtype),
        rho=jnp.zeros((m,), dtype=dtype),
        head=jnp.int32(0),
        n_hist=jnp.int32(0),
        gamma=jnp.asarray(1.0, dtype),
        iteration=jnp.int32(0),
        reason=jnp.int32(NOT_CONVERGED),
        ls_failed=jnp.bool_(False),
        values=values,
        grad_norms=gnorms,
        z=z0,
    )

    def cond(s: _LBFGSState):
        return s.reason == NOT_CONVERGED

    def body(s: _LBFGSState) -> _LBFGSState:
        p = -two_loop_direction(s.grad, s.S, s.Y, s.rho, s.head, s.n_hist, s.gamma)
        dphi0 = jnp.dot(s.grad, p)
        # safeguard: fall back to steepest descent on non-descent direction
        bad = dphi0 >= 0.0
        p = jnp.where(bad, -s.grad, p)
        dphi0 = jnp.where(bad, -jnp.dot(s.grad, s.grad), dphi0)

        gnorm = jnp.linalg.norm(s.grad)
        first = s.n_hist == 0
        init_step = jnp.where(
            first, jnp.minimum(1.0, 1.0 / jnp.maximum(gnorm, 1e-12)), 1.0
        ).astype(dtype)

        if use_z:
            carry = objective.ls_prepare_z(s.z, s.w, p)
        else:
            carry = objective.ls_prepare(s.w, p)
        ls = strong_wolfe(
            objective.ls_eval,
            carry,
            s.value,
            dphi0,
            init_step=init_step,
            c1=config.c1,
            c2=config.c2,
            max_evals=config.max_ls_evals,
        )

        w_step = s.w + ls.alpha * p
        if use_z:
            w_new = w_step
            z_new = objective.ls_advance(carry, ls.alpha)
            f_new, g_new = objective.value_and_grad_at(w_new, z_new)
        else:
            w_new = project_or_identity(constraints, w_step)
            z_new = s.z
            f_new, g_new = objective.value_and_grad(w_new)

        S, Y, rho, head, n_hist, gamma = update_history(
            s.S, s.Y, s.rho, s.head, s.n_hist, s.gamma,
            w_new - s.w, g_new - s.grad, config.min_curvature,
        )

        it = s.iteration + 1
        reason = convergence_reason(
            it,
            f_new,
            s.value,
            jnp.linalg.norm(g_new),
            anchor_f,
            anchor_gn,
            config.max_iterations,
            config.tolerance,
            ls.failed,
        )
        nxt = _LBFGSState(
            w=w_new,
            value=f_new,
            grad=g_new,
            prev_value=s.value,
            S=S, Y=Y, rho=rho, head=head, n_hist=n_hist, gamma=gamma,
            iteration=it,
            reason=reason,
            ls_failed=ls.failed,
            values=s.values.at[it].set(f_new),
            grad_norms=s.grad_norms.at[it].set(jnp.linalg.norm(g_new)),
            z=z_new,
        )
        # Freeze lanes that already converged (vmap batching runs the body
        # for all lanes until every lane's cond is False).
        return jax.tree.map(
            lambda a, b: jnp.where(s.reason == NOT_CONVERGED, b, a), s, nxt
        )

    final = lax.while_loop(cond, body, init)
    # On line-search failure keep the best iterate seen (pre-failure w).
    # data passes: the init evaluation + one direction-margins pass per
    # iteration (line-search re-evaluations ride the carried margins —
    # O(n) elementwise, not a sparse-data pass).
    return SolveResult(
        w=final.w,
        value=final.value,
        grad=final.grad,
        iterations=final.iteration,
        reason=final.reason,
        values=final.values,
        grad_norms=final.grad_norms,
        data_passes=final.iteration + 1,
    )
