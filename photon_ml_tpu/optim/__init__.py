from photon_ml_tpu.optim.adapter import glm_adapter  # noqa: F401
from photon_ml_tpu.optim.common import (  # noqa: F401
    CONVERGENCE_REASON_NAMES,
    FUNCTION_VALUES_CONVERGED,
    GRADIENT_CONVERGED,
    MAX_ITERATIONS,
    NOT_CONVERGED,
    OBJECTIVE_NOT_IMPROVING,
    BoxConstraints,
    Objective,
    SolveResult,
    from_value_and_grad,
)
from photon_ml_tpu.optim.factory import (  # noqa: F401
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
    build_objective,
    solve,
)
from photon_ml_tpu.optim.guard import (  # noqa: F401
    GuardSpec,
    model_is_finite,
    solve_health,
)
from photon_ml_tpu.optim.newton import NewtonConfig, newton_solve  # noqa: F401
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve  # noqa: F401
from photon_ml_tpu.optim.owlqn import owlqn_solve  # noqa: F401
from photon_ml_tpu.optim.tron import TRONConfig, tron_solve  # noqa: F401
