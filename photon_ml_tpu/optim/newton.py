"""Damped Newton with explicit Hessians — the small-dimension fast path.

No reference analog (the reference solves every per-entity problem with the
same serial LBFGS/TRON it uses globally, RandomEffectCoordinate.scala:
101-130); this is a TPU-first addition. Per-entity random-effect problems
are TINY (projected local dims K ~ 16-1000): under ``vmap`` the deep
LBFGS/line-search ``while_loop`` nest is LATENCY-bound — hundreds of
sequential micro-steps — while an explicit-Hessian Newton iteration is a
few big batched ops on the MXU: build H [E, K, K] via one data sweep,
Cholesky-solve, damp by fixed step-halving. 5-10x shallower loops for the
same optimum on convex GLMs.

Guard rails: requires a twice-differentiable loss (no smoothed hinge), no
L1 (factory rejects), and is intended for small K — H is dense [K, K].
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (
    NOT_CONVERGED,
    BoxConstraints,
    SolveResult,
    convergence_reason,
    project_or_identity,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class NewtonConfig:
    max_iterations: int = 20
    tolerance: float = 1e-7
    max_halvings: int = 10  # damping: halve the step until f decreases
    ridge: float = 1e-8  # Cholesky jitter


class _NewtonState(NamedTuple):
    w: Array
    value: Array
    grad: Array
    prev_value: Array
    iteration: Array
    reason: Array
    values: Array
    grad_norms: Array


def newton_solve(
    value_and_grad,
    hessian,
    w0: Array,
    config: NewtonConfig = NewtonConfig(),
    constraints: Optional[BoxConstraints] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
    ls_prepare=None,
    ls_eval=None,
) -> SolveResult:
    """Minimize a convex twice-differentiable objective.

    ``value_and_grad(w) -> (f, g)``; ``hessian(w) -> H [d, d]``. Under
    ``vmap`` this solves batches of independent problems with converged
    lanes frozen (the RE bucket pattern). With the optional directional
    oracle (``ls_prepare``/``ls_eval``, unconstrained only) the damping
    candidates cost O(n) elementwise each instead of full objective sweeps.
    """
    dtype = w0.dtype
    d = w0.shape[0]
    w0 = project_or_identity(constraints, w0)
    f0, g0 = value_and_grad(w0)
    g0n = jnp.linalg.norm(g0)
    anchor_f = f0 if init_value is None else jnp.asarray(init_value, dtype)
    anchor_gn = g0n if init_grad_norm is None else jnp.asarray(init_grad_norm, dtype)

    nvals = config.max_iterations + 1
    values = jnp.full((nvals,), jnp.inf, dtype=dtype).at[0].set(f0)
    gnorms = jnp.full((nvals,), jnp.inf, dtype=dtype).at[0].set(g0n)

    init = _NewtonState(
        w=w0,
        value=f0,
        grad=g0,
        prev_value=f0,
        iteration=jnp.int32(0),
        reason=jnp.int32(NOT_CONVERGED),
        values=values,
        grad_norms=gnorms,
    )

    eye = jnp.eye(d, dtype=dtype)
    use_oracle = (
        constraints is None and ls_prepare is not None and ls_eval is not None
    )

    def cond(s: _NewtonState):
        return s.reason == NOT_CONVERGED

    def body(s: _NewtonState) -> _NewtonState:
        H = hessian(s.w) + config.ridge * eye
        # Cholesky solve; fall back to steepest descent if H is not SPD
        L = jnp.linalg.cholesky(H)
        ok = jnp.all(jnp.isfinite(L))
        step = jnp.where(
            ok,
            -jax.scipy.linalg.cho_solve((jnp.where(ok, L, eye), True), s.grad),
            -s.grad,
        )

        # damping: evaluate ALL candidate alphas 1, 1/2, 1/4, ... in ONE
        # vectorized sweep (no sequential halving loop — latency is the
        # enemy for vmapped per-entity solves) and take the first decrease
        alphas = jnp.asarray(0.5, dtype) ** jnp.arange(
            config.max_halvings, dtype=dtype
        )
        if use_oracle:
            # margin-space oracle: each candidate is elementwise, not a
            # full gather/scatter objective sweep
            carry = ls_prepare(s.w, step)
            f_tries = jax.vmap(lambda a: ls_eval(carry, a)[0])(alphas)
        else:
            w_tries = project_or_identity(
                constraints, s.w[None, :] + alphas[:, None] * step[None, :]
            )
            f_tries = jax.vmap(lambda wt: value_and_grad(wt)[0])(w_tries)
        good = f_tries < s.value
        found = jnp.any(good)
        best_alpha = jnp.where(found, alphas[jnp.argmax(good)], 0.0)

        w_new = project_or_identity(constraints, s.w + best_alpha * step)
        f_new, g_new = value_and_grad(w_new)
        it = s.iteration + 1
        reason = convergence_reason(
            it,
            f_new,
            s.value,
            jnp.linalg.norm(g_new),
            anchor_f,
            anchor_gn,
            config.max_iterations,
            config.tolerance,
            ~found,  # no decreasing step found = objective not improving
        )
        nxt = _NewtonState(
            w=w_new,
            value=f_new,
            grad=g_new,
            prev_value=s.value,
            iteration=it,
            reason=reason,
            values=s.values.at[it].set(f_new),
            grad_norms=s.grad_norms.at[it].set(jnp.linalg.norm(g_new)),
        )
        return jax.tree.map(
            lambda a, b: jnp.where(s.reason == NOT_CONVERGED, b, a), s, nxt
        )

    final = lax.while_loop(cond, body, init)
    return SolveResult(
        w=final.w,
        value=final.value,
        grad=final.grad,
        iterations=final.iteration,
        reason=final.reason,
        values=final.values,
        grad_norms=final.grad_norms,
        data_passes=final.iteration + 1,
    )
