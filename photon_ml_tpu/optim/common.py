"""Shared optimizer machinery: objective adapters, convergence semantics,
box-constraint projection, and result types.

Convergence reasons and checks mirror the reference's Optimizer
(photon-lib optimization/Optimizer.scala:155-169): an optimizer run stops on
  - MaxIterations:          iter >= max_iterations
  - ObjectiveNotImproving:  the line search failed to make progress
  - FunctionValuesConverged |f_k - f_{k-1}| <= tolerance * f_0
  - GradientConverged       ||g_k|| <= tolerance * ||g_0||
All checks are relative to the *initial* state, so warm-started re-runs may
reuse a stored initial state for consistent convergence behavior
(Optimizer.scala:33-35 semantics; pass ``init_value``/``init_grad_norm``).

Everything here is pure-functional and shape-static: it jits, vmaps (for
per-entity random-effect solves) and shard_maps unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

# ConvergenceReason codes (int32). 0 = still running.
NOT_CONVERGED = 0
MAX_ITERATIONS = 1
OBJECTIVE_NOT_IMPROVING = 2
FUNCTION_VALUES_CONVERGED = 3
GRADIENT_CONVERGED = 4

CONVERGENCE_REASON_NAMES = {
    NOT_CONVERGED: "NotConverged",
    MAX_ITERATIONS: "MaxIterations",
    OBJECTIVE_NOT_IMPROVING: "ObjectiveNotImproving",
    FUNCTION_VALUES_CONVERGED: "FunctionValuesConverged",
    GRADIENT_CONVERGED: "GradientConverged",
}


class Objective(NamedTuple):
    """Adapter the optimizers drive.

    ``ls_prepare``/``ls_eval`` give line searches a cheap directional oracle:
    for GLMs, margins along a search direction are ``z + a*u`` with
    ``u = X @ p`` precomputed once, so each trial is O(n) elementwise instead
    of a full gather/scatter pass (a TPU-side win the Spark reference cannot
    express — every Breeze line-search trial there is a full treeAggregate).
    ``hvp`` is required by TRON only.
    """

    value_and_grad: Callable[[Array], tuple[Array, Array]]
    value: Callable[[Array], Array]
    ls_prepare: Callable[[Array, Array], Any]
    ls_eval: Callable[[Any, Array], tuple[Array, Array]]
    hvp: Optional[Callable[[Array, Array], Array]] = None
    # -- optional margin-carrying protocol (GLM fast path) ------------------
    # When all four are present AND the solve is unconstrained, LBFGS keeps
    # the per-row margins z = X'@w in its loop state: each iteration then
    # costs ONE gather pass (u = X'@p via ls_prepare_z) + ONE scatter pass
    # (gradient via value_and_grad_at) instead of two full gather+scatter
    # sweeps — ~2x fewer one-hot matmuls on the tiled layout.
    margins: Optional[Callable[[Array], Array]] = None  # w -> z
    ls_prepare_z: Optional[Callable[[Array, Array, Array], Any]] = None  # (z,w,p)
    ls_advance: Optional[Callable[[Any, Array], Array]] = None  # (carry,a)->z'
    value_and_grad_at: Optional[
        Callable[[Array, Array], tuple[Array, Array]]
    ] = None  # (w, z) -> (f, g)
    dir_margins: Optional[Callable[[Array], Array]] = None  # p -> X'@p (+shift)
    # TRON CG fast path: ``curvature(z)`` -> per-row d2 = weight*l''(z),
    # computed ONCE per outer iteration; ``hvp_at(d2, v)`` -> Hv with no
    # per-call z gather or d2z pass (one gather + one scatter sweep)
    curvature: Optional[Callable[[Array], Array]] = None  # z -> d2 rows
    hvp_at: Optional[Callable[[Array, Array], Array]] = None  # (d2, v) -> Hv
    # Full dense Hessian (small-d only): the batched-Newton fast path for
    # per-entity solves. None when the layout can't densify (TiledBatch).
    hessian: Optional[Callable[[Array], Array]] = None  # w -> H [d, d]


def from_value_and_grad(
    fn: Callable[[Array], tuple[Array, Array]],
    hvp: Optional[Callable[[Array, Array], Array]] = None,
) -> Objective:
    """Wrap a plain value-and-grad callable (line-search trials do full evals)."""

    def ls_prepare(w, p):
        return (w, p)

    def ls_eval(carry, alpha):
        w, p = carry
        f, g = fn(w + alpha * p)
        return f, jnp.dot(g, p)

    return Objective(
        value_and_grad=fn,
        value=lambda w: fn(w)[0],
        ls_prepare=ls_prepare,
        ls_eval=ls_eval,
        hvp=hvp,
    )


class BoxConstraints(NamedTuple):
    """Per-coefficient box [lower, upper]; +-inf entries are unconstrained.

    The reference projects every iterate into the constraint hypercube
    (LBFGS.scala:72-87 / OptimizerConfig constraintMap).
    """

    lower: Array
    upper: Array

    def project(self, w: Array) -> Array:
        return jnp.clip(w, self.lower, self.upper)


def project_or_identity(constraints: Optional[BoxConstraints], w: Array) -> Array:
    return w if constraints is None else constraints.project(w)


class SolveResult(NamedTuple):
    """Terminal optimizer state plus per-iteration telemetry buffers.

    ``values``/``grad_norms`` are fixed-size (max_iterations + 1) tracking
    buffers — the OptimizationStatesTracker analog — valid up to
    ``iterations`` (inclusive); the rest is padding.
    """

    w: Array
    value: Array
    grad: Array
    iterations: Array  # i32
    reason: Array  # i32 convergence code
    values: Array  # f[max_iter + 1]
    grad_norms: Array  # f[max_iter + 1]
    # i32 count of FULL passes over the training data (value+grad or
    # Hessian-vector evaluations): benches divide rows*data_passes by
    # wall-clock so optimizers with inner data loops (TRON's truncated CG
    # runs one Hv pass per CG step) report throughput comparably with
    # single-pass-per-iteration optimizers.
    data_passes: Array = 0


def convergence_reason(
    iteration: Array,
    value: Array,
    prev_value: Array,
    grad_norm: Array,
    init_value: Array,
    init_grad_norm: Array,
    max_iterations: int,
    tolerance: float,
    ls_failed: Array,
) -> Array:
    """Reference-parity convergence decision (Optimizer.scala:155-169)."""
    tol = jnp.asarray(tolerance, dtype=value.dtype)
    reason = jnp.where(
        iteration >= max_iterations,
        MAX_ITERATIONS,
        jnp.where(
            ls_failed,
            OBJECTIVE_NOT_IMPROVING,
            jnp.where(
                jnp.abs(value - prev_value) <= tol * jnp.abs(init_value),
                FUNCTION_VALUES_CONVERGED,
                jnp.where(
                    grad_norm <= tol * init_grad_norm, GRADIENT_CONVERGED, NOT_CONVERGED
                ),
            ),
        ),
    )
    return reason.astype(jnp.int32)
