"""Optimizer selection and regularization wiring.

Mirrors the reference's OptimizerFactory (photon-api
optimization/OptimizerFactory.scala:39-77) and RegularizationContext
(optimization/RegularizationContext.scala:41-66): LBFGS handles NONE/L2,
OWLQN handles L1/ELASTIC_NET (l1 = alpha*lambda, l2 = (1-alpha)*lambda),
TRON handles NONE/L2 only and requires a twice-differentiable loss.
"""

from __future__ import annotations

import dataclasses
from enum import Enum
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.objective import GLMObjective, make_objective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim.adapter import glm_adapter
from photon_ml_tpu.optim.common import BoxConstraints, SolveResult
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, lbfgs_solve
from photon_ml_tpu.optim.newton import NewtonConfig, newton_solve
from photon_ml_tpu.optim.owlqn import owlqn_solve
from photon_ml_tpu.optim.tron import TRONConfig, tron_solve

Array = jax.Array


class OptimizerType(str, Enum):
    LBFGS = "lbfgs"
    TRON = "tron"
    # TPU-first addition (no reference analog): damped Newton with explicit
    # batched [d, d] Hessians — the latency-light fast path for SMALL-d
    # solves (per-entity random effects), where vmapped LBFGS is bound by
    # sequential while_loop depth, not FLOPs
    NEWTON = "newton"


class RegularizationType(str, Enum):
    NONE = "none"
    L1 = "l1"
    L2 = "l2"
    ELASTIC_NET = "elastic_net"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Splits a single regularization weight into (l1, l2) parts."""

    reg_type: RegularizationType = RegularizationType.NONE
    alpha: float = 1.0  # elastic-net mixing: l1 = alpha*w, l2 = (1-alpha)*w

    def __post_init__(self):
        if self.reg_type == RegularizationType.ELASTIC_NET:
            if not (0.0 <= self.alpha <= 1.0):
                raise ValueError(f"elastic-net alpha must be in [0,1]: {self.alpha}")

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L1:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return self.alpha * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L2:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (1.0 - self.alpha) * reg_weight
        return 0.0


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Typed analog of the reference's OptimizerConfig + GLMOptimizationConfiguration.

    ``box_constraints`` holds (feature_index, lower, upper) triples — the
    constraintMap analog (OptimizerConfig.scala); every optimizer projects
    iterates into the hypercube. Indices address the GLOBAL feature space,
    so constraints apply to fixed-effect / plain-GLM solves only (per-entity
    projected spaces renumber features; matching reference scope).
    """

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    regularization: RegularizationContext = RegularizationContext()
    regularization_weight: float = 0.0
    lbfgs_history: int = 10
    down_sampling_rate: float = 1.0
    box_constraints: Optional[tuple[tuple[int, float, float], ...]] = None

    def dense_box_bounds(self, num_features: int, sentinel: bool = False):
        """Validated dense numpy (lower, upper) bounds from the sparse
        (index, lower, upper) triples, or None when unconstrained. With
        ``sentinel`` the arrays carry one extra trailing unbounded slot —
        the gather target for projected spaces' padding id (index-map
        sentinel == num_features)."""
        if not self.box_constraints:
            return None
        import numpy as np

        size = num_features + (1 if sentinel else 0)
        lower = np.full(size, -np.inf, np.float32)
        upper = np.full(size, np.inf, np.float32)
        for idx, lo, hi in self.box_constraints:
            if not 0 <= idx < num_features:
                raise ValueError(
                    f"box constraint index {idx} out of range [0, {num_features})"
                )
            if lo > hi:
                raise ValueError(f"box constraint [{lo}, {hi}] is empty")
            lower[idx], upper[idx] = lo, hi
        return lower, upper

    def build_box_constraints(self, num_features: int) -> Optional[BoxConstraints]:
        """Materialize the sparse (index, lower, upper) triples as dense
        projection bounds for a ``num_features``-dim solve."""
        bounds = self.dense_box_bounds(num_features)
        if bounds is None:
            return None
        lower, upper = bounds
        return BoxConstraints(
            lower=jnp.asarray(lower, jnp.float32),
            upper=jnp.asarray(upper, jnp.float32),
        )

    def validate(self, loss_name: str) -> None:
        uses_l1 = self.regularization.reg_type in (
            RegularizationType.L1,
            RegularizationType.ELASTIC_NET,
        )
        if self.optimizer_type in (OptimizerType.TRON, OptimizerType.NEWTON):
            name = self.optimizer_type.value.upper()
            if uses_l1:
                raise ValueError(
                    f"{name} does not support L1/elastic-net regularization "
                    "(OptimizerFactory parity)"
                )
            if not get_loss(loss_name).has_hessian:
                raise ValueError(
                    f"{name} requires a twice-differentiable loss; "
                    f"'{loss_name}' is not (use LBFGS/OWLQN)"
                )


def split_reg_weights(
    reg: RegularizationContext, weights
) -> tuple[jax.Array, jax.Array]:
    """Vectorized (l2, l1) split of a λ GRID: the per-scalar
    ``RegularizationContext.l1_weight``/``l2_weight`` arithmetic applied to
    a whole [G] array at once, always returning [G] arrays (NONE-type
    regularization broadcasts its 0.0 so the sweep solvers' config axis
    keeps a uniform shape)."""
    lams = jnp.asarray(weights, jnp.float32)
    return (
        jnp.broadcast_to(
            jnp.asarray(reg.l2_weight(lams), jnp.float32), lams.shape
        ),
        jnp.broadcast_to(
            jnp.asarray(reg.l1_weight(lams), jnp.float32), lams.shape
        ),
    )


def build_objective(
    loss_name: str,
    config: OptimizerConfig,
    factors: Optional[Array] = None,
    shifts: Optional[Array] = None,
) -> GLMObjective:
    """GLM objective with the L2 part of the configured regularization."""
    return make_objective(
        loss_name,
        l2_weight=config.regularization.l2_weight(config.regularization_weight),
        factors=factors,
        shifts=shifts,
    )


def dispatch_solve(
    adapter,
    w0: Array,
    config: OptimizerConfig,
    l1,
    constraints: Optional[BoxConstraints] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
) -> SolveResult:
    """Route a prebuilt objective adapter to the configured optimizer.

    Shared by the single-device path (solve) and the mesh path
    (parallel.distributed) so dispatch rules live in exactly one place.
    ``l1`` may be a traced scalar — the OWLQN-vs-LBFGS choice depends only
    on the (static) regularization type, so lambda sweeps don't recompile.
    """
    uses_l1 = config.regularization.reg_type in (
        RegularizationType.L1,
        RegularizationType.ELASTIC_NET,
    )
    if config.optimizer_type == OptimizerType.TRON:
        return tron_solve(
            adapter,
            w0,
            TRONConfig(
                max_iterations=config.max_iterations, tolerance=config.tolerance
            ),
            constraints=constraints,
            init_value=init_value,
            init_grad_norm=init_grad_norm,
        )
    if config.optimizer_type == OptimizerType.NEWTON:
        if adapter.hessian is None:
            raise ValueError(
                "NEWTON needs a dense-Hessian adapter (small-d layouts only; "
                "the tiled layout cannot densify)"
            )
        return newton_solve(
            adapter.value_and_grad,
            adapter.hessian,
            w0,
            NewtonConfig(
                max_iterations=config.max_iterations, tolerance=config.tolerance
            ),
            constraints=constraints,
            init_value=init_value,
            init_grad_norm=init_grad_norm,
            ls_prepare=adapter.ls_prepare,
            ls_eval=adapter.ls_eval,
        )

    lcfg = LBFGSConfig(
        max_iterations=config.max_iterations,
        tolerance=config.tolerance,
        history=config.lbfgs_history,
    )
    if uses_l1:
        return owlqn_solve(
            adapter,
            w0,
            l1,
            lcfg,
            constraints=constraints,
            init_value=init_value,
            init_grad_norm=init_grad_norm,
        )
    return lbfgs_solve(
        adapter,
        w0,
        lcfg,
        constraints=constraints,
        init_value=init_value,
        init_grad_norm=init_grad_norm,
    )


def solve(
    loss_name: str,
    batch: SparseBatch,
    config: OptimizerConfig,
    w0: Array,
    constraints: Optional[BoxConstraints] = None,
    factors: Optional[Array] = None,
    shifts: Optional[Array] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
) -> SolveResult:
    """One-stop GLM solve: build objective + adapter, dispatch the optimizer.

    Pure and jit-friendly: wrap in jax.jit (static config) or vmap over
    batched problems.
    """
    config.validate(loss_name)
    obj = build_objective(loss_name, config, factors=factors, shifts=shifts)
    adapter = glm_adapter(obj, batch)
    l1 = config.regularization.l1_weight(config.regularization_weight)
    if constraints is None:
        constraints = config.build_box_constraints(batch.num_features)
    return dispatch_solve(
        adapter, w0, config, l1, constraints, init_value, init_grad_norm
    )
