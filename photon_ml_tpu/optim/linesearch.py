"""Strong-Wolfe line search (Nocedal & Wright Alg. 3.5/3.6) as a single
``lax.while_loop`` state machine — one directional-oracle evaluation per loop
iteration, jittable and vmappable.

This replaces the Breeze StrongWolfeLineSearch the reference's LBFGS relies
on (photon-lib optimization/LBFGS.scala wraps breeze.optimize.LBFGS). Default
constants c1=1e-4, c2=0.9 match the Breeze/Nocedal defaults for quasi-Newton
directions.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

# state machine modes
_BRACKET = 0
_ZOOM = 1
_DONE = 2
_FAILED = 3


class LineSearchResult(NamedTuple):
    alpha: Array  # accepted step (0.0 on failure)
    phi: Array  # objective at accepted step
    dphi: Array  # directional derivative at accepted step
    failed: Array  # bool
    num_evals: Array  # i32


class _LSState(NamedTuple):
    mode: Array
    alpha: Array  # next trial step
    alpha_prev: Array
    phi_prev: Array
    dphi_prev: Array
    lo: Array  # zoom bracket low endpoint (best-so-far inside bracket)
    phi_lo: Array
    dphi_lo: Array
    hi: Array  # zoom bracket high endpoint
    phi_hi: Array
    dphi_hi: Array
    best_alpha: Array  # Wolfe-accepted point
    best_phi: Array
    best_dphi: Array
    armijo_alpha: Array  # best Armijo-satisfying trial seen anywhere
    armijo_phi: Array
    armijo_dphi: Array
    evals: Array


def _cubic_min(a, fa, dfa, b, fb, dfb):
    """Minimizer of the cubic interpolant on [a, b]; falls back to bisection."""
    d1 = dfa + dfb - 3.0 * (fa - fb) / (a - b)
    rad = d1 * d1 - dfa * dfb
    safe = rad >= 0.0
    d2 = jnp.sqrt(jnp.where(safe, rad, 0.0)) * jnp.sign(b - a)
    denom = dfb - dfa + 2.0 * d2
    x = b - (b - a) * (dfb + d2 - d1) / denom
    mid = 0.5 * (a + b)
    lo_, hi_ = jnp.minimum(a, b), jnp.maximum(a, b)
    # keep the trial strictly interior (5% margin) so zoom always shrinks
    margin = 0.05 * (hi_ - lo_)
    ok = safe & jnp.isfinite(x) & (x > lo_ + margin) & (x < hi_ - margin) & (
        jnp.abs(denom) > 1e-20
    )
    return jnp.where(ok, x, mid)


def strong_wolfe(
    ls_eval: Callable[[Any, Array], tuple[Array, Array]],
    carry: Any,
    phi0: Array,
    dphi0: Array,
    init_step: Array | float = 1.0,
    c1: float = 1e-4,
    c2: float = 0.9,
    max_evals: int = 20,
    max_step: float = 1e10,
) -> LineSearchResult:
    """Find alpha satisfying phi(a) <= phi0 + c1*a*dphi0 and |dphi(a)| <= c2*|dphi0|.

    ``ls_eval(carry, a) -> (phi(a), dphi(a))`` is the directional oracle.
    On eval exhaustion, falls back to the best sufficient-decrease point seen
    (Armijo-only acceptance, like Breeze's fallback on exhaustion).
    """
    dtype = phi0.dtype
    f = jnp.asarray

    init = _LSState(
        mode=jnp.int32(_BRACKET),
        alpha=f(init_step, dtype=dtype),
        alpha_prev=f(0.0, dtype=dtype),
        phi_prev=phi0,
        dphi_prev=dphi0,
        lo=f(0.0, dtype=dtype),
        phi_lo=phi0,
        dphi_lo=dphi0,
        hi=f(0.0, dtype=dtype),
        phi_hi=phi0,
        dphi_hi=dphi0,
        best_alpha=f(0.0, dtype=dtype),
        best_phi=phi0,
        best_dphi=dphi0,
        armijo_alpha=f(0.0, dtype=dtype),
        armijo_phi=phi0,
        armijo_dphi=dphi0,
        evals=jnp.int32(0),
    )

    armijo = lambda a, phi: phi <= phi0 + c1 * a * dphi0
    curvature = lambda dphi: jnp.abs(dphi) <= c2 * jnp.abs(dphi0)

    def cond(s: _LSState):
        return (s.mode < _DONE) & (s.evals < max_evals)

    def body(s: _LSState) -> _LSState:
        phi, dphi = ls_eval(carry, s.alpha)
        evals = s.evals + 1

        # track best Armijo point across both phases (exhaustion fallback)
        better = armijo(s.alpha, phi) & (phi < s.armijo_phi)
        s = s._replace(
            armijo_alpha=jnp.where(better, s.alpha, s.armijo_alpha),
            armijo_phi=jnp.where(better, phi, s.armijo_phi),
            armijo_dphi=jnp.where(better, dphi, s.armijo_dphi),
        )

        def bracket_step(s):
            # Alg 3.5: decide accept / zoom / extend
            hit_armijo_fail = (~armijo(s.alpha, phi)) | (
                (evals > 1) & (phi >= s.phi_prev)
            )
            accept = armijo(s.alpha, phi) & curvature(dphi)
            pos_slope = dphi >= 0.0

            # -> zoom(alpha_prev, alpha) on armijo failure
            # -> zoom(alpha, alpha_prev) on positive slope
            go_zoom = hit_armijo_fail | (~accept & pos_slope)
            zoom_lo = jnp.where(hit_armijo_fail, s.alpha_prev, s.alpha)
            zoom_philo = jnp.where(hit_armijo_fail, s.phi_prev, phi)
            zoom_dphilo = jnp.where(hit_armijo_fail, s.dphi_prev, dphi)
            zoom_hi = jnp.where(hit_armijo_fail, s.alpha, s.alpha_prev)
            zoom_phihi = jnp.where(hit_armijo_fail, phi, s.phi_prev)
            zoom_dphihi = jnp.where(hit_armijo_fail, dphi, s.dphi_prev)

            next_alpha_bracket = jnp.minimum(s.alpha * 2.0, max_step)
            overflow = s.alpha >= max_step

            mode = jnp.where(
                accept,
                _DONE,
                jnp.where(go_zoom, _ZOOM, jnp.where(overflow, _FAILED, _BRACKET)),
            ).astype(jnp.int32)
            first_zoom_trial = _cubic_min(
                zoom_lo, zoom_philo, zoom_dphilo, zoom_hi, zoom_phihi, zoom_dphihi
            )
            return s._replace(
                mode=mode,
                alpha=jnp.where(go_zoom, first_zoom_trial, next_alpha_bracket),
                alpha_prev=s.alpha,
                phi_prev=phi,
                dphi_prev=dphi,
                lo=jnp.where(go_zoom, zoom_lo, s.lo),
                phi_lo=jnp.where(go_zoom, zoom_philo, s.phi_lo),
                dphi_lo=jnp.where(go_zoom, zoom_dphilo, s.dphi_lo),
                hi=jnp.where(go_zoom, zoom_hi, s.hi),
                phi_hi=jnp.where(go_zoom, zoom_phihi, s.phi_hi),
                dphi_hi=jnp.where(go_zoom, zoom_dphihi, s.dphi_hi),
                best_alpha=jnp.where(accept, s.alpha, s.best_alpha),
                best_phi=jnp.where(accept, phi, s.best_phi),
                best_dphi=jnp.where(accept, dphi, s.best_dphi),
                evals=evals,
            )

        def zoom_step(s):
            # Alg 3.6 with cubic-interpolated trial (s.alpha is the trial)
            a = s.alpha
            fail_armijo = (~armijo(a, phi)) | (phi >= s.phi_lo)
            accept = (~fail_armijo) & curvature(dphi)
            # on ~fail_armijo & ~accept: lo moves to a; hi moves to old lo if
            # dphi*(hi-lo) >= 0
            flip_hi = dphi * (s.hi - s.lo) >= 0.0
            new_lo = jnp.where(fail_armijo, s.lo, a)
            new_philo = jnp.where(fail_armijo, s.phi_lo, phi)
            new_dphilo = jnp.where(fail_armijo, s.dphi_lo, dphi)
            new_hi = jnp.where(fail_armijo, a, jnp.where(flip_hi, s.lo, s.hi))
            new_phihi = jnp.where(
                fail_armijo, phi, jnp.where(flip_hi, s.phi_lo, s.phi_hi)
            )
            new_dphihi = jnp.where(
                fail_armijo, dphi, jnp.where(flip_hi, s.dphi_lo, s.dphi_hi)
            )

            interval = jnp.abs(new_hi - new_lo)
            tiny = interval <= 1e-12 * jnp.maximum(1.0, jnp.abs(new_lo))
            trial = _cubic_min(
                new_lo, new_philo, new_dphilo, new_hi, new_phihi, new_dphihi
            )
            mode = jnp.where(
                accept, _DONE, jnp.where(tiny, _FAILED, _ZOOM)
            ).astype(jnp.int32)
            return s._replace(
                mode=mode,
                alpha=trial,
                lo=new_lo,
                phi_lo=new_philo,
                dphi_lo=new_dphilo,
                hi=new_hi,
                phi_hi=new_phihi,
                dphi_hi=new_dphihi,
                best_alpha=jnp.where(accept, a, s.best_alpha),
                best_phi=jnp.where(accept, phi, s.best_phi),
                best_dphi=jnp.where(accept, dphi, s.best_dphi),
                evals=evals,
            )

        return lax.cond(s.mode == _BRACKET, bracket_step, zoom_step, s)

    final = lax.while_loop(cond, body, init)

    found = final.mode == _DONE
    # Exhaustion/failed fallback: best sufficient-decrease trial seen anywhere
    # (bracket growth or zoom), Armijo-only acceptance.
    usable = (~found) & (final.armijo_alpha > 0.0) & (final.armijo_phi < phi0)
    alpha = jnp.where(found, final.best_alpha, jnp.where(usable, final.armijo_alpha, 0.0))
    phi = jnp.where(found, final.best_phi, jnp.where(usable, final.armijo_phi, phi0))
    dphi = jnp.where(
        found, final.best_dphi, jnp.where(usable, final.armijo_dphi, dphi0)
    )
    failed = ~(found | usable)
    return LineSearchResult(
        alpha=alpha, phi=phi, dphi=dphi, failed=failed, num_evals=final.evals
    )


def backtracking(
    value_fn: Callable[[Array], Array],
    full_value0: Array,
    sufficient_decrease_fn: Callable[[Array, Array], Array],
    step_fn: Callable[[Array], Array],
    init_step: Array | float = 1.0,
    shrink: float = 0.5,
    max_evals: int = 25,
) -> tuple[Array, Array, Array]:
    """Generic backtracking search used by OWLQN's orthant-projected step.

    ``step_fn(alpha) -> w_candidate`` builds the (projected) candidate,
    ``value_fn(w)`` evaluates the full (regularized) objective, and
    ``sufficient_decrease_fn(alpha, value)`` decides acceptance.
    Returns (alpha, value, failed).
    """

    def cond(s):
        alpha, value, evals, done = s
        return (~done) & (evals < max_evals)

    def body(s):
        alpha, _, evals, _ = s
        v = value_fn(step_fn(alpha))
        ok = sufficient_decrease_fn(alpha, v)
        return (
            jnp.where(ok, alpha, alpha * shrink),
            v,
            evals + 1,
            ok,
        )

    alpha0 = jnp.asarray(init_step, dtype=full_value0.dtype)
    alpha, value, evals, done = lax.while_loop(
        cond, body, (alpha0, full_value0, jnp.int32(0), jnp.bool_(False))
    )
    return jnp.where(done, alpha, 0.0), jnp.where(done, value, full_value0), ~done
