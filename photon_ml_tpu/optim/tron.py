"""TRON: trust-region Newton method with truncated conjugate-gradient inner
solver, as a jit-compiled ``lax.while_loop`` pair — the TPU-native port of
the LIBLINEAR algorithm the reference uses (photon-lib
optimization/TRON.scala:153-341; Lin & Moré / Hsia et al.).

Behavior parity with the reference:
  - constants (eta0, eta1, eta2) = (1e-4, 0.25, 0.75),
    (sigma1, sigma2, sigma3) = (0.25, 0.5, 4.0)  (TRON.scala:102-103)
  - initial trust region delta = ||g0||            (TRON.scala init)
  - CG: max 20 iterations, stop at ||r|| <= 0.1*||g||, boundary handling
    per eq. (13)                                   (TRON.scala:280-341)
  - on first outer iteration delta = min(delta, ||step||)
  - improvement-failure retry: up to 5 shrink-and-retry attempts per
    iteration before giving up                     (TRON.scala:165-255)
  - defaults maxIter=15, tolerance=1e-5            (TRON.scala:259-264)

Each CG step costs one Hessian-vector product = one fused pass over the
(sharded) data; on a mesh it psums like the gradient, so the whole outer
loop stays on device.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (
    NOT_CONVERGED,
    OBJECTIVE_NOT_IMPROVING,
    BoxConstraints,
    Objective,
    SolveResult,
    convergence_reason,
    project_or_identity,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class TRONConfig:
    max_iterations: int = 15
    tolerance: float = 1e-5
    max_cg_iterations: int = 20
    cg_tolerance_factor: float = 0.1  # CG stops at ||r|| <= factor * ||g||
    max_improvement_failures: int = 5
    eta0: float = 1e-4
    eta1: float = 0.25
    eta2: float = 0.75
    sigma1: float = 0.25
    sigma2: float = 0.5
    sigma3: float = 4.0


class _CGState(NamedTuple):
    step: Array
    residual: Array
    direction: Array
    rtr: Array
    iteration: Array
    done: Array


def _truncated_cg(
    hvp, gradient: Array, delta: Array, config: TRONConfig
) -> tuple[Array, Array, Array]:
    """Solve H step = -gradient approximately within ||step|| <= delta.

    Returns (cg_iterations, step, residual). Mirrors
    TRON.truncatedConjugateGradientMethod (TRON.scala:280-341).
    """
    tol = config.cg_tolerance_factor * jnp.linalg.norm(gradient)

    r0 = -gradient
    init = _CGState(
        step=jnp.zeros_like(gradient),
        residual=r0,
        direction=r0,
        rtr=jnp.dot(r0, r0),
        iteration=jnp.int32(0),
        done=jnp.bool_(False),
    )

    def cond(s: _CGState):
        return (~s.done) & (s.iteration < config.max_cg_iterations)

    def body(s: _CGState) -> _CGState:
        converged = jnp.linalg.norm(s.residual) <= tol

        def advance(s: _CGState) -> _CGState:
            hd = hvp(s.direction)
            dhd = jnp.dot(s.direction, hd)
            alpha = s.rtr / jnp.where(dhd != 0.0, dhd, 1.0)
            step_try = s.step + alpha * s.direction
            outside = jnp.linalg.norm(step_try) > delta

            # boundary case: solve ||step + alpha*d|| = delta (eq. 13)
            std = jnp.dot(s.step, s.direction)
            sts = jnp.dot(s.step, s.step)
            dtd = jnp.dot(s.direction, s.direction)
            dsq = delta * delta
            rad = jnp.sqrt(jnp.maximum(std * std + dtd * (dsq - sts), 0.0))
            alpha_b = jnp.where(
                std >= 0.0,
                (dsq - sts) / jnp.where(std + rad != 0.0, std + rad, 1.0),
                (rad - std) / jnp.where(dtd != 0.0, dtd, 1.0),
            )

            alpha_eff = jnp.where(outside, alpha_b, alpha)
            new_step = s.step + alpha_eff * s.direction
            new_residual = s.residual - alpha_eff * hd
            new_rtr = jnp.dot(new_residual, new_residual)
            beta = new_rtr / jnp.where(s.rtr != 0.0, s.rtr, 1.0)
            new_direction = new_residual + beta * s.direction
            return _CGState(
                step=new_step,
                residual=new_residual,
                direction=jnp.where(outside, s.direction, new_direction),
                rtr=new_rtr,
                iteration=s.iteration + 1,
                done=outside,
            )

        return lax.cond(converged, lambda s: s._replace(done=True), advance, s)

    final = lax.while_loop(cond, body, init)
    return final.iteration, final.step, final.residual


class _TRONState(NamedTuple):
    w: Array
    value: Array
    grad: Array
    prev_value: Array
    delta: Array
    iteration: Array
    failures: Array  # consecutive improvement failures within this iteration
    reason: Array
    values: Array
    grad_norms: Array
    z: Array  # carried margins X'@w (margin-carrying fast path; else [0])
    passes: Array  # i32 cumulative full data passes (value+grad and CG Hv)


def tron_solve(
    objective: Objective,
    w0: Array,
    config: TRONConfig = TRONConfig(),
    constraints: Optional[BoxConstraints] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
) -> SolveResult:
    """Minimize a twice-differentiable objective (requires ``objective.hvp``)."""
    if objective.hvp is None:
        raise ValueError("TRON requires an objective with a Hessian-vector product")
    dtype = w0.dtype

    w0 = project_or_identity(constraints, w0)
    # margin-carrying fast path: z = X'@w is fixed across a whole truncated-
    # CG inner loop, so each Hv product needs one gather + one scatter
    # (hvp_at) instead of the fused two-gather+scatter sweep; the trial
    # point advances z linearly (z + X'@step). Projection breaks linearity,
    # so box-constrained solves keep the standard path.
    use_z = (
        constraints is None
        and objective.margins is not None
        and objective.dir_margins is not None
        and objective.curvature is not None
        and objective.hvp_at is not None
        and objective.value_and_grad_at is not None
    )
    if use_z:
        z0 = objective.margins(w0)
        f0, g0 = objective.value_and_grad_at(w0, z0)
    else:
        z0 = jnp.zeros((0,), dtype)
        f0, g0 = objective.value_and_grad(w0)
    g0n = jnp.linalg.norm(g0)
    anchor_f = f0 if init_value is None else jnp.asarray(init_value, dtype)
    anchor_gn = g0n if init_grad_norm is None else jnp.asarray(init_grad_norm, dtype)

    nvals = config.max_iterations + 1
    values = jnp.full((nvals,), jnp.inf, dtype=dtype).at[0].set(f0)
    gnorms = jnp.full((nvals,), jnp.inf, dtype=dtype).at[0].set(g0n)

    init = _TRONState(
        w=w0,
        value=f0,
        grad=g0,
        prev_value=f0,
        delta=g0n,
        iteration=jnp.int32(0),
        failures=jnp.int32(0),
        reason=jnp.int32(NOT_CONVERGED),
        values=values,
        grad_norms=gnorms,
        z=z0,
        passes=jnp.int32(1),  # the init value_and_grad evaluation
    )

    def cond(s: _TRONState):
        return s.reason == NOT_CONVERGED

    def body(s: _TRONState) -> _TRONState:
        if use_z:
            d2 = objective.curvature(s.z)  # loop-invariant across the CG solve
            hvp = lambda v: objective.hvp_at(d2, v)
        else:
            hvp = lambda v: objective.hvp(s.w, v)
        cg_its, step, residual = _truncated_cg(hvp, s.grad, s.delta, config)

        w_try = s.w + step
        gs = jnp.dot(s.grad, step)
        predicted = -0.5 * (gs - jnp.dot(step, residual))
        if use_z:
            z_try = s.z + objective.dir_margins(step)
            f_try, g_try = objective.value_and_grad_at(w_try, z_try)
        else:
            z_try = s.z
            f_try, g_try = objective.value_and_grad(w_try)
        actual = s.value - f_try
        step_norm = jnp.linalg.norm(step)

        # First-iteration adjustment of the initial step bound
        delta = jnp.where(
            s.iteration == 0, jnp.minimum(s.delta, step_norm), s.delta
        )

        denom = f_try - s.value - gs
        alpha = jnp.where(
            denom <= 0.0,
            config.sigma3,
            jnp.maximum(
                config.sigma1, -0.5 * (gs / jnp.where(denom != 0.0, denom, 1.0))
            ),
        )

        # trust-region radius update (TRON.scala:205-218)
        a_s = alpha * step_norm
        delta = jnp.where(
            actual < config.eta0 * predicted,
            jnp.minimum(jnp.maximum(alpha, config.sigma1) * step_norm,
                        config.sigma2 * delta),
            jnp.where(
                actual < config.eta1 * predicted,
                jnp.maximum(config.sigma1 * delta,
                            jnp.minimum(a_s, config.sigma2 * delta)),
                jnp.where(
                    actual < config.eta2 * predicted,
                    jnp.maximum(config.sigma1 * delta,
                                jnp.minimum(a_s, config.sigma3 * delta)),
                    jnp.maximum(delta, jnp.minimum(a_s, config.sigma3 * delta)),
                ),
            ),
        )

        improved = actual > config.eta0 * predicted
        w_new = project_or_identity(constraints, w_try)

        it = jnp.where(improved, s.iteration + 1, s.iteration)
        failures = jnp.where(improved, 0, s.failures + 1)
        gave_up = (~improved) & (failures >= config.max_improvement_failures)

        value_new = jnp.where(improved, f_try, s.value)
        reason_on_accept = convergence_reason(
            it,
            f_try,
            s.value,
            jnp.linalg.norm(g_try),
            anchor_f,
            anchor_gn,
            config.max_iterations,
            config.tolerance,
            jnp.bool_(False),
        )
        reason = jnp.where(
            improved,
            reason_on_accept,
            jnp.where(gave_up, OBJECTIVE_NOT_IMPROVING, NOT_CONVERGED),
        ).astype(jnp.int32)

        nxt = _TRONState(
            w=jnp.where(improved, w_new, s.w),
            value=value_new,
            grad=jnp.where(improved, g_try, s.grad),
            prev_value=jnp.where(improved, s.value, s.prev_value),
            delta=delta,
            iteration=it,
            failures=failures,
            reason=reason,
            # each CG step is one Hv data pass, plus this iteration's
            # trial-point value_and_grad (lower-bounding CG as 1 when the
            # trust region truncated it immediately)
            passes=s.passes + jnp.maximum(cg_its, 1).astype(jnp.int32) + 1,
            z=jnp.where(improved, z_try, s.z),
            values=jnp.where(
                improved, s.values.at[it].set(f_try), s.values
            ),
            grad_norms=jnp.where(
                improved,
                s.grad_norms.at[it].set(jnp.linalg.norm(g_try)),
                s.grad_norms,
            ),
        )
        return jax.tree.map(
            lambda a, b: jnp.where(s.reason == NOT_CONVERGED, b, a), s, nxt
        )

    final = lax.while_loop(cond, body, init)
    return SolveResult(
        w=final.w,
        value=final.value,
        grad=final.grad,
        iterations=final.iteration,
        reason=final.reason,
        values=final.values,
        grad_norms=final.grad_norms,
        data_passes=final.passes,
    )
