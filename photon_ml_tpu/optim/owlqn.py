"""OWL-QN (Orthant-Wise Limited-memory Quasi-Newton) for L1 / elastic-net
regularized objectives, as a jit-compiled ``lax.while_loop``.

Reference behavior target: photon-lib optimization/OWLQN.scala:44-91 (which
wraps breeze.optimize.OWLQN). Algorithm: Andrew & Gao 2007. The L1 term is
handled by a pseudo-gradient + orthant-projected backtracking line search;
the LBFGS history is built from raw (smooth-part) gradients. The
``l1_weight`` is a traced leaf so warm-started lambda sweeps reuse one
compiled program (the reference mutates l1RegularizationWeight for the same
purpose, OWLQN.scala:56-63).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (
    NOT_CONVERGED,
    BoxConstraints,
    Objective,
    SolveResult,
    convergence_reason,
    project_or_identity,
)
from photon_ml_tpu.optim.lbfgs import LBFGSConfig, two_loop_direction, update_history
from photon_ml_tpu.optim.linesearch import backtracking

Array = jax.Array


def pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """Sub-gradient of f(w) + l1*|w| used as OWL-QN's steepest-descent proxy."""
    right = g + l1  # derivative approaching from the positive side
    left = g - l1  # from the negative side
    at_zero = jnp.where(right < 0.0, right, jnp.where(left > 0.0, left, 0.0))
    return jnp.where(w > 0.0, right, jnp.where(w < 0.0, left, at_zero))


class _OWLQNState(NamedTuple):
    w: Array
    value: Array  # full F = f + l1*|w|_1
    grad: Array  # raw smooth gradient
    pseudo: Array
    prev_value: Array
    S: Array
    Y: Array
    rho: Array
    head: Array
    n_hist: Array
    gamma: Array
    iteration: Array
    reason: Array
    values: Array
    grad_norms: Array


def owlqn_solve(
    objective: Objective,
    w0: Array,
    l1_weight: Array | float,
    config: LBFGSConfig = LBFGSConfig(),
    constraints: Optional[BoxConstraints] = None,
    init_value: Optional[Array] = None,
    init_grad_norm: Optional[Array] = None,
) -> SolveResult:
    """Minimize f(w) + l1_weight * ||w||_1.

    ``l1_weight`` may be a scalar or a per-coefficient vector (e.g. to
    exempt an intercept). The smooth part f comes from the objective adapter
    (which already includes any L2 term — elastic net = L2 in objective +
    l1 here, matching RegularizationContext.ELASTIC_NET splitting
    lambda into alpha*lambda L1 + (1-alpha)*lambda L2).
    """
    m, d = config.history, w0.shape[0]
    dtype = w0.dtype
    l1 = jnp.broadcast_to(jnp.asarray(l1_weight, dtype), (d,))

    w0 = project_or_identity(constraints, w0)
    f0, g0 = objective.value_and_grad(w0)
    F0 = f0 + jnp.sum(l1 * jnp.abs(w0))
    pg0 = pseudo_gradient(w0, g0, l1)

    anchor_f = F0 if init_value is None else jnp.asarray(init_value, dtype)
    anchor_gn = (
        jnp.linalg.norm(pg0)
        if init_grad_norm is None
        else jnp.asarray(init_grad_norm, dtype)
    )

    nvals = config.max_iterations + 1
    values = jnp.full((nvals,), jnp.inf, dtype=dtype).at[0].set(F0)
    gnorms = jnp.full((nvals,), jnp.inf, dtype=dtype).at[0].set(jnp.linalg.norm(pg0))

    init = _OWLQNState(
        w=w0,
        value=F0,
        grad=g0,
        pseudo=pg0,
        prev_value=F0,
        S=jnp.zeros((m, d), dtype=dtype),
        Y=jnp.zeros((m, d), dtype=dtype),
        rho=jnp.zeros((m,), dtype=dtype),
        head=jnp.int32(0),
        n_hist=jnp.int32(0),
        gamma=jnp.asarray(1.0, dtype),
        iteration=jnp.int32(0),
        reason=jnp.int32(NOT_CONVERGED),
        values=values,
        grad_norms=gnorms,
    )

    def cond(s: _OWLQNState):
        return s.reason == NOT_CONVERGED

    def body(s: _OWLQNState) -> _OWLQNState:
        v = -s.pseudo  # steepest descent direction on F
        p = -two_loop_direction(s.pseudo, s.S, s.Y, s.rho, s.head, s.n_hist, s.gamma)
        # orthant alignment: zero coordinates where p disagrees with -pseudo
        p = jnp.where(p * v > 0.0, p, 0.0)
        # fall back to steepest descent if projection annihilated p
        degenerate = jnp.dot(p, p) <= 0.0
        p = jnp.where(degenerate, v, p)

        # orthant signs: sign(w) where nonzero, else sign of -pseudo
        xi = jnp.where(s.w != 0.0, jnp.sign(s.w), jnp.sign(v))

        def candidate(alpha):
            stepped = s.w + alpha * p
            proj = jnp.where(stepped * xi > 0.0, stepped, 0.0)
            return project_or_identity(constraints, proj)

        def full_value(w_c):
            return objective.value(w_c) + jnp.sum(l1 * jnp.abs(w_c))

        c1 = config.c1

        def sufficient(alpha, val):
            w_c = candidate(alpha)
            # Armijo on F via pseudo-gradient: F(w_c) <= F(w) + c1 * pg.(w_c - w)
            return val <= s.value + c1 * jnp.dot(s.pseudo, w_c - s.w)

        first = s.n_hist == 0
        pgn = jnp.linalg.norm(s.pseudo)
        init_step = jnp.where(
            first, jnp.minimum(1.0, 1.0 / jnp.maximum(pgn, 1e-12)), 1.0
        ).astype(dtype)

        alpha, F_new, failed = backtracking(
            full_value,
            s.value,
            sufficient,
            candidate,
            init_step=init_step,
            max_evals=config.max_ls_evals,
        )
        w_new = candidate(alpha)
        f_new, g_new = objective.value_and_grad(w_new)
        F_new = f_new + jnp.sum(l1 * jnp.abs(w_new))
        pg_new = pseudo_gradient(w_new, g_new, l1)

        S, Y, rho, head, n_hist, gamma = update_history(
            s.S, s.Y, s.rho, s.head, s.n_hist, s.gamma,
            w_new - s.w, g_new - s.grad, config.min_curvature,
        )

        it = s.iteration + 1
        reason = convergence_reason(
            it,
            F_new,
            s.value,
            jnp.linalg.norm(pg_new),
            anchor_f,
            anchor_gn,
            config.max_iterations,
            config.tolerance,
            failed,
        )
        nxt = _OWLQNState(
            w=w_new,
            value=F_new,
            grad=g_new,
            pseudo=pg_new,
            prev_value=s.value,
            S=S, Y=Y, rho=rho, head=head, n_hist=n_hist, gamma=gamma,
            iteration=it,
            reason=reason,
            values=s.values.at[it].set(F_new),
            grad_norms=s.grad_norms.at[it].set(jnp.linalg.norm(pg_new)),
        )
        return jax.tree.map(
            lambda a, b: jnp.where(s.reason == NOT_CONVERGED, b, a), s, nxt
        )

    final = lax.while_loop(cond, body, init)
    return SolveResult(
        w=final.w,
        value=final.value,
        grad=final.pseudo,
        iterations=final.iteration,
        reason=final.reason,
        values=final.values,
        grad_norms=final.grad_norms,
        data_passes=final.iteration + 1,
    )
