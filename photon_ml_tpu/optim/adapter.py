"""Bridge from :class:`GLMObjective` to the optimizer :class:`Objective`
adapter, including the margin-space fast line search.

Along a search direction p, GLM margins are affine: z(a) = z + a*u with
u = X' @ p precomputed once per line search. Each Wolfe trial then costs
O(n) elementwise work instead of a full gather/scatter pass over the nnz —
something the Spark reference cannot express (every Breeze line-search trial
there is a full treeAggregate over the cluster; SURVEY.md §3.4).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.sparse import SparseBatch
from photon_ml_tpu.optim.common import Objective

Array = jax.Array


class _LSCarry(NamedTuple):
    z: Array  # margins at w
    u: Array  # directional margins X' @ p
    w: Array
    p: Array
    ww: Array  # w.w
    wp: Array  # w.p
    pp: Array  # p.p


def glm_adapter(
    obj: GLMObjective,
    batch: SparseBatch,
    axis_name: str | None = None,
    row_sharding=None,
) -> Objective:
    """Build the optimizer-facing adapter for a GLM objective over a batch.

    The returned closures capture ``obj`` and ``batch``; under jit they are
    traced with whatever sharding the batch carries, so the same adapter
    serves single-device, vmapped (per-entity) and mesh-sharded training.

    Two mesh modes:
      - GSPMD (the product path, parallel.distributed.gspmd_solve):
        ``row_sharding`` pins the margin-space arrays (z, the directional
        margins u) to the batch rows' ``NamedSharding(mesh, P("batch"))``
        so the compiler keeps every per-row intermediate distributed and
        inserts psums only at the data sums — the treeAggregate ->
        psum-over-ICI mapping of PAPER.md with zero hand-rolled SPMD.
      - explicit SPMD (legacy shard_map callers): ``axis_name`` set means
        the batch is the LOCAL row shard and all data sums are psum'd —
        including the line search's per-trial phi/dphi, one scalar-pair
        all-reduce over ICI per trial.
    """
    loss = obj.loss

    def psum(x):
        return x if axis_name is None else jax.lax.psum(x, axis_name)

    def rows(x):
        # margin-space arrays carry the batch-axis sharding; a missing
        # constraint lets GSPMD replicate [n]-sized intermediates, which
        # is exactly the silent-replication bug class this removes
        if row_sharding is None:
            return x
        return jax.lax.with_sharding_constraint(x, row_sharding)

    def value_and_grad(w):
        return obj.value_and_grad(w, batch, axis_name)

    def value(w):
        return obj.value(w, batch, axis_name)

    def ls_prepare(w, p):
        # TiledBatch shares one pass over the nnz slots for both gathers;
        # SparseBatch composes margins + dot_rows.
        p_eff, p_shift = obj._effective(p)
        w_eff, w_shift = obj._effective(w)
        z, u = batch.margins_pair(w_eff, w_shift, p_eff, p_shift)
        z, u = rows(z), rows(u)
        return _LSCarry(
            z=z,
            u=u,
            w=w,
            p=p,
            ww=jnp.dot(w, w),
            wp=jnp.dot(w, p),
            pp=jnp.dot(p, p),
        )

    def ls_eval(carry: _LSCarry, alpha):
        z_a = carry.z + alpha * carry.u
        l, dz = loss.loss_and_dz(z_a, batch.labels)
        l2 = obj.l2_weight.astype(z_a.dtype)
        data_sums = psum(
            jnp.stack(
                [jnp.sum(batch.weights * l), jnp.sum(batch.weights * dz * carry.u)]
            )
        )
        phi = data_sums[0] + 0.5 * l2 * (
            carry.ww + 2.0 * alpha * carry.wp + alpha * alpha * carry.pp
        )
        dphi = data_sums[1] + l2 * (carry.wp + alpha * carry.pp)
        return phi, dphi

    hvp = None
    if loss.has_hessian:
        def hvp(w, v):
            return obj.hessian_vector(w, v, batch, axis_name)

    # margin-carrying protocol: z is threaded through the LBFGS loop so each
    # iteration does one gather (u = X'@p) + one scatter (gradient) instead
    # of two fused gather+scatter sweeps
    def margins(w):
        return rows(obj.margins(w, batch))

    def ls_prepare_z(z, w, p):
        u = dir_margins(p)
        return _LSCarry(
            z=z,
            u=u,
            w=w,
            p=p,
            ww=jnp.dot(w, w),
            wp=jnp.dot(w, p),
            pp=jnp.dot(p, p),
        )

    def ls_advance(carry: _LSCarry, alpha):
        return carry.z + alpha * carry.u

    def value_and_grad_at(w, z):
        return obj.value_and_grad_at_margins(w, z, batch, axis_name)

    def dir_margins(p):
        p_eff, p_shift = obj._effective(p)
        return rows(batch.dot_rows(p_eff) + p_shift)

    hessian = None
    if loss.has_hessian and hasattr(batch, "dense_rows"):
        def hessian(w):
            return obj.dense_hessian(w, batch, axis_name)

    curvature = None
    hvp_at = None
    if loss.has_hessian:
        def curvature(z):
            return obj.curvature_at_margins(z, batch)

        def hvp_at(d2, v):
            return obj.hessian_vector_with_curvature(d2, v, batch, axis_name)

    return Objective(
        value_and_grad=value_and_grad,
        value=value,
        ls_prepare=ls_prepare,
        ls_eval=ls_eval,
        hvp=hvp,
        margins=margins,
        ls_prepare_z=ls_prepare_z,
        ls_advance=ls_advance,
        value_and_grad_at=value_and_grad_at,
        dir_margins=dir_margins,
        curvature=curvature,
        hvp_at=hvp_at,
        hessian=hessian,
    )
