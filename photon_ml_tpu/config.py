"""JSON config parsing: the parse-side of the typed config system.

Reference analog: photon-client's scopt flag parsers — GameParams
(estimators/GameParams.scala:252-492) with its per-coordinate mini-DSL
strings, and the legacy PhotonMLCmdLineParser. One JSON document replaces
both (SURVEY.md §5 "Config / flag system"): it names the input data, the
coordinates (updating-sequence order preserved from the JSON object order),
their optimizers, evaluators, and output. `game_config_to_json` inverts the
parse so saved model metadata can be re-parsed into a runnable config.
"""

from __future__ import annotations

import json
from typing import Mapping, Optional

from photon_ml_tpu.game.estimator import (
    FactoredRandomEffectConfig,
    FixedEffectConfig,
    GameConfig,
    RandomEffectConfig,
    _config_metadata,
)
from photon_ml_tpu.optim.factory import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)


_REG_TYPE_ALIASES = {
    "none": "none",
    "l1": "l1",
    "l2": "l2",
    "elastic_net": "elastic_net",
    "elasticnet": "elastic_net",
}


def parse_optimizer_config_string(spec: str) -> OptimizerConfig:
    """Parse the reference's comma-separated optimizer mini-DSL:
    ``maxIter,tolerance,regWeight,downSamplingRate,optimizerType,regType
    [,alpha]`` (GLMOptimizationConfiguration.parseAndBuildFromString:87-110;
    the trailing alpha extends it for elastic net)."""
    parts = [p.strip() for p in spec.split(",")]
    if len(parts) not in (6, 7):
        raise ValueError(
            f"bad optimizer config string '{spec}': expected "
            "'maxIter,tol,lambda,downSamplingRate,optimizerType,"
            "regularizationType[,alpha]'"
        )
    max_iter, tol, lam, ds_rate = parts[0], parts[1], parts[2], parts[3]
    try:
        opt_type = OptimizerType(parts[4].lower())
    except ValueError:
        raise ValueError(f"unknown optimizer type '{parts[4]}'") from None
    reg_name = parts[5].lower()
    if reg_name not in _REG_TYPE_ALIASES:
        raise ValueError(f"unknown regularization type '{parts[5]}'")
    reg_type = RegularizationType(_REG_TYPE_ALIASES[reg_name])
    if len(parts) == 7 and reg_type != RegularizationType.ELASTIC_NET:
        raise ValueError(
            f"alpha ('{parts[6]}') only applies to elastic_net, not "
            f"'{parts[5]}'"
        )
    alpha = float(parts[6]) if len(parts) == 7 else 1.0
    return OptimizerConfig(
        optimizer_type=opt_type,
        max_iterations=int(max_iter),
        tolerance=float(tol),
        regularization=RegularizationContext(reg_type, alpha=alpha),
        regularization_weight=float(lam),
        down_sampling_rate=float(ds_rate),
    )


def parse_optimizer_config(obj: Optional[Mapping | str]) -> OptimizerConfig:
    """Parse the JSON optimizer spec (GLMOptimizationConfiguration analog);
    a plain string routes through the reference's comma-separated DSL."""
    if isinstance(obj, str):
        return parse_optimizer_config_string(obj)
    obj = dict(obj or {})
    reg_type = RegularizationType(obj.pop("regularization", "none"))
    reg = RegularizationContext(reg_type, alpha=float(obj.pop("alpha", 1.0)))

    def parse_constraints(v):
        # [[index, lower|null, upper|null], ...] (constraintMap analog)
        out = []
        for triple in v:
            idx, lo, hi = triple
            out.append((
                int(idx),
                float("-inf") if lo is None else float(lo),
                float("inf") if hi is None else float(hi),
            ))
        return tuple(out) or None

    known = {
        "type": ("optimizer_type", lambda v: OptimizerType(v)),
        "max_iterations": ("max_iterations", int),
        "tolerance": ("tolerance", float),
        "regularization_weight": ("regularization_weight", float),
        "lbfgs_history": ("lbfgs_history", int),
        "down_sampling_rate": ("down_sampling_rate", float),
        "box_constraints": ("box_constraints", parse_constraints),
    }
    kwargs = {}
    for key, (field, conv) in known.items():
        if key in obj:
            kwargs[field] = conv(obj.pop(key))
    if obj:
        raise ValueError(f"unknown optimizer config keys: {sorted(obj)}")
    return OptimizerConfig(regularization=reg, **kwargs)


def parse_coordinate_config(obj: Mapping):
    obj = dict(obj)
    ctype = obj.pop("type", "fixed_effect")
    if ctype == "fixed_effect":
        out = FixedEffectConfig(
            shard_name=obj.pop("shard_name"),
            optimizer=parse_optimizer_config(obj.pop("optimizer", None)),
            normalization=obj.pop("normalization", "none"),
            intercept_index=obj.pop("intercept_index", None),
            down_sampling_seed=int(obj.pop("down_sampling_seed", 0)),
            layout=obj.pop("layout", "auto"),
        )
    elif ctype == "random_effect":
        ratio = obj.pop("features_to_samples_ratio", None)
        out = RandomEffectConfig(
            shard_name=obj.pop("shard_name"),
            id_name=obj.pop("id_name"),
            optimizer=parse_optimizer_config(obj.pop("optimizer", None)),
            active_rows_per_entity=obj.pop("active_rows_per_entity", None),
            min_rows_per_entity=int(obj.pop("min_rows_per_entity", 1)),
            features_to_samples_ratio=None if ratio is None else float(ratio),
            projector=obj.pop("projector", "index_map"),
            projected_dim=obj.pop("projected_dim", None),
            projection_seed=int(obj.pop("projection_seed", 0)),
            projection_intercept_index=obj.pop("projection_intercept_index", None),
            compute_variances=bool(obj.pop("compute_variances", False)),
        )
    elif ctype == "factored_random_effect":
        out = FactoredRandomEffectConfig(
            shard_name=obj.pop("shard_name"),
            id_name=obj.pop("id_name"),
            latent_dim=int(obj.pop("latent_dim")),
            mf_iterations=int(obj.pop("mf_iterations", 1)),
            re_optimizer=parse_optimizer_config(obj.pop("optimizer", None)),
            latent_optimizer=parse_optimizer_config(
                obj.pop("latent_optimizer", None)
            ),
            active_rows_per_entity=obj.pop("active_rows_per_entity", None),
            min_rows_per_entity=int(obj.pop("min_rows_per_entity", 1)),
            seed=int(obj.pop("seed", 0)),
        )
    else:
        raise ValueError(f"unknown coordinate type '{ctype}'")
    if obj:  # typos must not silently train with defaults
        raise ValueError(
            f"unknown keys in {ctype} coordinate config: {sorted(obj)}"
        )
    return out


def parse_game_config(obj: Mapping | str) -> GameConfig:
    """Parse a GameConfig from a JSON document (dict or JSON string).

    JSON object order of "coordinates" IS the updating sequence."""
    if isinstance(obj, str):
        obj = json.loads(obj)
    coords = {
        name: parse_coordinate_config(c)
        for name, c in obj.get("coordinates", {}).items()
    }
    return GameConfig(
        task=obj["task"],
        coordinates=coords,
        num_iterations=int(obj.get("num_iterations", 1)),
        evaluators=tuple(obj.get("evaluators", ())),
    )


def game_config_to_json(config: GameConfig) -> dict:
    """Inverse of parse_game_config (round-trips through model metadata)."""
    return _config_metadata(config)
