from photon_ml_tpu.ops.losses import LOSSES, PointwiseLoss, get_loss  # noqa: F401
from photon_ml_tpu.ops.objective import GLMObjective, make_objective  # noqa: F401
from photon_ml_tpu.ops.sparse import SparseBatch, concat_batches  # noqa: F401
from photon_ml_tpu.ops.tiled import TiledBatch  # noqa: F401
