"""GLM objective: weighted loss value, gradient, Hessian-vector products and
Hessian diagonal over a :class:`SparseBatch`, with feature normalization
applied algebraically (never densifying) and optional L2 regularization.

This is the TPU-native replacement for the reference's aggregator trio
(photon-lib function/glm/{ValueAndGradient,HessianVector,HessianDiagonal}
Aggregator.scala) and the Distributed/SingleNode GLM loss functions
(photon-api function/glm/). Where the reference streams per-datum ``add``
calls inside ``treeAggregate``, here each quantity is a handful of fused
gather/segment-sum/scatter ops compiled by XLA; under a sharded mesh the
same code yields partial sums that are combined by ``psum``
(see photon_ml_tpu.parallel.distributed).

Normalization trick (ValueAndGradientAggregator.scala:35-79 analog): for
x' = (x - shift) * factor, margins and derivatives are computed against the
raw sparse x via
    z_i       = x_i . (w * factor) - (w * factor) . shift + offset_i
    grad      = factor * scatter(dz) - (factor * shift) * sum(dz)
and similarly for Hv and the Hessian diagonal, so sparsity is preserved.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.losses import PointwiseLoss, get_loss
from photon_ml_tpu.ops.sparse import SparseBatch

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GLMObjective:
    """Weighted GLM objective  F(w) = sum_i weight_i * l(z_i, y_i) + (l2/2)|w|^2.

    ``l2_weight`` is a traced leaf so lambda sweeps reuse one compiled
    program (the reference mutates l1/l2 weights for warm-started sweeps,
    DistributedOptimizationProblem.scala:60-71).

    ``factors``/``shifts`` implement normalization x' = (x - shift) * factor;
    ``None`` means identity. L1 is NOT part of this objective — it is handled
    by OWLQN's pseudo-gradient, mirroring the reference split.
    """

    loss_name: str = dataclasses.field(metadata=dict(static=True))
    l2_weight: Array = dataclasses.field(default_factory=lambda: jnp.float32(0.0))
    factors: Optional[Array] = None
    shifts: Optional[Array] = None

    @property
    def loss(self) -> PointwiseLoss:
        return get_loss(self.loss_name)

    # -- normalization algebra ----------------------------------------------

    def _effective(self, w: Array) -> tuple[Array, Array]:
        """(w * factor, margin shift constant -(w*factor).shifts)."""
        w_eff = w if self.factors is None else w * self.factors
        if self.shifts is None:
            shift = jnp.zeros((), dtype=w.dtype)
        else:
            shift = -jnp.dot(w_eff, self.shifts)
        return w_eff, shift

    def _back_transform_vec(self, raw: Array, row_total: Array) -> Array:
        """Map a raw feature-space scatter into normalized space:
        factor * raw - (factor * shift) * row_total."""
        out = raw if self.factors is None else raw * self.factors
        if self.shifts is not None:
            fs = self.shifts if self.factors is None else self.factors * self.shifts
            out = out - fs * row_total
        return out

    def margins(self, w: Array, batch: SparseBatch) -> Array:
        w_eff, shift = self._effective(w)
        return batch.margins(w_eff, shift)

    # -- value / gradient ----------------------------------------------------
    #
    # ``axis_name`` enables SPMD data parallelism: when the batch rows are a
    # local shard inside a shard_map over that mesh axis, the per-shard data
    # sums are psum'd over ICI while the regularization terms (functions of
    # the replicated coefficients) stay local. This is the treeAggregate
    # replacement (SURVEY.md §2.a row 1) — the optimizers run unchanged.

    @staticmethod
    def _psum(x, axis_name):
        return x if axis_name is None else jax.lax.psum(x, axis_name)

    def value_and_grad(
        self, w: Array, batch: SparseBatch, axis_name: Optional[str] = None
    ) -> tuple[Array, Array]:
        # One batch-layout-level sweep computes the weighted loss sum, the
        # raw gradient scatter, and sum(w*dz) (needed for the normalization
        # back-transform). TiledBatch fuses all three into one pallas pass.
        w_eff, shift = self._effective(w)
        data_value, raw_grad, row_total = batch.fused_value_grad(
            w_eff, shift, self.loss_name
        )
        value = self._psum(data_value, axis_name)
        grad = self._psum(
            self._back_transform_vec(raw_grad, row_total), axis_name
        )
        l2 = self.l2_weight.astype(w.dtype)
        value = value + 0.5 * l2 * jnp.dot(w, w)
        grad = grad + l2 * w
        return value, grad

    def value_and_grad_at_margins(
        self,
        w: Array,
        z: Array,
        batch: SparseBatch,
        axis_name: Optional[str] = None,
    ) -> tuple[Array, Array]:
        """value_and_grad with the margins z ALREADY known: skips the gather
        half of the fused sweep (one scatter pass). Math identical to
        value_and_grad — the margin-carrying LBFGS fast path."""
        l, dz = self.loss.loss_and_dz(z, batch.labels)
        wdz = batch.weights * dz
        data_value = jnp.sum(batch.weights * l)
        raw_grad = batch.scatter_features(wdz)
        row_total = jnp.sum(wdz)
        value = self._psum(data_value, axis_name)
        grad = self._psum(
            self._back_transform_vec(raw_grad, row_total), axis_name
        )
        l2 = self.l2_weight.astype(w.dtype)
        return value + 0.5 * l2 * jnp.dot(w, w), grad + l2 * w

    def value(
        self, w: Array, batch: SparseBatch, axis_name: Optional[str] = None
    ) -> Array:
        z = self.margins(w, batch)
        l = self.loss.loss(z, batch.labels)
        return self._psum(jnp.sum(batch.weights * l), axis_name) + 0.5 * (
            self.l2_weight.astype(w.dtype)
        ) * jnp.dot(w, w)

    def grad(
        self, w: Array, batch: SparseBatch, axis_name: Optional[str] = None
    ) -> Array:
        return self.value_and_grad(w, batch, axis_name)[1]

    # -- second-order --------------------------------------------------------

    def hessian_vector(
        self, w: Array, v: Array, batch: SparseBatch, axis_name: Optional[str] = None
    ) -> Array:
        """H(w) @ v  =  sum_i weight_i * l''(z_i) * (x'_i . v) * x'_i  + l2*v.

        One layout-level sweep (TiledBatch fuses gather z/u + scatter into a
        single pallas pass — TRON's truncated-CG hot op).
        """
        v_eff, v_shift = self._effective(v)
        w_eff, w_shift = self._effective(w)
        raw_hv, q_total = batch.fused_hessian_vector(
            w_eff, w_shift, v_eff, v_shift, self.loss_name
        )
        hv = self._psum(
            self._back_transform_vec(raw_hv, q_total), axis_name
        )
        return hv + self.l2_weight.astype(w.dtype) * v

    def curvature_at_margins(self, z: Array, batch: SparseBatch) -> Array:
        """Per-row curvature d2 = weight * l''(z) — loop-invariant across a
        TRON truncated-CG inner solve, so compute it ONCE per outer step."""
        return batch.weights * self.loss.d2z(z, batch.labels)

    def hessian_vector_with_curvature(
        self,
        d2: Array,
        v: Array,
        batch: SparseBatch,
        axis_name: Optional[str] = None,
    ) -> Array:
        """H(w) @ v with the per-row curvature d2 = weight*l''(z) ALREADY
        known: one gather (u = X'@v) + one scatter instead of the fused
        kernel's two gathers + scatter, and no per-call elementwise d2z
        pass. TRON's CG uses one fixed z/d2 for its whole inner loop."""
        v_eff, v_shift = self._effective(v)
        raw_hv, q_total = batch.fused_hv_at(d2, v_eff, v_shift)
        hv = self._psum(self._back_transform_vec(raw_hv, q_total), axis_name)
        return hv + self.l2_weight.astype(v.dtype) * v

    def hessian_diagonal(
        self, w: Array, batch: SparseBatch, axis_name: Optional[str] = None
    ) -> Array:
        """diag H(w)_j = sum_i weight_i l''(z_i) x'_ij^2 + l2."""
        z = self.margins(w, batch)
        d2_row = batch.weights * self.loss.d2z(z, batch.labels)
        raw_sq = batch.scatter_features_sq(d2_row)  # sum d2 * x^2
        if self.factors is None and self.shifts is None:
            diag = raw_sq
        else:
            f = (
                jnp.ones((batch.num_features,), dtype=w.dtype)
                if self.factors is None
                else self.factors
            )
            if self.shifts is None:
                diag = f * f * raw_sq
            else:
                raw_lin = batch.scatter_features(d2_row)  # sum d2 * x
                total = jnp.sum(d2_row)
                s = self.shifts
                diag = f * f * (raw_sq - 2.0 * s * raw_lin + s * s * total)
        return self._psum(diag, axis_name) + self.l2_weight.astype(w.dtype)

    def dense_hessian(
        self, w: Array, batch: SparseBatch, axis_name: Optional[str] = None
    ) -> Array:
        """Full H(w) = X'^T diag(wgt*l'') X' + l2 I as a dense [d, d] —
        the explicit-Hessian path for SMALL d (per-entity local spaces;
        batched Newton). Normalization materializes X' = (X - shift)*factor
        on the densified design."""
        z = self.margins(w, batch)
        d2 = batch.weights * self.loss.d2z(z, batch.labels)
        X = batch.dense_rows()
        if self.shifts is not None:
            X = X - self.shifts[None, :]
        if self.factors is not None:
            X = X * self.factors[None, :]
        H = (X * d2[:, None]).T @ X
        H = self._psum(H, axis_name)
        d = batch.num_features
        return H + self.l2_weight.astype(w.dtype) * jnp.eye(d, dtype=w.dtype)

    # -- plumbing ------------------------------------------------------------

    def with_l2(self, l2_weight) -> "GLMObjective":
        return dataclasses.replace(
            self, l2_weight=jnp.asarray(l2_weight, dtype=jnp.float32)
        )

    def with_normalization(self, factors, shifts) -> "GLMObjective":
        return dataclasses.replace(self, factors=factors, shifts=shifts)


def make_objective(
    loss: str | PointwiseLoss,
    l2_weight: float = 0.0,
    factors: Optional[Array] = None,
    shifts: Optional[Array] = None,
) -> GLMObjective:
    name = loss if isinstance(loss, str) else loss.name
    return GLMObjective(
        loss_name=get_loss(name).name,
        l2_weight=jnp.float32(l2_weight),
        factors=factors,
        shifts=shifts,
    )
