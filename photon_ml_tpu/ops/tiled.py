"""Tiled one-hot-matmul sparse layout: the TPU fast path for GLM passes.

The padded-COO :class:`~photon_ml_tpu.ops.sparse.SparseBatch` computes
margins/gradients with XLA gather/scatter, which on TPU is random-access
bound (~100-150M elem/s; PERF_NOTES.md). This module reaches HBM/MXU speed
instead by removing ALL random access:

  - Rows are grouped into tiles of R=128 consecutive rows. Each tile's nnz
    become a fixed-length slot list of (value, col_hi, col_lo, row_local)
    where ``col = col_hi * 128 + col_lo`` and ``row_local = row % 128``.
  - The coefficient vector lives as a [B, 128] grid (B = ceil(F/128)).
  - Gathering w[col] per slot = one-hot(col_hi) @ w2, then a masked
    product with one-hot(col_lo) reduced BY MATVEC against a ones vector.
  - Scattering per-slot contributions into feature space = the transposed
    one-hot matmul into a [128, B] accumulator (the [S, B] mask side is
    the smaller elementwise operand).
  - EVERY reduction and row broadcast rides the MXU: these kernels are
    VPU-bound (mask construction + elementwise chains saturate the vector
    unit while the MXU idles at ~3% — PERF_NOTES.md roofline), so lane
    shuffle-reduces and [S, 128] row-mask broadcasts are replaced by
    matmuls against the TRANSPOSED row one-hot mask_rT [R, S]. Measured:
    margins 75 -> 39 ms, fused value+grad 91 -> 62 ms (v5e, config below).
  - f32 exactness comes from bf16x2 splits (x = hi + lo in bfloat16,
    products against 0/1 masks are exact, MXU accumulates in f32). The
    split MUST happen inside the kernel: XLA's
    ``--xla_allow_excess_precision`` folds ``bf16(x - f32(bf16(x)))`` to
    zero, silently degrading the pass to single-bf16 (measured 2e-3
    gradient error; in-kernel split measures ~5e-6). Mosaic's
    precision=HIGHEST f32 matmul measures 5e-3 — not a substitute.

Measured on TPU v5e (1M rows x 10K features, 20 nnz/row): one fused
value+grad pass ~62 ms vs ~650 ms for the XLA gather/scatter path (~10x);
the margin-carrying LBFGS iteration is one dot_rows (~39 ms) plus one
scatter pass.

This replaces the hot loop the reference distributes over a Spark cluster
(ValueAndGradientAggregator.scala:132-153) with on-chip matmuls.

Skew note: the slot-list length S is the max nnz over tiles; heavily skewed
row lengths inflate padding. The layout builder reports waste; callers with
pathological rows should pre-shuffle rows (any order is fine — tiles are
independent).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.sparse import SparseBatch, validate_coo_indices

Array = jax.Array

LANE = 128
ROWS_PER_TILE = 128


def _interpret() -> bool:
    """Pallas TPU kernels run in interpret mode on non-TPU backends (tests)."""
    return jax.default_backend() != "tpu"


def _split_bf16(x):
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _mm2(a, bh, bl):
    """Exact a @ (bh + bl): bf16 one-hot x bf16x2 table, f32 accumulation."""
    x = jax.lax.dot_general(
        a, bh, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return x + jax.lax.dot_general(
        a, bl, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _mmT2(a, bh, bl):
    """Exact a^T @ (bh + bl) (contract slot dim 0)."""
    x = jax.lax.dot_general(
        a, bh, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return x + jax.lax.dot_general(
        a, bl, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _slot_contrib(vals, w_ref, mask_hi, mask_lo):
    """Per-slot vals_s * w[col_s] as an [S, 1] f32 column.

    All reductions ride the MXU: the lane pick + sum is a masked-product
    matvec against a ones vector instead of a 128-lane shuffle reduce
    (measured ~30% kernel time on v5e; the VPU is this kernel family's
    critically saturated unit — see PERF_NOTES roofline)."""
    w = w_ref[:]
    whi, wlo = _split_bf16(w)
    wrow = _mm2(mask_hi, whi, wlo)                    # [S, 128] f32
    e = (wrow * mask_lo) * vals[:, None]              # one lane nonzero
    eh, el = _split_bf16(e)
    ones = jnp.ones((LANE, 1), jnp.bfloat16)
    g = jax.lax.dot_general(
        eh, ones, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return g + jax.lax.dot_general(
        el, ones, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [S, 1]


def _rowsum_mxu(contrib_col, mask_rT):
    """[S, 1] per-slot contributions -> [1, R] per-row sums via the
    TRANSPOSED row one-hot ON THE MXU ([R,S] @ [S,1], bf16x2 exact).
    Both row ops use mask_rT so Mosaic sees only (1,0)-contractions."""
    ch, cl = _split_bf16(contrib_col)
    return _mm2(mask_rT, ch, cl).reshape(1, -1)       # [R, 1] -> [1, R]


def _row_margins(vals, mask_rT, w_ref, mask_hi, mask_lo):
    """Per-row margin sums [1, R] for one tile (shared kernel body)."""
    return _rowsum_mxu(_slot_contrib(vals, w_ref, mask_hi, mask_lo), mask_rT)


def _slots_of_rows(per_row, mask_rT):
    """Broadcast a [1, R] per-row vector to slots ([S, 1]) via the
    transposed row one-hot matvec (exact: per_row splits bf16x2)."""
    ph, plo = _split_bf16(per_row)
    s_row = jax.lax.dot_general(
        ph, mask_rT, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    s_row = s_row + jax.lax.dot_general(
        plo, mask_rT, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # [1, S]
    return s_row.reshape(-1, 1)


def _scatter_accum(out_ref, per_slot, mask_hi, mask_lo):
    """Accumulate sum_s per_slot[s]*onehot(col_s) into the TRANSPOSED
    [LANE, B] accumulator: tmp = per_slot ⊙ mask_hi is [S, B] (the smaller
    mask side), then mask_lo^T @ tmp on the MXU (bf16x2 exact)."""
    tmp = per_slot * mask_hi                          # [S, B]
    th, tl = _split_bf16(tmp)
    out_ref[:] = out_ref[:] + _mmT2(mask_lo, th, tl)  # [LANE, B]


def _masks(hi_ref, lo_ref, rlo_ref, S: int, B: int):
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (S, B), 1)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (S, LANE), 1)
    iota_r = jax.lax.broadcasted_iota(jnp.int32, (LANE, S), 0)
    mask_hi = (hi_ref[0, 0, :][:, None] == iota_b).astype(jnp.bfloat16)
    mask_lo = (lo_ref[0, 0, :][:, None] == iota_l).astype(jnp.bfloat16)
    # row one-hot in TRANSPOSED [R, S] orientation: every use is then a
    # standard (1,0) MXU contraction (Mosaic rejects dim-1 contractions)
    mask_rT = (rlo_ref[0, 0, :][None, :] == iota_r).astype(jnp.bfloat16)
    return mask_hi, mask_lo, mask_rT


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _margins_kernel(use_offsets: bool, pair: bool,
                    *refs):
    """z = per-row sum of vals * w[col] (+offsets +shift).

    With ``pair`` a second table v is gathered in the same sweep (shares all
    masks): used for (margins(w), dot_rows(p)) in one pass per LBFGS line
    search, and for (margins(w), dot_rows(v)) in Hessian-vector products.
    """
    if pair:
        (vals_ref, hi_ref, lo_ref, rlo_ref, off_ref, w_ref, v_ref,
         shift_ref, out_z_ref, out_u_ref) = refs
    else:
        (vals_ref, hi_ref, lo_ref, rlo_ref, off_ref, w_ref,
         shift_ref, out_z_ref) = refs
    S = vals_ref.shape[2]
    B = w_ref.shape[0]
    mask_hi, mask_lo, mask_rT = _masks(hi_ref, lo_ref, rlo_ref, S, B)
    vals = vals_ref[0, 0, :]

    z = _row_margins(vals, mask_rT, w_ref, mask_hi, mask_lo) + shift_ref[0, 0]
    if use_offsets:
        z = z + off_ref[0, :, :]
    out_z_ref[0, :, :] = z

    if pair:
        u = _row_margins(vals, mask_rT, v_ref, mask_hi, mask_lo)
        out_u_ref[0, :, :] = u + shift_ref[0, 1]


def _scatter_kernel(square: bool, *refs):
    """g = sum_i per_row[i] * x_i (or x_i^2): transposed one-hot matmul."""
    (vals_ref, hi_ref, lo_ref, rlo_ref, pr_ref, out_g_ref) = refs
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_g_ref[:] = jnp.zeros_like(out_g_ref)

    S = vals_ref.shape[2]
    B = out_g_ref.shape[1]
    mask_hi, mask_lo, mask_rT = _masks(hi_ref, lo_ref, rlo_ref, S, B)
    vals = vals_ref[0, 0, :]
    if square:
        vals = vals * vals

    per_slot = _slots_of_rows(pr_ref[0, :, :], mask_rT) * vals[:, None]
    _scatter_accum(out_g_ref, per_slot, mask_hi, mask_lo)


def _value_grad_kernel(loss_name: str, use_offsets: bool, *refs):
    """Fused weighted loss value + raw gradient scatter + sum(weights*dz)."""
    (vals_ref, hi_ref, lo_ref, rlo_ref, lab_ref, wgt_ref, off_ref,
     w_ref, shift_ref, out_s_ref, out_g_ref) = refs
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_s_ref[:] = jnp.zeros_like(out_s_ref)
        out_g_ref[:] = jnp.zeros_like(out_g_ref)

    S = vals_ref.shape[2]
    B = w_ref.shape[0]
    mask_hi, mask_lo, mask_rT = _masks(hi_ref, lo_ref, rlo_ref, S, B)
    vals = vals_ref[0, 0, :]

    z = _row_margins(vals, mask_rT, w_ref, mask_hi, mask_lo) + shift_ref[0, 0]
    if use_offsets:
        z = z + off_ref[0, :, :]

    loss = get_loss(loss_name)
    y = lab_ref[0, :, :]
    wgt = wgt_ref[0, :, :]
    l, dz = loss.loss_and_dz(z, y)
    g_row = wgt * dz                                   # [1, R]
    sums = jnp.stack([jnp.sum(wgt * l), jnp.sum(g_row)]).reshape(1, 2)
    out_s_ref[:] = out_s_ref[:] + sums

    per_slot = _slots_of_rows(g_row, mask_rT) * vals[:, None]
    _scatter_accum(out_g_ref, per_slot, mask_hi, mask_lo)


def _hv_kernel(loss_name: str, use_offsets: bool, *refs):
    """Fused Hessian-vector sweep: gather z = margins(w) and u = dot(v) from
    the same masks, form q = weight * l''(z) * u, scatter q into feature
    space and accumulate sum(q) — TRON's CG step in ONE data pass (the
    composed margins_pair + scatter path costs two)."""
    (vals_ref, hi_ref, lo_ref, rlo_ref, lab_ref, wgt_ref, off_ref,
     w_ref, v_ref, shift_ref, out_s_ref, out_g_ref) = refs
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_s_ref[:] = jnp.zeros_like(out_s_ref)
        out_g_ref[:] = jnp.zeros_like(out_g_ref)

    S = vals_ref.shape[2]
    B = w_ref.shape[0]
    mask_hi, mask_lo, mask_rT = _masks(hi_ref, lo_ref, rlo_ref, S, B)
    vals = vals_ref[0, 0, :]

    z = _row_margins(vals, mask_rT, w_ref, mask_hi, mask_lo) + shift_ref[0, 0]
    if use_offsets:
        z = z + off_ref[0, :, :]
    u = _row_margins(vals, mask_rT, v_ref, mask_hi, mask_lo) + shift_ref[0, 1]

    loss = get_loss(loss_name)
    q_row = wgt_ref[0, :, :] * loss.d2z(z, lab_ref[0, :, :]) * u   # [1, R]
    out_s_ref[:] = out_s_ref[:] + jnp.stack(
        [jnp.sum(q_row), jnp.float32(0.0)]).reshape(1, 2)

    per_slot = _slots_of_rows(q_row, mask_rT) * vals[:, None]
    _scatter_accum(out_g_ref, per_slot, mask_hi, mask_lo)


def _hv_at_kernel(*refs):
    """Hessian-vector sweep with the margin-derived row curvature d2 =
    weight * l''(z) PRECOMPUTED: gather u = dot(v), form q = d2 * u,
    scatter q and accumulate sum(q) — one pass, one gather + one scatter
    matmul (vs _hv_kernel's two gathers + scatter; TRON CG holds z fixed
    for its whole inner loop)."""
    (vals_ref, hi_ref, lo_ref, rlo_ref, d2_ref, v_ref, shift_ref,
     out_s_ref, out_g_ref) = refs
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_s_ref[:] = jnp.zeros_like(out_s_ref)
        out_g_ref[:] = jnp.zeros_like(out_g_ref)

    S = vals_ref.shape[2]
    B = v_ref.shape[0]
    mask_hi, mask_lo, mask_rT = _masks(hi_ref, lo_ref, rlo_ref, S, B)
    vals = vals_ref[0, 0, :]

    u = _row_margins(vals, mask_rT, v_ref, mask_hi, mask_lo) + shift_ref[0, 0]
    q_row = d2_ref[0, :, :] * u  # [1, R]
    out_s_ref[:] = out_s_ref[:] + jnp.stack(
        [jnp.sum(q_row), jnp.float32(0.0)]).reshape(1, 2)

    per_slot = _slots_of_rows(q_row, mask_rT) * vals[:, None]
    _scatter_accum(out_g_ref, per_slot, mask_hi, mask_lo)


def _spec_s(S):
    return pl.BlockSpec((1, 1, S), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)


def _spec_r():
    return pl.BlockSpec((1, 1, ROWS_PER_TILE), lambda i: (i, 0, 0),
                        memory_space=pltpu.VMEM)


def _spec_w(B):
    return pl.BlockSpec((B, LANE), lambda i: (0, 0), memory_space=pltpu.VMEM)


def _spec_acc(shape):
    return pl.BlockSpec(shape, lambda i: (0, 0), memory_space=pltpu.VMEM)


@functools.lru_cache(maxsize=None)
def _margins_call(T, S, B, use_offsets, pair, interpret):
    kern = functools.partial(_margins_kernel, use_offsets, pair)
    n_tab = 2 if pair else 1
    out_shape = [jax.ShapeDtypeStruct((T, 1, ROWS_PER_TILE), jnp.float32)]
    out_specs = [_spec_r()]
    if pair:
        out_shape.append(jax.ShapeDtypeStruct((T, 1, ROWS_PER_TILE), jnp.float32))
        out_specs.append(_spec_r())
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[_spec_s(S)] * 4 + [_spec_r()] + [_spec_w(B)] * n_tab
        + [pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM)],
        out_specs=out_specs if pair else out_specs[0],
        out_shape=out_shape if pair else out_shape[0],
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _scatter_call(T, S, B, square, interpret):
    kern = functools.partial(_scatter_kernel, square)
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[_spec_s(S)] * 4 + [_spec_r()],
        out_specs=_spec_acc((LANE, B)),
        out_shape=jax.ShapeDtypeStruct((LANE, B), jnp.float32),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _hv_call(T, S, B, loss_name, use_offsets, interpret):
    kern = functools.partial(_hv_kernel, loss_name, use_offsets)
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[_spec_s(S)] * 4 + [_spec_r()] * 3 + [_spec_w(B)] * 2
        + [pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM)],
        out_specs=[_spec_acc((1, 2)), _spec_acc((LANE, B))],
        out_shape=[
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
            jax.ShapeDtypeStruct((LANE, B), jnp.float32),
        ],
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _hv_at_call(T, S, B, interpret):
    return pl.pallas_call(
        _hv_at_kernel,
        grid=(T,),
        in_specs=[_spec_s(S)] * 4 + [_spec_r()] + [_spec_w(B)]
        + [pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM)],
        out_specs=[_spec_acc((1, 2)), _spec_acc((LANE, B))],
        out_shape=[
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
            jax.ShapeDtypeStruct((LANE, B), jnp.float32),
        ],
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _value_grad_call(T, S, B, loss_name, use_offsets, interpret):
    kern = functools.partial(_value_grad_kernel, loss_name, use_offsets)
    return pl.pallas_call(
        kern,
        grid=(T,),
        in_specs=[_spec_s(S)] * 4 + [_spec_r()] * 3 + [_spec_w(B)]
        + [pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM)],
        out_specs=[_spec_acc((1, 2)), _spec_acc((LANE, B))],
        out_shape=[
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
            jax.ShapeDtypeStruct((LANE, B), jnp.float32),
        ],
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# TiledBatch
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TiledBatch:
    """Sparse labeled examples in the tiled one-hot-matmul layout.

    Duck-type compatible with :class:`SparseBatch` for everything
    :class:`~photon_ml_tpu.ops.objective.GLMObjective` and the optimizer
    adapters use (margins / dot_rows / scatter_features / scatter_features_sq
    / labels / offsets / weights / num_features / num_rows), so it drops into
    every existing solve path unchanged. ``num_rows`` is padded to a multiple
    of 128; padded rows carry weight 0.
    """

    vals: Array      # f32[T, 1, S] slot values (0 in padding)
    hi: Array        # i32[T, 1, S] col // 128 (== B sentinel in padding)
    lo: Array        # i32[T, 1, S] col % 128
    rlo: Array       # i32[T, 1, S] row % 128
    labels3: Array   # f32[T, 1, 128]
    offsets3: Array  # f32[T, 1, 128]
    weights3: Array  # f32[T, 1, 128]; 0 for padded rows
    num_features: int = dataclasses.field(metadata=dict(static=True))

    # -- shape views --------------------------------------------------------

    @property
    def num_tiles(self) -> int:
        return self.vals.shape[0]

    @property
    def num_rows(self) -> int:
        return self.num_tiles * ROWS_PER_TILE

    @property
    def nnz_slots(self) -> int:
        return self.vals.shape[0] * self.vals.shape[2]

    @property
    def num_blocks(self) -> int:
        return -(-self.num_features // LANE)

    @property
    def dtype(self):
        return self.vals.dtype

    @property
    def labels(self) -> Array:
        return self.labels3.reshape(-1)

    @property
    def offsets(self) -> Array:
        return self.offsets3.reshape(-1)

    @property
    def weights(self) -> Array:
        return self.weights3.reshape(-1)

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_coo(
        values: np.ndarray,
        rows: np.ndarray,
        cols: np.ndarray,
        labels: np.ndarray,
        num_features: int,
        offsets: Optional[np.ndarray] = None,
        weights: Optional[np.ndarray] = None,
    ) -> "TiledBatch":
        """Host-side layout build: group nnz by row tile, pad to max."""
        n = int(len(labels))
        R = ROWS_PER_TILE
        T = max(-(-n // R), 1)
        B = -(-int(num_features) // LANE)
        rows = np.asarray(rows, np.int64)
        cols = np.asarray(cols, np.int64)
        values = np.asarray(values, np.float64)
        validate_coo_indices(rows, cols, n, num_features)

        tile = rows // R
        if len(tile) and not np.all(tile[1:] >= tile[:-1]):
            order = np.argsort(tile, kind="stable")
            tile_s = tile[order]
            rows = rows[order]
            cols = cols[order]
            values = values[order]
        else:  # ingest emits row-sorted COO — skip the nnz sort
            tile_s = tile
        starts = np.searchsorted(tile_s, np.arange(T))
        counts = np.diff(np.append(starts, len(tile_s)))
        S = int(max(LANE, -(-int(counts.max(initial=0)) // LANE) * LANE))
        offs = np.arange(len(tile_s)) - starts[tile_s]
        dest = tile_s * S + offs

        vals2 = np.zeros((T * S,), np.float32)
        hi2 = np.full((T * S,), B, np.int32)   # sentinel: one-hot all-zero
        lo2 = np.zeros((T * S,), np.int32)
        rlo2 = np.zeros((T * S,), np.int32)
        vals2[dest] = values
        hi2[dest] = (cols // LANE).astype(np.int32)
        lo2[dest] = (cols % LANE).astype(np.int32)
        rlo2[dest] = (rows % R).astype(np.int32)

        npad = T * R
        lab = np.zeros(npad, np.float32)
        lab[:n] = np.asarray(labels, np.float64)
        off = np.zeros(npad, np.float32)
        if offsets is not None:
            off[:n] = np.asarray(offsets, np.float64)
        wgt = np.zeros(npad, np.float32)
        wgt[:n] = 1.0 if weights is None else np.asarray(weights, np.float64)

        shp = (T, 1, S)
        return TiledBatch(
            vals=jnp.asarray(vals2.reshape(shp)),
            hi=jnp.asarray(hi2.reshape(shp)),
            lo=jnp.asarray(lo2.reshape(shp)),
            rlo=jnp.asarray(rlo2.reshape(shp)),
            labels3=jnp.asarray(lab.reshape(T, 1, R)),
            offsets3=jnp.asarray(off.reshape(T, 1, R)),
            weights3=jnp.asarray(wgt.reshape(T, 1, R)),
            num_features=int(num_features),
        )

    @staticmethod
    def from_batch(batch: SparseBatch) -> "TiledBatch":
        """Convert a padded-COO SparseBatch (drops its padding slots)."""
        vals = np.asarray(batch.values)
        rows = np.asarray(batch.rows)
        cols = np.asarray(batch.cols)
        keep = vals != 0
        return TiledBatch.from_coo(
            values=vals[keep],
            rows=rows[keep],
            cols=cols[keep],
            labels=np.asarray(batch.labels),
            num_features=batch.num_features,
            offsets=np.asarray(batch.offsets),
            weights=np.asarray(batch.weights),
        )

    @staticmethod
    def from_dense(X, labels, offsets=None, weights=None) -> "TiledBatch":
        X = np.asarray(X)
        rows, cols = np.nonzero(X)
        return TiledBatch.from_coo(
            values=X[rows, cols], rows=rows, cols=cols, labels=labels,
            num_features=X.shape[1], offsets=offsets, weights=weights,
        )

    def to_dense(self) -> np.ndarray:
        """Host-side densify (tests / diagnostics only)."""
        T, _, S = self.vals.shape
        X = np.zeros((self.num_rows, self.num_features), np.float64)
        vals = np.asarray(self.vals).reshape(-1)
        hi = np.asarray(self.hi).reshape(-1)
        lo = np.asarray(self.lo).reshape(-1)
        rlo = np.asarray(self.rlo).reshape(-1)
        tiles = np.repeat(np.arange(T), S)
        keep = hi < self.num_blocks
        col = hi[keep] * LANE + lo[keep]
        row = tiles[keep] * ROWS_PER_TILE + rlo[keep]
        np.add.at(X, (row, col), vals[keep])
        return X

    # -- device kernels ------------------------------------------------------

    def _w2(self, w: Array) -> Array:
        """Pad a [F] vector to the [B, 128] coefficient grid."""
        B = self.num_blocks
        pad = B * LANE - self.num_features
        return jnp.pad(w.astype(jnp.float32), (0, pad)).reshape(B, LANE)

    def _slot_args(self):
        return (self.vals, self.hi, self.lo, self.rlo)

    def margins(self, w: Array, shift: Array | float = 0.0) -> Array:
        """Per-row margins z_i = x_i . w + shift + offset_i."""
        T, _, S = self.vals.shape
        call = _margins_call(T, S, self.num_blocks, True, False, _interpret())
        sh = jnp.stack([jnp.asarray(shift, jnp.float32), jnp.float32(0)])
        z = call(*self._slot_args(), self.offsets3, self._w2(w),
                 sh.reshape(1, 2))
        return z.reshape(-1)

    def dot_rows(self, w: Array) -> Array:
        """Per-row raw dot products x_i . w (no offset/shift)."""
        T, _, S = self.vals.shape
        call = _margins_call(T, S, self.num_blocks, False, False, _interpret())
        sh = jnp.zeros((1, 2), jnp.float32)
        z = call(*self._slot_args(), self.offsets3, self._w2(w), sh)
        return z.reshape(-1)

    def margins_pair(
        self, w: Array, shift, p: Array, p_shift
    ) -> tuple[Array, Array]:
        """(margins(w, shift), dot_rows(p) + p_shift) in one fused sweep."""
        T, _, S = self.vals.shape
        call = _margins_call(T, S, self.num_blocks, True, True, _interpret())
        sh = jnp.stack([
            jnp.asarray(shift, jnp.float32), jnp.asarray(p_shift, jnp.float32)
        ])
        z, u = call(*self._slot_args(), self.offsets3, self._w2(w),
                    self._w2(p), sh.reshape(1, 2))
        return z.reshape(-1), u.reshape(-1)

    def _scatter(self, per_row: Array, square: bool) -> Array:
        T, _, S = self.vals.shape
        call = _scatter_call(T, S, self.num_blocks, square, _interpret())
        pr3 = per_row.astype(jnp.float32).reshape(T, 1, ROWS_PER_TILE)
        g = call(*self._slot_args(), pr3)
        # accumulator is [LANE, B]; feature f = b*128 + j lives at [j, b]
        return g.T.reshape(-1)[: self.num_features]

    def scatter_features(self, per_row: Array) -> Array:
        """sum_i per_row[i] * x_i as a dense feature-space vector."""
        return self._scatter(per_row, False)

    def scatter_features_sq(self, per_row: Array) -> Array:
        """sum_i per_row[i] * (x_i ** 2) (Hessian diagonal)."""
        return self._scatter(per_row, True)

    def fused_value_grad(
        self, w: Array, shift, loss_name: str
    ) -> tuple[Array, Array, Array]:
        """(sum_i wgt_i*l(z_i), raw feature-space gradient, sum_i wgt_i*dz_i).

        The raw gradient is the un-normalized scatter sum_i wgt_i*dz_i*x_i;
        the caller applies normalization back-transform and regularization
        (GLMObjective.value_and_grad fast path).
        """
        T, _, S = self.vals.shape
        call = _value_grad_call(
            T, S, self.num_blocks, loss_name, True, _interpret())
        sh = jnp.stack([jnp.asarray(shift, jnp.float32), jnp.float32(0)])
        sums, g = call(*self._slot_args(), self.labels3, self.weights3,
                       self.offsets3, self._w2(w), sh.reshape(1, 2))
        return sums[0, 0], g.T.reshape(-1)[: self.num_features], sums[0, 1]

    def fused_hessian_vector(
        self, w: Array, shift, v: Array, v_shift, loss_name: str
    ) -> tuple[Array, Array]:
        """(raw Hv scatter sum_i wgt_i*l''(z_i)*(x_i.v)*x_i, sum of the
        per-row q = wgt*l''*u terms) in ONE fused sweep (TRON CG fast path).
        Caller applies normalization back-transform and the L2 term."""
        T, _, S = self.vals.shape
        call = _hv_call(T, S, self.num_blocks, loss_name, True, _interpret())
        sh = jnp.stack([
            jnp.asarray(shift, jnp.float32), jnp.asarray(v_shift, jnp.float32)
        ])
        sums, g = call(*self._slot_args(), self.labels3, self.weights3,
                       self.offsets3, self._w2(w), self._w2(v),
                       sh.reshape(1, 2))
        return g.T.reshape(-1)[: self.num_features], sums[0, 0]

    def fused_hv_at(
        self, d2_row: Array, v_eff: Array, v_shift
    ) -> tuple[Array, Array]:
        """(raw Hv scatter, sum q) with the row curvature d2 = wgt*l''(z)
        precomputed: ONE pass doing gather u + scatter q (TRON CG holds z
        fixed across its inner loop)."""
        T, _, S = self.vals.shape
        call = _hv_at_call(T, S, self.num_blocks, _interpret())
        d2_3 = d2_row.astype(jnp.float32).reshape(T, 1, ROWS_PER_TILE)
        sh = jnp.stack([jnp.asarray(v_shift, jnp.float32), jnp.float32(0)])
        sums, g = call(*self._slot_args(), d2_3, self._w2(v_eff),
                       sh.reshape(1, 2))
        return g.T.reshape(-1)[: self.num_features], sums[0, 0]

    def feature_moment_sums(self) -> tuple[Array, Array, Array]:
        """Per-feature (sum x, sum x^2, count nonzero) over valid rows."""
        valid = (self.weights > 0).astype(jnp.float32)
        s1 = self.scatter_features(valid)
        s2 = self.scatter_features_sq(valid)
        ones = dataclasses.replace(
            self, vals=(self.vals != 0).astype(jnp.float32))
        cnt = ones.scatter_features(valid)
        return s1, s2, cnt

    def with_offsets(self, offsets: Array) -> "TiledBatch":
        return dataclasses.replace(
            self,
            offsets3=offsets.astype(jnp.float32).reshape(
                self.num_tiles, 1, ROWS_PER_TILE),
        )
