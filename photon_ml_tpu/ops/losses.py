"""Pointwise GLM losses: l(z, y), dl/dz, d2l/dz2 as vectorized JAX functions.

Parity targets (reference, for behavior only — see SURVEY.md §2.c):
  - logistic: photon-api .../function/glm/LogisticLossFunction.scala
  - squared:  photon-api .../function/glm/SquaredLossFunction.scala
  - poisson:  photon-api .../function/glm/PoissonLossFunction.scala
  - smoothed hinge (Rennie): photon-api .../function/svm/SmoothedHingeLossFunction.scala

All functions are elementwise over arrays of margins ``z`` and labels ``y``
so they fuse into the surrounding segment-sum/objective computation under XLA.
Labels with y > 0.5 are treated as positive, matching the reference's
POSITIVE_RESPONSE_THRESHOLD convention (so both {0,1} and {-1,1} labels work).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

Array = jax.Array

_POSITIVE_THRESHOLD = 0.5


class PointwiseLoss(NamedTuple):
    """A pointwise loss l(z, y) with first and second derivatives in z.

    ``has_hessian`` is False for losses that are not twice differentiable
    (smoothed hinge); the optimizer factory rejects TRON for those, matching
    the reference's OptimizerFactory behavior.
    """

    name: str
    loss: Callable[[Array, Array], Array]
    dz: Callable[[Array, Array], Array]
    d2z: Callable[[Array, Array], Array]
    has_hessian: bool = True

    def loss_and_dz(self, z: Array, y: Array) -> tuple[Array, Array]:
        return self.loss(z, y), self.dz(z, y)


def _y01(y: Array) -> Array:
    """Map labels to {0, 1} using the positive-response threshold."""
    return jnp.where(y > _POSITIVE_THRESHOLD, 1.0, 0.0).astype(y.dtype)


def _ypm1(y: Array) -> Array:
    """Map labels to {-1, +1} using the positive-response threshold."""
    return jnp.where(y > _POSITIVE_THRESHOLD, 1.0, -1.0).astype(y.dtype)


# ---------------------------------------------------------------------------
# Logistic: l(z, y) = log(1 + exp(z)) - y*z  for y in {0,1}
# ---------------------------------------------------------------------------

def _logistic_loss(z: Array, y: Array) -> Array:
    # softplus(z) - y*z == log1pExp(-z) for y=1, log1pExp(z) for y=0:
    # numerically stable for large |z| (softplus is implemented stably).
    return jax.nn.softplus(z) - _y01(y) * z


def _logistic_dz(z: Array, y: Array) -> Array:
    return jax.nn.sigmoid(z) - _y01(y)


def _logistic_d2z(z: Array, y: Array) -> Array:
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


LogisticLoss = PointwiseLoss("logistic", _logistic_loss, _logistic_dz, _logistic_d2z)


# ---------------------------------------------------------------------------
# Squared: l(z, y) = 0.5 * (z - y)^2
# ---------------------------------------------------------------------------

def _squared_loss(z: Array, y: Array) -> Array:
    d = z - y
    return 0.5 * d * d


def _squared_dz(z: Array, y: Array) -> Array:
    return z - y


def _squared_d2z(z: Array, y: Array) -> Array:
    return jnp.ones_like(z)


SquaredLoss = PointwiseLoss("squared", _squared_loss, _squared_dz, _squared_d2z)


# ---------------------------------------------------------------------------
# Poisson: l(z, y) = exp(z) - y*z
# ---------------------------------------------------------------------------

def _poisson_loss(z: Array, y: Array) -> Array:
    return jnp.exp(z) - y * z


def _poisson_dz(z: Array, y: Array) -> Array:
    return jnp.exp(z) - y


def _poisson_d2z(z: Array, y: Array) -> Array:
    return jnp.exp(z)


PoissonLoss = PointwiseLoss("poisson", _poisson_loss, _poisson_dz, _poisson_d2z)


# ---------------------------------------------------------------------------
# Smoothed hinge (Rennie): piecewise quadratic approximation of hinge loss.
#   u = y*z with y in {-1,+1}
#   l = 0.5 - u        (u <= 0)
#       0.5*(1-u)^2    (0 < u < 1)
#       0              (u >= 1)
# Not twice differentiable; d2z below is the a.e. second derivative
# (generalized Hessian), but has_hessian=False gates TRON off.
# ---------------------------------------------------------------------------

def _smoothed_hinge_loss(z: Array, y: Array) -> Array:
    u = _ypm1(y) * z
    return jnp.where(
        u <= 0.0, 0.5 - u, jnp.where(u < 1.0, 0.5 * (1.0 - u) * (1.0 - u), 0.0)
    )


def _smoothed_hinge_dz(z: Array, y: Array) -> Array:
    ym = _ypm1(y)
    u = ym * z
    du = jnp.where(u < 0.0, -1.0, jnp.where(u < 1.0, u - 1.0, 0.0))
    return du * ym


def _smoothed_hinge_d2z(z: Array, y: Array) -> Array:
    u = _ypm1(y) * z
    return jnp.where((u > 0.0) & (u < 1.0), 1.0, 0.0)


SmoothedHingeLoss = PointwiseLoss(
    "smoothed_hinge",
    _smoothed_hinge_loss,
    _smoothed_hinge_dz,
    _smoothed_hinge_d2z,
    has_hessian=False,
)


LOSSES: dict[str, PointwiseLoss] = {
    loss.name: loss
    for loss in (LogisticLoss, SquaredLoss, PoissonLoss, SmoothedHingeLoss)
}

# Task-type aliases mirroring the reference's TaskType enum.
_TASK_ALIASES = {
    "logistic_regression": "logistic",
    "linear_regression": "squared",
    "poisson_regression": "poisson",
    "smoothed_hinge_loss_linear_svm": "smoothed_hinge",
}


def get_loss(name: str) -> PointwiseLoss:
    key = name.lower()
    key = _TASK_ALIASES.get(key, key)
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Available: {sorted(LOSSES)}")
    return LOSSES[key]
