"""Sparse example batches for TPU GLM training.

TPUs have no native CSR support, so sparse design matrices are stored as
padded COO with static shapes: parallel arrays ``values``/``rows``/``cols``
of length nnz_pad, plus per-row ``labels``/``offsets``/``weights`` of length
n_pad. Margins are computed as gather + multiply + ``segment_sum`` (rows are
sorted, so XLA lowers this to an efficient scan); gradients as a scatter-add
into the feature dimension. This replaces the reference's Breeze sparse-vector
hot loop (ValueAndGradientAggregator.scala:132-153) with fused vector ops.

Padding convention: padded nnz entries have value 0 (so they contribute
nothing to any sum) and point at the LAST row index / col 0 — the last-row
choice keeps ``rows`` non-decreasing, which ``segment_sum`` is promised via
``indices_are_sorted=True`` and may exploit on TPU. Padded rows have weight 0.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _round_up(n: int, multiple: int) -> int:
    if multiple <= 1:
        return max(n, 1)
    return max(((n + multiple - 1) // multiple) * multiple, multiple)


def _pad(a: np.ndarray, total: int, fill=0) -> np.ndarray:
    """Pad a 1-D host array to ``total`` entries with ``fill``."""
    a = np.asarray(a)
    out = np.full((total,), fill, dtype=a.dtype)
    out[: a.shape[0]] = a
    return out


def validate_coo_indices(
    rows: np.ndarray, cols: np.ndarray, num_rows: int, num_features: int
) -> None:
    """Reject out-of-range COO indices: silent out-of-range cols would be
    dropped by the clamped device gathers and corrupt the scatter adds.
    Shared by SparseBatch.from_coo and TiledBatch.from_coo."""
    if len(cols) and (cols.min() < 0 or cols.max() >= num_features):
        raise ValueError(
            f"feature indices must be in [0, {num_features}); got "
            f"[{cols.min()}, {cols.max()}]"
        )
    if len(rows) and (rows.min() < 0 or rows.max() >= num_rows):
        raise ValueError(
            f"row indices must be in [0, {num_rows}); got "
            f"[{rows.min()}, {rows.max()}]"
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseBatch:
    """A fixed-shape batch of sparse labeled examples.

    The TPU-native analog of the reference's ``RDD[LabeledPoint]`` /
    ``Iterable[LabeledPoint]`` (photon-lib data/LabeledPoint.scala): labels,
    offsets and weights are columnar arrays, and features are one padded COO
    block. ``num_features`` is static so downstream gradient shapes are fixed
    under jit.

    Leaves may be HOST numpy arrays (what the constructors produce) or
    device arrays: host batches make the data plane (grouping, tiling,
    stats) transfer-free, and a solve path uploads once via :meth:`device`
    (or implicitly at a jit boundary).
    """

    values: Array  # f[nnz_pad] feature values (0 in padding)
    rows: Array  # i32[nnz_pad] row index per nnz, non-decreasing
    cols: Array  # i32[nnz_pad] feature index per nnz
    labels: Array  # f[n_pad]
    offsets: Array  # f[n_pad]
    weights: Array  # f[n_pad]; 0 for padded rows
    num_features: int = dataclasses.field(metadata=dict(static=True))

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    @property
    def nnz(self) -> int:
        return self.values.shape[0]

    @property
    def dtype(self):
        return self.values.dtype

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_coo(
        values: np.ndarray,
        rows: np.ndarray,
        cols: np.ndarray,
        labels: np.ndarray,
        num_features: int,
        offsets: Optional[np.ndarray] = None,
        weights: Optional[np.ndarray] = None,
        dtype=jnp.float32,
        row_pad_multiple: int = 1,
        nnz_pad_multiple: int = 1,
    ) -> "SparseBatch":
        """Build a batch from host COO arrays, sorting by row and padding.

        Raises on out-of-range row/col indices — a silent out-of-range col
        would be dropped by the clamped device gathers and corrupt the
        scatter adds (TiledBatch.from_coo validates identically).
        """
        n = int(len(labels))
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        validate_coo_indices(rows, cols, n, num_features)
        values = np.asarray(values)
        if len(rows) and not np.all(rows[1:] >= rows[:-1]):
            # ingest paths emit row-sorted COO; only re-sort when needed
            order = np.argsort(rows, kind="stable")
            values = values[order]
            rows = rows[order]
            cols = cols[order]

        n_pad = _round_up(n, row_pad_multiple)
        nnz = int(len(values))
        nnz_pad = _round_up(nnz, nnz_pad_multiple)

        labels_p = _pad(np.asarray(labels, dtype=np.float64), n_pad)
        offsets_p = _pad(
            np.zeros(n) if offsets is None else np.asarray(offsets, np.float64), n_pad
        )
        weights_p = _pad(
            np.ones(n) if weights is None else np.asarray(weights, np.float64), n_pad
        )

        # leaves stay HOST numpy (dtype applied host-side): construction is
        # transfer-free, and consumers upload exactly once where the batch
        # is actually solved/scored (see .device()). This keeps the
        # host-side data plane (RE grouping, tiling, stats, ingest) off the
        # PCIe link entirely.
        np_dtype = np.dtype(dtype)
        return SparseBatch(
            values=_pad(np.asarray(values, np.float64), nnz_pad).astype(np_dtype),
            rows=_pad(rows.astype(np.int64), nnz_pad, fill=n_pad - 1).astype(
                np.int32
            ),
            cols=_pad(cols.astype(np.int64), nnz_pad).astype(np.int32),
            labels=labels_p.astype(np_dtype),
            offsets=offsets_p.astype(np_dtype),
            weights=weights_p.astype(np_dtype),
            num_features=int(num_features),
        )

    def device(self, sharding=None) -> "SparseBatch":
        """Upload every leaf (no-op for leaves already on device)."""
        put = (
            jax.device_put
            if sharding is None
            else (lambda x: jax.device_put(x, sharding))
        )
        return jax.tree.map(put, self)

    @staticmethod
    def from_dense(
        X: np.ndarray,
        labels: np.ndarray,
        offsets: Optional[np.ndarray] = None,
        weights: Optional[np.ndarray] = None,
        dtype=jnp.float32,
    ) -> "SparseBatch":
        X = np.asarray(X)
        rows, cols = np.nonzero(X)
        return SparseBatch.from_coo(
            values=X[rows, cols],
            rows=rows,
            cols=cols,
            labels=labels,
            num_features=X.shape[1],
            offsets=offsets,
            weights=weights,
            dtype=dtype,
        )

    def dense_rows(self) -> Array:
        """DEVICE-side densify [num_rows, num_features] — jit/vmap friendly.
        Intended for small feature dims (per-entity local spaces) where
        explicit-Hessian solvers want the dense design."""
        X = jnp.zeros((self.num_rows, self.num_features), self.dtype)
        return X.at[self.rows, self.cols].add(self.values)

    def to_dense(self) -> np.ndarray:
        """Host-side densify (tests / diagnostics only)."""
        X = np.zeros((self.num_rows, self.num_features), dtype=np.float64)
        np.add.at(
            X,
            (np.asarray(self.rows), np.asarray(self.cols)),
            np.asarray(self.values, dtype=np.float64),
        )
        return X

    # -- device kernels ------------------------------------------------------

    def margins(self, w: Array, shift: Array | float = 0.0) -> Array:
        """Per-row margins z_i = x_i . w + shift + offset_i.

        ``w`` is the (already normalization-scaled) coefficient vector;
        ``shift`` the scalar margin correction -(w*factor).shifts from the
        normalization trick (ValueAndGradientAggregator.scala:35-79 analog).
        """
        contrib = self.values * jnp.take(w, self.cols, fill_value=0)
        dots = jax.ops.segment_sum(
            contrib, self.rows, num_segments=self.num_rows, indices_are_sorted=True
        )
        return dots + self.offsets + shift

    def dot_rows(self, w: Array) -> Array:
        """Per-row raw dot products x_i . w (no offset/shift)."""
        contrib = self.values * jnp.take(w, self.cols, fill_value=0)
        return jax.ops.segment_sum(
            contrib, self.rows, num_segments=self.num_rows, indices_are_sorted=True
        )

    def margins_pair(
        self, w: Array, shift, p: Array, p_shift
    ) -> tuple[Array, Array]:
        """(margins(w, shift), dot_rows(p) + p_shift).

        Layouts that can share one data sweep between the two gathers
        (TiledBatch) override this; here it is the plain composition, so
        call sites need no per-layout dispatch.
        """
        return self.margins(w, shift), self.dot_rows(p) + p_shift

    def fused_value_grad(
        self, w: Array, shift, loss_name: str
    ) -> tuple[Array, Array, Array]:
        """(sum_i wgt_i*l(z_i), raw gradient scatter, sum_i wgt_i*dz_i).

        The raw gradient is sum_i wgt_i*dz_i*x_i with NO normalization
        back-transform or regularization (the objective applies those).
        TiledBatch computes all three in one fused pallas sweep; this is
        the equivalent composition for the padded-COO layout.
        """
        from photon_ml_tpu.ops.losses import get_loss

        z = self.margins(w, shift)
        l, dz = get_loss(loss_name).loss_and_dz(z, self.labels)
        g_row = self.weights * dz
        return (
            jnp.sum(self.weights * l),
            self.scatter_features(g_row),
            jnp.sum(g_row),
        )

    def fused_hessian_vector(
        self, w: Array, shift, v: Array, v_shift, loss_name: str
    ) -> tuple[Array, Array]:
        """(raw Hv scatter sum_i wgt_i*l''(z_i)*(x_i.v)*x_i, sum_i q_i).

        TiledBatch computes this in one fused pallas sweep; this is the
        equivalent composition for the padded-COO layout.
        """
        from photon_ml_tpu.ops.losses import get_loss

        z, xv = self.margins_pair(w, shift, v, v_shift)
        q = self.weights * get_loss(loss_name).d2z(z, self.labels) * xv
        return self.scatter_features(q), jnp.sum(q)

    def fused_hv_at(
        self, d2_row: Array, v_eff: Array, v_shift
    ) -> tuple[Array, Array]:
        """(raw Hv scatter, sum q) with the row curvature d2 = wgt*l''(z)
        precomputed (q = d2 * (x.v + v_shift)). Plain composition here;
        TiledBatch fuses gather + scatter into one pallas pass."""
        u = self.dot_rows(v_eff) + v_shift
        q = d2_row * u
        return self.scatter_features(q), jnp.sum(q)

    def scatter_features(self, per_row: Array) -> Array:
        """Compute sum_i per_row[i] * x_i as a dense feature-space vector.

        A scatter-add over the feature dimension. (A column-sorted CSC
        mirror using sorted segment_sum was measured NOT faster on TPU —
        segment_sum lowers to scatter there; see PERF_NOTES.md.)
        """
        contrib = self.values * jnp.take(per_row, self.rows, fill_value=0)
        return jnp.zeros((self.num_features,), dtype=contrib.dtype).at[self.cols].add(
            contrib
        )

    def scatter_features_sq(self, per_row: Array) -> Array:
        """Compute sum_i per_row[i] * (x_i ** 2) elementwise (Hessian diagonal)."""
        contrib = self.values * self.values * jnp.take(per_row, self.rows, fill_value=0)
        return jnp.zeros((self.num_features,), dtype=contrib.dtype).at[self.cols].add(
            contrib
        )

    def feature_moment_sums(self) -> tuple[Array, Array, Array]:
        """Per-feature (sum x, sum x^2, count nonzero) over valid rows."""
        valid = jnp.take(
            (self.weights > 0).astype(self.dtype), self.rows, fill_value=0
        )
        v = self.values * valid
        zeros = jnp.zeros((self.num_features,), dtype=self.dtype)
        s1 = zeros.at[self.cols].add(v)
        s2 = zeros.at[self.cols].add(v * v)
        cnt = zeros.at[self.cols].add((v != 0).astype(self.dtype))
        return s1, s2, cnt

    def with_offsets(self, offsets: Array) -> "SparseBatch":
        return dataclasses.replace(self, offsets=offsets)

    # -- sharding helpers ----------------------------------------------------

    def pad_rows_to(self, n_pad: int, nnz_pad: int) -> "SparseBatch":
        """Pad row-count and nnz to given totals (host-side, numpy)."""
        if n_pad < self.num_rows or nnz_pad < self.nnz:
            raise ValueError("pad target smaller than current size")

        return SparseBatch(
            values=_pad(self.values, nnz_pad),
            rows=_pad(self.rows, nnz_pad, fill=n_pad - 1),
            cols=_pad(self.cols, nnz_pad),
            labels=_pad(self.labels, n_pad),
            offsets=_pad(self.offsets, n_pad),
            weights=_pad(self.weights, n_pad),
            num_features=self.num_features,
        )


def concat_batches(batches: Sequence[SparseBatch]) -> SparseBatch:
    """Host-side concatenation of row-blocks (row indices re-based)."""
    if not batches:
        raise ValueError("no batches")
    num_features = batches[0].num_features
    row_base = 0
    vals, rows, cols, labels, offsets, weights = [], [], [], [], [], []
    for b in batches:
        if b.num_features != num_features:
            raise ValueError("feature-dimension mismatch")
        vals.append(np.asarray(b.values))
        rows.append(np.asarray(b.rows) + row_base)
        cols.append(np.asarray(b.cols))
        labels.append(np.asarray(b.labels))
        offsets.append(np.asarray(b.offsets))
        weights.append(np.asarray(b.weights))
        row_base += b.num_rows
    return SparseBatch(
        values=np.concatenate(vals),
        rows=np.concatenate(rows).astype(np.int32),
        cols=np.concatenate(cols).astype(np.int32),
        labels=np.concatenate(labels),
        offsets=np.concatenate(offsets),
        weights=np.concatenate(weights),
        num_features=num_features,
    )
