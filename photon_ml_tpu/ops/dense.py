"""Dense local-design batch: the billion-coefficient random-effect layout.

``DenseBatch`` holds one entity's design matrix as a dense [R, K] array and
is duck-type compatible with :class:`~photon_ml_tpu.ops.sparse.SparseBatch`
for everything :class:`~photon_ml_tpu.ops.objective.GLMObjective` and the
optimizer adapters touch, so ``glm_adapter``/``dispatch_solve``/``vmap``
work unchanged.

Why it exists: per-entity problems in index-map-projected local spaces are
SMALL (K ~ 1e2-1e3) and, after the projection squeezed out unobserved
features, fairly dense. At the reference's headline scale ("hundreds of
billions of coefficients", /root/reference/README.md:73; projection
envelope ~1e8 entities x ~1e3 features, projector/README.md:8-12) the solve
throughput is set by how the per-entity sweeps map to hardware: COO
gather/segment ops are random-access bound on TPU (~1e8 elem/s,
PERF_NOTES.md), while dense [E, R, K] batched matmuls ride the MXU at
full bandwidth with ZERO random access. A vmapped solve over a [E, R, K]
stack is one ``jnp.einsum`` per sweep.

Used by the streaming 1B-coefficient trainer (photon_ml_tpu.game.streaming)
and anywhere a small dense design is already at hand (diagnostics,
latent-space MF refits).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops.losses import get_loss

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DenseBatch:
    """Dense labeled examples X [R, K] (+ labels/offsets/weights [R]).

    All sweeps are matmuls/einsums — vmap over a leading entity axis turns
    them into MXU-batched contractions. Weights of 0 mark padded rows.
    """

    x: Array        # f[R, K]
    labels: Array   # f[R]
    offsets: Array  # f[R]
    weights: Array  # f[R]

    @property
    def num_features(self) -> int:
        return self.x.shape[-1]

    @property
    def num_rows(self) -> int:
        return self.x.shape[-2]

    @property
    def dtype(self):
        return self.x.dtype

    @staticmethod
    def from_arrays(x, labels, offsets=None, weights=None) -> "DenseBatch":
        x = jnp.asarray(x, jnp.float32)
        n = x.shape[-2]
        z = jnp.zeros((n,), jnp.float32)
        return DenseBatch(
            x=x,
            labels=jnp.asarray(labels, jnp.float32),
            offsets=z if offsets is None else jnp.asarray(offsets, jnp.float32),
            weights=(
                jnp.ones((n,), jnp.float32)
                if weights is None
                else jnp.asarray(weights, jnp.float32)
            ),
        )

    def dense_rows(self) -> Array:
        return self.x

    def to_dense(self) -> np.ndarray:
        return np.asarray(self.x)

    # -- sweeps (SparseBatch duck-type) --------------------------------------

    def margins(self, w: Array, shift: Array | float = 0.0) -> Array:
        return self.x @ w + shift + self.offsets

    def dot_rows(self, w: Array) -> Array:
        return self.x @ w

    def margins_pair(self, w, shift, p, p_shift):
        zu = self.x @ jnp.stack([w, p], axis=1)        # [R, 2]
        return zu[:, 0] + shift + self.offsets, zu[:, 1] + p_shift

    def fused_value_grad(self, w, shift, loss_name: str):
        loss = get_loss(loss_name)
        z = self.margins(w, shift)
        l, dz = loss.loss_and_dz(z, self.labels)
        wdz = self.weights * dz
        return jnp.sum(self.weights * l), wdz @ self.x, jnp.sum(wdz)

    def fused_hessian_vector(self, w, shift, v, v_shift, loss_name: str):
        loss = get_loss(loss_name)
        zu = self.x @ jnp.stack([w, v], axis=1)
        z = zu[:, 0] + shift + self.offsets
        u = zu[:, 1] + v_shift
        q = self.weights * loss.d2z(z, self.labels) * u
        return q @ self.x, jnp.sum(q)

    def fused_hv_at(self, d2_row, v, v_shift):
        q = d2_row * (self.x @ v + v_shift)
        return q @ self.x, jnp.sum(q)

    def scatter_features(self, per_row: Array) -> Array:
        return per_row @ self.x

    def scatter_features_sq(self, per_row: Array) -> Array:
        return per_row @ (self.x * self.x)

    def with_offsets(self, offsets: Array) -> "DenseBatch":
        return dataclasses.replace(
            self, offsets=jnp.asarray(offsets, self.offsets.dtype)
        )
