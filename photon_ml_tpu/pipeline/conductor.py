"""The freshness conductor: a supervised daemon unifying the three
freshness tiers (nearline, incremental, full retrain) under one cadence.

The repo grew three freshness mechanisms at three timescales — nearline
per-entity solves (seconds), masked incremental retrains (minutes), and
full retrains (hours) — with no conductor: nothing tailed deltas on a
cadence, nothing reconciled a nearline-updated row that also lands in a
delta's touched set, and nothing measured event→served staleness, the
metric the whole tier exists for.  :class:`FreshnessPipeline` is that
conductor, surfaced as ``cli pipeline``.

Each cycle:

1. tail the delta directory; :func:`delta_digest` over the globbed
   shards detects new/changed content (an unchanged digest is an idle
   cycle — no read, no fit, no publish);
2. ``scan_delta`` the new shards against the base model's vocabularies;
3. decide the nearline-vs-delta reconciliation (``pipeline.reconcile``)
   and record it — see :mod:`photon_ml_tpu.pipeline.reconcile` for the
   retrain-wins-touched rule and its rationale;
4. either run the masked incremental re-solve
   (``estimator.fit_incremental`` → ``MaskedRandomEffectCoordinate``)
   or, when the touched fraction or the cycles-since-full count trips a
   threshold, escalate (``pipeline.escalate``) to a full retrain into a
   fresh base generation under the workdir;
5. ``publish_incremental`` the result — lineage carries the base
   checkpoint, delta digest, and the reconciliation record — and
   hot-swap the live :class:`ModelRegistry` so the next score serves it;
6. observe per-delta-file event→served staleness and publish the p99 as
   the gauge ``pipeline.event_to_served_staleness_p99_s`` (the tier's
   headline SLO, gated in ``bench_suite --freshness``).

Crash safety is inherited, not reimplemented: every publish goes through
the registry's assemble-then-``os.rename`` protocol and the base
checkpoint is only ever read, so a hard kill at ANY point mid-cycle
(the three ``pipeline.*`` seams below, exercised by
``tools/chaos.py --pipeline``) leaves the base byte-identical and the
registry free of partial versions; the restarted daemon re-seeds its
digest cursor from the newest published lineage and simply redoes the
interrupted cycle.

Supervision: ``/statusz``-style live status via
:class:`FleetStatusWriter` (the conductor is a 1-member fleet — its
heartbeat file, cycle counters, and served version ride the standard
fleet-status document), per-cycle spans/counters rendered as the
RunReport "Pipeline" section, and SIGTERM → finish the current cycle,
exit 75 (the scheduler-restart convention shared with training).
"""

from __future__ import annotations

import dataclasses
import glob
import logging
import os
import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence

import numpy as np

from .. import faults, telemetry
from ..config import parse_game_config
from ..game.checkpoint import CheckpointSpec
from ..game.estimator import GameEstimator
from ..incremental import (
    delta_digest,
    load_warm_start,
    publish_incremental,
    scan_delta,
)
from ..quality import QualityGateRefused
from .reconcile import newest_version_metadata, reconcile_nearline

logger = logging.getLogger(__name__)

# -- fault seams -------------------------------------------------------------
# All three are PLAIN seams (not write-path: the conductor never writes
# the base, and every registry write is behind incremental.publish's own
# write-path seam) — a hard kill here must leave the base checkpoint
# byte-identical and the registry without partial versions, which the
# chaos row `tools/chaos.py --pipeline` asserts.
FP_CYCLE_START = faults.register_point(
    "pipeline.cycle_start",
    description="top of a conductor cycle, before the delta poll is "
    "acted on — a kill here loses nothing (the cycle had no effects yet)",
)
FP_RECONCILE = faults.register_point(
    "pipeline.reconcile",
    description="before the nearline-vs-delta reconciliation decision "
    "is recorded — a kill here must not publish a version whose lineage "
    "lacks the decision",
)
FP_ESCALATE = faults.register_point(
    "pipeline.escalate",
    description="before an escalated full retrain begins — a kill here "
    "must leave the incumbent base generation intact and serving",
)


@dataclasses.dataclass(frozen=True)
class PipelineSpec:
    """Static configuration for one :class:`FreshnessPipeline` run.

    ``config`` is a full train-CLI config document (input spec,
    coordinates, ...) — the conductor reuses the train driver's readers
    and estimator so a pipeline cycle fits exactly what ``cli train``
    would. ``base_dir`` is the warm-start base (step checkpoint or saved
    model dir); after an escalation the conductor re-bases onto the new
    generation it trained under ``workdir``.
    """

    config: Mapping[str, Any]
    delta_dir: str
    base_dir: str
    registry_dir: str
    workdir: str
    interval_s: float = 5.0
    # 0 = run until stopped (SIGTERM); tests and the bench pin a count
    max_cycles: int = 0
    delta_glob: str = "*.avro"
    # escalation trips on EITHER threshold; escalate_after_cycles=0
    # disables the count trigger, escalate_touched_fraction>=1.0
    # effectively disables the fraction trigger
    escalate_touched_fraction: float = 0.5
    escalate_after_cycles: int = 0
    # hot-swap a live ModelRegistry after each publish (off for
    # fit-only runs where nothing serves)
    serve: bool = True
    status_file: Optional[str] = None
    status_port: Optional[int] = None
    heartbeat_deadline_s: float = 30.0
    # champion/challenger publish gate (serving.registry): every cycle's
    # candidate carries bootstrap error bars; a candidate regressing
    # beyond the champion's CI is quarantined, not swapped in. False
    # still computes and records stats but bypasses the refusal.
    quality_gate: bool = True
    bootstrap_samples: int = 32


class FreshnessPipeline:
    """The conductor loop. One instance = one supervised daemon run."""

    def __init__(self, spec: PipelineSpec):
        if not spec.delta_dir:
            raise ValueError("PipelineSpec.delta_dir is required")
        if not spec.registry_dir:
            raise ValueError("PipelineSpec.registry_dir is required")
        self.spec = spec
        # parse eagerly: a malformed config must fail at startup, not on
        # the first non-idle cycle hours later
        self._game_config = parse_game_config(spec.config)
        self._estimator = GameEstimator(self._game_config)
        self._base_dir = spec.base_dir
        # index maps are pinned on the first cycle's combined read and
        # reused verbatim after — the served feature space must not
        # drift cycle to cycle (scoring ids must match the base model's)
        self._index_maps: Optional[Mapping] = None
        self._last_digest: Optional[str] = self._seed_digest()
        self._staleness: List[float] = []
        self._stop = threading.Event()
        self.cycle = 0
        self._cycles_since_full = 0
        self._published: List[str] = []
        self._quarantined: List[str] = []
        self._escalations = 0
        self._idle_cycles = 0
        self._reconciliations = 0
        self._registry = None
        self._status = None
        self._heartbeat = None
        self._last_p99: Optional[float] = None

    # -- cursor seeding ------------------------------------------------------

    def _seed_digest(self) -> Optional[str]:
        """Resume the digest cursor from the newest published lineage so
        a restarted conductor does not re-publish the delta it already
        served (the crash-restart idempotence contract)."""
        _, meta = newest_version_metadata(self.spec.registry_dir)
        lineage = ((meta or {}).get("extra") or {}).get("lineage") or {}
        return lineage.get("delta_digest")

    def _delta_paths(self) -> List[str]:
        return sorted(
            glob.glob(os.path.join(self.spec.delta_dir, self.spec.delta_glob))
        )

    # -- status --------------------------------------------------------------

    def _start_status(self) -> None:
        if self.spec.status_file is None and self.spec.status_port is None:
            return
        from ..parallel.fleet_status import FleetStatusWriter
        from ..parallel.multihost import HeartbeatWriter

        fleet_dir = os.path.join(self.spec.workdir, "fleet")
        os.makedirs(fleet_dir, exist_ok=True)
        self._status = FleetStatusWriter(
            fleet_dir,
            num_processes=1,
            heartbeat_deadline_s=self.spec.heartbeat_deadline_s,
            status_file=self.spec.status_file,
            port=self.spec.status_port,
        ).start()
        # the conductor is its own 1-member fleet: the standard
        # heartbeat file is what makes members["0"].alive true
        self._heartbeat = HeartbeatWriter(fleet_dir, 0).start()

    def _write_status(self, entry: Mapping[str, Any]) -> None:
        if self._status is None:
            return
        extras = dict(entry)
        extras.update(
            base_dir=self._base_dir,
            cycles_since_full=self._cycles_since_full,
            publishes=len(self._published),
            escalations=self._escalations,
            idle_cycles=self._idle_cycles,
            staleness_p99_s=self._last_p99,
            served_version=(
                getattr(self._registry, "current_version", None)
                if self._registry is not None
                else None
            ),
        )
        # per-member facts ride member_extras (the snapshot schema only
        # renders supervisor fields + per-member merges); generation
        # doubles as the cycle counter in the fixed doc
        self._status.update(
            generation=self.cycle,
            member_extras={0: {"pipeline": extras}},
        )
        self._status.write_once()

    def _close(self, outcome: str) -> None:
        if self._status is not None:
            self._status.update(outcome=outcome)
            self._status.write_once()
            self._status.stop()
            self._status = None
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        if self._registry is not None:
            self._registry.stop()

    # -- the cycle -----------------------------------------------------------

    def run_cycle(self) -> Dict[str, Any]:
        """One conductor cycle. Returns a JSON-safe cycle record."""
        self.cycle += 1
        faults.fault_point(FP_CYCLE_START)
        telemetry.counter("pipeline.cycles").inc()
        entry: Dict[str, Any] = {
            "cycle": self.cycle,
            "idle": True,
            "published_version": None,
            "escalated": False,
        }
        paths = self._delta_paths()
        digest = delta_digest(paths) if paths else None
        if not paths or digest == self._last_digest:
            self._idle_cycles += 1
            telemetry.counter("pipeline.idle_cycles").inc()
            self._write_status(entry)
            return entry
        entry["idle"] = False
        with telemetry.span(
            "pipeline.cycle",
            cycle=self.cycle,
            delta_files=len(paths),
            delta_digest=digest,
        ):
            entry.update(self._refresh(paths))
        self._last_digest = digest
        self._write_status(entry)
        return entry

    def _event_times(self, paths: Sequence[str]) -> List[float]:
        times = []
        for p in paths:
            try:
                times.append(os.path.getmtime(p))
            except OSError:
                pass  # a shard replaced mid-cycle still gets retrained
        return times

    def _refresh(self, paths: Sequence[str]) -> Dict[str, Any]:
        from ..cli.train import read_input

        event_times = self._event_times(paths)
        ws = load_warm_start(self._base_dir)
        if ws.model is None:
            raise RuntimeError(
                f"{self._base_dir} holds a streamed coefficient table, "
                "not a full GAME model — the conductor needs a model "
                "base (train with --checkpoint-dir or point --base at a "
                "saved model dir)"
            )
        base_vocabs = {}
        for sub in ws.model.models.values():
            id_name = getattr(sub, "id_name", None)
            vocab = getattr(sub, "vocab", None)
            if id_name is not None and vocab is not None:
                base_vocabs[id_name] = vocab

        # the delta alone (id columns drive the touched mask) ...
        delta_spec = {**self.spec.config["input"], "paths": list(paths)}
        delta_spec.pop("ingest", None)  # scan is host-side
        delta_spec.pop("date_range", None)
        delta_spec.pop("date_range_days_ago", None)
        delta_data, _ = read_input(delta_spec, index_maps=self._index_maps)
        scan = scan_delta(delta_data, base_vocabs, paths=list(paths))

        # ... then the combined stream (base shards ∪ delta): the
        # deterministic planner keeps base chunk ids stable under the
        # appended files, so streamed reads resume bit-identically
        input_spec = dict(self.spec.config["input"])
        base_paths = input_spec.get("paths")
        if isinstance(base_paths, str):
            base_paths = [base_paths]
        input_spec["paths"] = list(base_paths) + list(paths)
        input_spec.pop("date_range", None)
        input_spec.pop("date_range_days_ago", None)
        train_data, index_maps = read_input(
            input_spec, index_maps=self._index_maps
        )
        if self._index_maps is None:
            self._index_maps = index_maps

        faults.fault_point(FP_RECONCILE)
        decision = reconcile_nearline(self.spec.registry_dir, scan)
        if decision["nearline_version"] is not None:
            self._reconciliations += 1
            telemetry.counter("pipeline.reconciliations").inc()

        touched = max(
            (c.touched_fraction for c in scan.coordinates.values()),
            default=0.0,
        )
        self._cycles_since_full += 1
        escalated = touched >= self.spec.escalate_touched_fraction or (
            self.spec.escalate_after_cycles > 0
            and self._cycles_since_full >= self.spec.escalate_after_cycles
        )
        base_version_name, _ = newest_version_metadata(self.spec.registry_dir)

        if escalated:
            faults.fault_point(FP_ESCALATE)
            telemetry.counter("pipeline.escalations").inc()
            self._escalations += 1
            gen_dir = os.path.join(
                self.spec.workdir, f"base-gen-{self.cycle:04d}"
            )
            with telemetry.span(
                "pipeline.full_retrain", cycle=self.cycle,
                touched_fraction=round(touched, 6),
            ):
                self._estimator.fit(
                    train_data,
                    checkpoint_spec=CheckpointSpec(directory=gen_dir),
                )
            # re-load through the warm-start reader so the published
            # (model, lineage) pair is exactly what the NEXT cycle will
            # warm-start from — one consistent chain, no special case
            ws_new = load_warm_start(gen_dir)
            model, lineage = ws_new.model, ws_new.lineage
            self._base_dir = gen_dir
            self._cycles_since_full = 0
        else:
            result = self._estimator.fit_incremental(
                train_data, ws, delta=scan,
                bootstrap_samples=self.spec.bootstrap_samples,
            )
            model, lineage = result.model, result.lineage

        quality = None
        if self.spec.quality_gate or self.spec.bootstrap_samples > 0:
            from ..quality import game_quality_stats

            # candidate error bars on the cycle's resident combined
            # data — the same rows the fit just saw, zero extra IO
            quality = game_quality_stats(
                model, train_data,
                num_samples=self.spec.bootstrap_samples,
            ).to_json()
            if not escalated and result.bootstrap is not None:
                quality["bootstrap"] = result.bootstrap

        try:
            published = publish_incremental(
                self.spec.registry_dir,
                model,
                self._index_maps,
                lineage,
                delta=scan,
                base_version=base_version_name,
                extra_metadata={
                    "pipeline": {
                        "cycle": self.cycle,
                        "escalated": bool(escalated),
                        "cycles_since_full": self._cycles_since_full,
                    }
                },
                reconciliation=decision,
                quality=quality,
                gate_override=not self.spec.quality_gate,
            )
        except QualityGateRefused as exc:
            # a quarantined cycle is a completed cycle: the champion
            # keeps serving, the digest cursor advances (run_cycle), so
            # the conductor does NOT retry the refused delta forever
            telemetry.counter("pipeline.quarantines").inc()
            qname = os.path.basename(exc.quarantine_path or "")
            self._quarantined.append(qname)
            logger.warning(
                "pipeline cycle %d quarantined its candidate (%s): %s",
                self.cycle, qname, exc.decision.reason,
            )
            return {
                "published_version": None,
                "quarantined_version": qname,
                "quality_gate": exc.decision.to_json(),
                "escalated": bool(escalated),
                "touched_fraction": round(float(touched), 6),
                "reconciliation": decision,
            }
        telemetry.counter("pipeline.publishes").inc()
        version_name = os.path.basename(published)
        logger.info(
            "pipeline cycle %d published %s (escalated=%s touched=%.4f)",
            self.cycle, version_name, escalated, touched,
        )
        self._published.append(version_name)

        served_ts = self._swap()
        # event time = delta shard mtime; served time = registry swap
        # confirmed. Every shard in the cycle contributes one sample so
        # the p99 reflects the OLDEST events a slow cycle kept stale.
        samples = [max(served_ts - t, 0.0) for t in event_times]
        hist = telemetry.histogram("pipeline.staleness_s")
        for s in samples:
            hist.observe(s)
        self._staleness.extend(samples)
        p99 = float(np.percentile(np.asarray(self._staleness), 99.0))
        self._last_p99 = p99
        telemetry.gauge("pipeline.event_to_served_staleness_p99_s").set(p99)
        return {
            "published_version": version_name,
            "escalated": bool(escalated),
            "touched_fraction": round(float(touched), 6),
            "reconciliation": decision,
            "staleness_p99_s": round(p99, 3),
        }

    def _swap(self) -> float:
        """Hot-swap the live registry to the freshest version; returns
        the served timestamp (wall clock by necessity — staleness is
        measured against delta-file mtimes, same contract as fleet
        heartbeat liveness)."""
        import time

        if not self.spec.serve:
            return time.time()  # photon: noqa[L006]
        if self._registry is None:
            from ..serving.registry import ModelRegistry

            # manual-refresh mode: the conductor IS the poller (it knows
            # exactly when a version landed), so no background thread
            self._registry = ModelRegistry(self.spec.registry_dir, warm=False)
        self._registry.refresh()
        return time.time()  # photon: noqa[L006]

    # -- the daemon loop -----------------------------------------------------

    def request_stop(self) -> None:
        """Ask the loop to exit after the in-flight cycle (signal-safe)."""
        self._stop.set()

    def run(self) -> Dict[str, Any]:
        """Supervised loop: cycle, sleep ``interval_s``, repeat until
        ``max_cycles`` or a stop request. Returns the run summary."""
        self._start_status()
        outcome = "completed"
        try:
            while True:
                if self._stop.is_set():
                    outcome = "interrupted"
                    break
                self.run_cycle()
                if (
                    self.spec.max_cycles
                    and self.cycle >= self.spec.max_cycles
                ):
                    break
                if self._stop.wait(self.spec.interval_s):
                    outcome = "interrupted"
                    break
        finally:
            self._close(outcome)
        return self.summary(interrupted=outcome == "interrupted")

    def summary(self, interrupted: bool = False) -> Dict[str, Any]:
        p99 = (
            float(np.percentile(np.asarray(self._staleness), 99.0))
            if self._staleness
            else None
        )
        return {
            "cycles": self.cycle,
            "idle_cycles": self._idle_cycles,
            "published_versions": list(self._published),
            "quarantined_versions": list(self._quarantined),
            "escalations": self._escalations,
            "reconciliations": self._reconciliations,
            "event_to_served_staleness_p99_s": (
                round(p99, 3) if p99 is not None else None
            ),
            "registry_dir": self.spec.registry_dir,
            "base_dir": self._base_dir,
            "interrupted": bool(interrupted),
        }
