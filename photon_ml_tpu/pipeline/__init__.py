"""The freshness tier's conductor: a supervised daemon (``cli
pipeline``) that tails a delta directory, runs masked incremental
retrains on a cadence, reconciles nearline updates, escalates to full
retrains, and hot-swaps the serving registry — with event→served
staleness p99 as the gated SLO.  See :mod:`.conductor` for the loop and
:mod:`.reconcile` for the nearline-vs-delta reconciliation rule.
"""

from .conductor import (
    FP_CYCLE_START,
    FP_ESCALATE,
    FP_RECONCILE,
    FreshnessPipeline,
    PipelineSpec,
)
from .reconcile import (
    RECONCILE_RULE,
    newest_version_metadata,
    reconcile_nearline,
)

__all__ = [
    "FP_CYCLE_START",
    "FP_ESCALATE",
    "FP_RECONCILE",
    "FreshnessPipeline",
    "PipelineSpec",
    "RECONCILE_RULE",
    "newest_version_metadata",
    "reconcile_nearline",
]
