"""Nearline-vs-delta reconciliation for the freshness conductor.

The registry accumulates versions from two writers at two timescales:
the nearline updater (per-entity residual solves, seconds) and the
incremental retrain path (masked coordinate-descent re-solves over the
combined history, minutes).  When the conductor publishes an
incremental version, any entity that the nearline tier touched since
the base AND that appears in the delta's touched set has two candidate
rows.  Somebody has to win, and the loser has to stay auditable.

The rule here is **retrain-wins-touched**: for every entity in the
delta's touched set, the masked re-solve wins.  Rationale: the masked
solve optimizes the full objective over the entity's complete combined
history, while a nearline solve is a residual mini-batch update over a
handful of recent events — strictly less evidence.  Nearline rows for
entities OUTSIDE the touched set are not carried either, because the
incremental fit warm-starts from the *base checkpoint*, not from the
nearline-published model; those entities keep their base rows
bit-identically (that invariant is what makes masked retrains cheap to
verify).  The nearline tier immediately resumes layering fresh events
on top of the newly served version, so its updates are superseded, not
lost.

Auditability: the superseded nearline version stays in the registry
with its ``nearline_seq`` / ``nearline_base_version`` metadata, and the
decision record produced here is embedded in the incremental version's
lineage (``lineage["reconciliation"]``), naming the superseded version
and sequence number.  ``/healthz`` serves the lineage of whatever
version the engine runs, so the decision round-trips to operators.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

from ..data.model_store import load_game_model_metadata
from ..serving.registry import scan_versions

RECONCILE_RULE = "retrain-wins-touched"

__all__ = [
    "RECONCILE_RULE",
    "newest_version_metadata",
    "reconcile_nearline",
]


def newest_version_metadata(
    registry_dir: str,
) -> Tuple[Optional[str], Optional[Dict[str, Any]]]:
    """Return ``(version_name, metadata)`` for the newest registry
    version, or ``(None, None)`` when the registry is empty or absent.

    An unreadable newest version (mid-publish crash debris that escaped
    the atomic-rename protocol, manual tampering) degrades to
    ``(name, None)`` rather than raising: the conductor must keep
    cycling past a corrupt tail, not wedge on it.
    """
    if not registry_dir or not os.path.isdir(registry_dir):
        return None, None
    versions = scan_versions(registry_dir)
    if not versions:
        return None, None
    _, path = versions[-1]
    name = os.path.basename(path)
    try:
        meta = load_game_model_metadata(path)
    except (OSError, ValueError, KeyError):
        return name, None
    return name, meta


def reconcile_nearline(registry_dir: str, delta_scan: Any) -> Dict[str, Any]:
    """Build the reconciliation decision record for one conductor cycle.

    ``delta_scan`` is the :class:`DeltaScan` for the cycle's delta.  The
    record is embedded verbatim into the published version's lineage so
    the decision is auditable from the registry alone.  A record is
    produced every cycle — ``nearline_version`` is ``None`` when the
    newest registry version carries no nearline metadata — so consumers
    never have to distinguish "no decision recorded" from "nothing to
    reconcile".
    """
    name, meta = newest_version_metadata(registry_dir)
    extra = (meta or {}).get("extra") or {}
    decision: Dict[str, Any] = {
        "rule": RECONCILE_RULE,
        "nearline_version": None,
        "nearline_seq": None,
        "nearline_base_version": None,
        "touched_count": sum(
            c.touched_count for c in getattr(delta_scan, "coordinates", {}).values()
        ),
    }
    if name is not None and extra.get("nearline_seq"):
        decision["nearline_version"] = name
        decision["nearline_seq"] = int(extra["nearline_seq"])
        base = extra.get("nearline_base_version")
        decision["nearline_base_version"] = (
            str(base) if base is not None else None
        )
    return decision
