"""GLM model classes: coefficients + link functions + prediction.

Reference analog: photon-api supervised/ (GeneralizedLinearModel.scala:25-77,
LogisticRegressionModel, LinearRegressionModel, PoissonRegressionModel,
SmoothedHingeLossLinearSVMModel) and photon-lib model/Coefficients.scala.
Scores are margins w.x (+offset); means apply the task link function.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.ops.sparse import SparseBatch

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    """Means + optional per-coefficient variances (Coefficients.scala:55-60)."""

    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def norm(self, order: int = 2) -> Array:
        return jnp.linalg.norm(self.means, ord=order)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """A trained GLM for one task type.

    ``task`` selects the link function: logistic -> sigmoid, poisson -> exp,
    squared/smoothed_hinge -> identity. ``compute_score`` is the raw margin
    (used by coordinate descent residuals); ``compute_mean`` applies the link
    (GeneralizedLinearModel.scala computeScore/computeMean split).
    """

    coefficients: Coefficients
    task: str = dataclasses.field(metadata=dict(static=True))

    def compute_score(self, batch: SparseBatch) -> Array:
        return batch.margins(self.coefficients.means)

    def compute_mean(self, batch: SparseBatch) -> Array:
        return self.mean_of(self.compute_score(batch))

    def mean_of(self, scores: Array) -> Array:
        loss_name = get_loss(self.task).name
        if loss_name == "logistic":
            return jax.nn.sigmoid(scores)
        if loss_name == "poisson":
            return jnp.exp(scores)
        return scores  # squared / smoothed hinge: identity link

    def predict_class(self, batch: SparseBatch, threshold: float = 0.5) -> Array:
        """Binary classification API (BinaryClassifier.predictClass analog)."""
        loss_name = get_loss(self.task).name
        if loss_name not in ("logistic", "smoothed_hinge"):
            raise ValueError(f"{self.task} is not a binary classification task")
        if loss_name == "logistic":
            return (self.compute_mean(batch) > threshold).astype(jnp.int32)
        return (self.compute_score(batch) > 0.0).astype(jnp.int32)

    def with_coefficients(self, means: Array, variances=None) -> "GeneralizedLinearModel":
        return dataclasses.replace(
            self, coefficients=Coefficients(means=means, variances=variances)
        )


def make_model(task: str, means: Array, variances=None) -> GeneralizedLinearModel:
    get_loss(task)  # validates task name
    return GeneralizedLinearModel(
        coefficients=Coefficients(means=means, variances=variances), task=task
    )
