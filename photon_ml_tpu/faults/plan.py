"""Deterministic, seed-driven fault injection: the substrate the crash
matrix (tools/chaos.py) and the recovery tests drive.

The Spark reference got failure coverage for free — every task retry,
executor loss, and shuffle refetch exercised its recovery machinery in
production. The TPU port replaced that substrate with long-lived device
state and explicit checkpoints, so its recovery paths only run when
something actually breaks. This module makes "something breaks" a
first-class, reproducible input:

- **Fault points** are named seams registered at import time
  (:func:`register_point`) with a cheap no-op call site
  (:func:`fault_point`) on the hot recovery seams: ingest decode/upload,
  checkpoint write (one point per phase of the atomic protocol), manifest
  read, registry poll/load, guarded solves, streaming chunk boundaries,
  serving dispatch. The registry is enumerable, so tests and the static
  gate (rule L016) can assert every point stays covered.
- A **FaultPlan** is a seeded schedule: per point, fire on the nth hit or
  with a seeded per-hit probability, raising a typed
  :class:`InjectedFault` / :class:`InjectedIOError`, corrupting a value
  with NaN (:func:`corrupt_array` / :func:`corrupt_health` sites), or
  calling ``os._exit`` for TRUE crash semantics — no ``finally`` blocks,
  no atexit flushes, exactly what a preemption or OOM-kill looks like.
- Plans transport across process boundaries via the
  ``PHOTON_FAULT_PLAN`` env var (JSON, or ``@/path/to/plan.json``), so a
  chaos harness can arm a subprocess fit without any code path knowing.

Everything is deterministic: nth-hit counters are process-global, and
probability draws come from ``random.Random(seed ^ crc32(point))`` — the
same plan against the same run injects the same faults.

Telemetry: every triggered injection counts ``faults.injected`` (and
``faults.injected.<point>``); exits are logged before dying so the crash
site is attributable from the log tail.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import threading
import zlib
from typing import Mapping, Optional, Sequence, Union

logger = logging.getLogger("photon_ml_tpu.faults")

ENV_VAR = "PHOTON_FAULT_PLAN"

#: Exit code injected crashes die with (distinct from the graceful-stop 75
#: and common signal codes, so a chaos harness can assert the process died
#: AT the injection point and not for some other reason).
DEFAULT_EXIT_CODE = 113

_ACTIONS = ("raise", "io", "exit", "nan")


class InjectedFault(RuntimeError):
    """A fault-injection rule fired at ``point`` (action ``raise``)."""

    def __init__(self, point: str, detail: str = ""):
        self.point = point
        super().__init__(
            f"injected fault at '{point}'" + (f": {detail}" if detail else "")
        )


class InjectedIOError(InjectedFault, OSError):
    """Action ``io``: an injected fault that IS an OSError, so transient-
    IO retry paths (ingest decode, registry load) treat it exactly like a
    real flaky read."""


class FaultPlanError(ValueError):
    """A plan document that cannot work: unknown action, conflicting
    triggers, malformed JSON."""


@dataclasses.dataclass(frozen=True)
class FaultPointInfo:
    """Registry metadata for one injection seam."""

    name: str
    write_path: bool  # checkpoint/publish write protocol: chaos-matrix set
    description: str
    distributed: bool = False  # multi-process seam: fleet crash-matrix set


_REGISTRY: dict[str, FaultPointInfo] = {}
_REGISTRY_LOCK = threading.Lock()


def register_point(
    name: str,
    *,
    write_path: bool = False,
    distributed: bool = False,
    description: str = "",
) -> str:
    """Declare an injection seam (module level, import time). Idempotent;
    re-registering with a DIFFERENT write_path/distributed classification
    is a programming error. Returns ``name`` so call sites bind it to a
    module constant."""
    with _REGISTRY_LOCK:
        existing = _REGISTRY.get(name)
        if existing is not None:
            if existing.write_path != write_path:
                raise ValueError(
                    f"fault point '{name}' already registered with "
                    f"write_path={existing.write_path}"
                )
            if existing.distributed != distributed:
                raise ValueError(
                    f"fault point '{name}' already registered with "
                    f"distributed={existing.distributed}"
                )
            return name
        _REGISTRY[name] = FaultPointInfo(
            name=name,
            write_path=write_path,
            description=description,
            distributed=distributed,
        )
    return name


def registered_points() -> dict[str, FaultPointInfo]:
    """Snapshot of every registered fault point (import the package
    first: registration happens at module import)."""
    with _REGISTRY_LOCK:
        return dict(_REGISTRY)


def write_path_points() -> list[str]:
    """The checkpoint/publish write-protocol points — the set the crash
    matrix (tools/chaos.py) enumerates, sorted for determinism."""
    with _REGISTRY_LOCK:
        return sorted(n for n, i in _REGISTRY.items() if i.write_path)


def distributed_points() -> list[str]:
    """The multi-process seams — the set the DISTRIBUTED crash matrix
    (tools/chaos.py fleet rows) enumerates, sorted for determinism."""
    with _REGISTRY_LOCK:
        return sorted(n for n, i in _REGISTRY.items() if i.distributed)


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """When and how one point fires.

    Exactly one trigger: ``nth`` (fire on the nth hit, 1-based; default
    1) or ``probability`` (seeded per-hit coin). ``action``: ``raise``
    (typed InjectedFault), ``io`` (InjectedIOError — an OSError, for
    transient-retry paths), ``exit`` (``os._exit(exit_code)`` — a true
    crash), or ``nan`` (value corruption at :func:`corrupt_array` /
    :func:`corrupt_health` sites; at a plain :func:`fault_point` site it
    degrades to ``raise``).
    """

    point: str
    action: str = "raise"
    nth: Optional[int] = None
    probability: Optional[float] = None
    exit_code: int = DEFAULT_EXIT_CODE

    def __post_init__(self):
        if self.action not in _ACTIONS:
            raise FaultPlanError(
                f"unknown fault action {self.action!r} (known: {_ACTIONS})"
            )
        if self.nth is not None and self.probability is not None:
            raise FaultPlanError(
                f"rule for '{self.point}': nth and probability are "
                "mutually exclusive"
            )
        if self.nth is not None and self.nth < 1:
            raise FaultPlanError(
                f"rule for '{self.point}': nth must be >= 1 (1-based hit)"
            )
        if self.probability is not None and not (
            0.0 < self.probability <= 1.0
        ):
            raise FaultPlanError(
                f"rule for '{self.point}': probability must be in (0, 1]"
            )

    def to_json(self) -> dict:
        out: dict = {"point": self.point, "action": self.action}
        if self.nth is not None:
            out["nth"] = self.nth
        if self.probability is not None:
            out["probability"] = self.probability
        if self.exit_code != DEFAULT_EXIT_CODE:
            out["exit_code"] = self.exit_code
        return out


class FaultPlan:
    """A seeded schedule of :class:`FaultRule`; thread-safe hit counting.

    Determinism contract: nth-hit counters are process-global per point,
    and probability draws come from a per-point ``random.Random`` seeded
    ``seed ^ crc32(point)`` — independent of dict order, hashing, or
    which other points fire.
    """

    def __init__(self, rules: Sequence[FaultRule], seed: int = 0):
        self.seed = int(seed)
        self._rules: dict[str, FaultRule] = {}
        for rule in rules:
            if rule.point in self._rules:
                raise FaultPlanError(
                    f"duplicate rule for point '{rule.point}'"
                )
            self._rules[rule.point] = rule
        self._hits: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {
            p: random.Random(self.seed ^ zlib.crc32(p.encode("utf-8")))
            for p, r in self._rules.items()
            if r.probability is not None
        }
        self._lock = threading.Lock()

    @property
    def points(self) -> list[str]:
        return sorted(self._rules)

    def hit(self, point: str) -> Optional[FaultRule]:
        """Record one hit of ``point``; the rule when this hit fires."""
        rule = self._rules.get(point)
        if rule is None:
            return None
        with self._lock:
            count = self._hits.get(point, 0) + 1
            self._hits[point] = count
            if rule.probability is not None:
                fire = self._rngs[point].random() < rule.probability
            else:
                fire = count == (rule.nth or 1)
        return rule if fire else None

    def hit_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._hits)

    def unregistered_points(self) -> list[str]:
        """Rules naming no REGISTERED point (typo'd plans inject nothing;
        the chaos harness refuses them)."""
        registry = registered_points()
        return sorted(p for p in self._rules if p not in registry)

    def to_json(self) -> dict:
        return {
            "seed": self.seed,
            "rules": [r.to_json() for r in self._rules.values()],
        }

    @classmethod
    def from_json(cls, doc: Union[str, Mapping]) -> "FaultPlan":
        if isinstance(doc, str):
            try:
                doc = json.loads(doc)
            except ValueError as e:
                raise FaultPlanError(f"malformed fault-plan JSON: {e}") from None
        if not isinstance(doc, Mapping):
            raise FaultPlanError(
                f"fault plan must be an object, got {type(doc).__name__}"
            )
        unknown = set(doc) - {"seed", "rules"}
        if unknown:
            raise FaultPlanError(
                f"unknown fault-plan keys: {sorted(unknown)}"
            )
        raw_rules = doc.get("rules") or []
        rules = []
        known = {f.name for f in dataclasses.fields(FaultRule)}
        for raw in raw_rules:
            if not isinstance(raw, Mapping) or "point" not in raw:
                raise FaultPlanError(
                    f"each rule needs at least a 'point': {raw!r}"
                )
            bad = set(raw) - known
            if bad:
                raise FaultPlanError(
                    f"unknown rule keys for '{raw['point']}': {sorted(bad)}"
                )
            rules.append(FaultRule(**raw))
        return cls(rules, seed=int(doc.get("seed", 0)))


# ---------------------------------------------------------------------------
# process-global activation
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultPlan] = None


def _plan_from_env() -> Optional[FaultPlan]:
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:], encoding="utf-8") as fh:
            raw = fh.read()
    return FaultPlan.from_json(raw)


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Activate ``plan`` process-wide (None deactivates); returns it."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def install_from_env() -> Optional[FaultPlan]:
    """(Re)read ``PHOTON_FAULT_PLAN`` and activate the plan it carries —
    called once at package import, so subprocesses armed via env inject
    without any code path cooperating."""
    return install_plan(_plan_from_env())


def clear_plan() -> None:
    install_plan(None)


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def warn_if_armed() -> bool:
    """Log loudly when a fault plan is active (drivers call this at
    startup: an armed production run should never be a surprise)."""
    plan = _ACTIVE
    if plan is None:
        return False
    logger.warning(
        "FAULT INJECTION ARMED: plan seed=%d rules=%s — this process WILL "
        "fail on purpose", plan.seed, plan.points,
    )
    return True


# ---------------------------------------------------------------------------
# call-site API
# ---------------------------------------------------------------------------


def _record_injection(point: str, rule: FaultRule) -> None:
    # lazy import: telemetry must stay importable without faults and
    # vice versa
    from photon_ml_tpu import telemetry

    telemetry.counter("faults.injected").inc()
    telemetry.counter(f"faults.injected.{point}").inc()
    logger.warning(
        "injecting fault at '%s' (action=%s)", point, rule.action
    )


def _trigger(point: str, rule: FaultRule):
    _record_injection(point, rule)
    if rule.action == "exit":
        # true crash semantics: no exception unwinding, no finally
        # blocks, no atexit — flush logging first so the crash site is
        # visible in the log tail, then die
        logging.shutdown()
        os._exit(rule.exit_code)
    if rule.action == "io":
        raise InjectedIOError(point)
    raise InjectedFault(point)


def fault_point(point: str) -> None:
    """The no-op-by-default injection seam. With no active plan this is
    one global read and a dict miss; with a plan whose rule fires it
    raises the typed error or crashes the process."""
    plan = _ACTIVE
    if plan is None:
        return
    rule = plan.hit(point)
    if rule is not None:
        _trigger(point, rule)


def corrupt_array(point: str, array):
    """Value-corruption seam: returns ``array`` untouched, or with its
    first element poisoned to NaN when the plan fires a ``nan`` rule here
    (``raise``/``io``/``exit`` rules behave as at :func:`fault_point`).
    Used on solve results so the guard's divergence recovery is testable
    on demand."""
    plan = _ACTIVE
    if plan is None:
        return array
    rule = plan.hit(point)
    if rule is None:
        return array
    if rule.action != "nan":
        _trigger(point, rule)
    _record_injection(point, rule)
    import numpy as np

    if isinstance(array, np.ndarray):
        out = array.copy()
        out.reshape(-1)[0] = np.nan
        return out
    # a jax array: functional poke at the first element
    flat = array.reshape(-1)
    return flat.at[0].set(float("nan")).reshape(array.shape)


def corrupt_health(point: str, health):
    """Health-flip seam: returns the device/bool health value, forced
    falsy when a ``nan`` rule fires (other actions raise/crash as at
    :func:`fault_point`). Lets the coordinate-descent guard path — whose
    solve results are model objects, not a single array — inject a
    divergence without touching model internals."""
    plan = _ACTIVE
    if plan is None:
        return health
    rule = plan.hit(point)
    if rule is None:
        return health
    if rule.action != "nan":
        _trigger(point, rule)
    _record_injection(point, rule)
    import jax.numpy as jnp

    return jnp.bool_(False)


# arm from the environment at import: chaos subprocesses set
# PHOTON_FAULT_PLAN before exec and need no further cooperation
install_from_env()
