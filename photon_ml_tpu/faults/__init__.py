"""Deterministic fault injection (see :mod:`photon_ml_tpu.faults.plan`).

Public surface::

    from photon_ml_tpu import faults

    _FP = faults.register_point("my.seam", write_path=False)   # import time
    faults.fault_point(_FP)                                    # call site

    plan = faults.FaultPlan([faults.FaultRule("my.seam", action="exit")])
    faults.install_plan(plan)          # in-process, or via PHOTON_FAULT_PLAN
"""

from photon_ml_tpu.faults.plan import (
    DEFAULT_EXIT_CODE,
    ENV_VAR,
    FaultPlan,
    FaultPlanError,
    FaultPointInfo,
    FaultRule,
    InjectedFault,
    InjectedIOError,
    active_plan,
    clear_plan,
    corrupt_array,
    corrupt_health,
    distributed_points,
    fault_point,
    install_from_env,
    install_plan,
    register_point,
    registered_points,
    warn_if_armed,
    write_path_points,
)

__all__ = [
    "DEFAULT_EXIT_CODE",
    "ENV_VAR",
    "FaultPlan",
    "FaultPlanError",
    "FaultPointInfo",
    "FaultRule",
    "InjectedFault",
    "InjectedIOError",
    "active_plan",
    "clear_plan",
    "corrupt_array",
    "corrupt_health",
    "distributed_points",
    "fault_point",
    "install_from_env",
    "install_plan",
    "register_point",
    "registered_points",
    "warn_if_armed",
    "write_path_points",
]
