"""Phase timers and logging setup.

Reference analog: photon-lib util/Timed.scala:33-77 (named duration blocks
logged around every driver phase, cli/game/training/Driver.scala:60-86) and
util/Timer.scala; PhotonLogger's role (SLF4J to HDFS) collapses to stdlib
logging configured once per process.
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Iterator, Optional

logger = logging.getLogger("photon_ml_tpu")


def setup_logging(level: int = logging.INFO, log_file: Optional[str] = None) -> None:
    """Configure the photon_ml_tpu logger tree (PhotonLogger analog).

    Idempotent per TARGET: repeated calls never duplicate a handler, but a
    later call adding a (new) log file still takes effect."""
    import os

    root = logging.getLogger("photon_ml_tpu")
    root.setLevel(level)
    handler: logging.Handler
    if log_file is not None:
        target = os.path.abspath(log_file)
        if any(
            isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == target
            for h in root.handlers
        ):
            return
        handler = logging.FileHandler(log_file)
    else:
        if any(
            isinstance(h, logging.StreamHandler)
            and not isinstance(h, logging.FileHandler)
            for h in root.handlers
        ):
            return
        handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    root.addHandler(handler)


class Timer:
    """Simple stopwatch (util/Timer.scala analog)."""

    def __init__(self):
        self._start: Optional[float] = None
        self.seconds: float = 0.0

    def start(self) -> "Timer":
        self._start = time.time()
        return self

    def stop(self) -> float:
        if self._start is None:
            raise RuntimeError("Timer.stop() before start()")
        self.seconds = time.time() - self._start
        self._start = None
        return self.seconds


@contextmanager
def timed(name: str, log: logging.Logger = logger) -> Iterator[Timer]:
    """Log the wall-clock duration of a named phase (Timed.scala analog)."""
    t = Timer().start()
    try:
        yield t
    finally:
        t.stop()
        log.info("%s: %.3fs", name, t.seconds)
