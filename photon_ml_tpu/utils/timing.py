"""Phase timers and logging setup.

Reference analog: photon-lib util/Timed.scala:33-77 (named duration blocks
logged around every driver phase, cli/game/training/Driver.scala:60-86) and
util/Timer.scala; PhotonLogger's role (SLF4J to HDFS) collapses to stdlib
logging configured once per process.

``timed()`` is a thin wrapper over :func:`photon_ml_tpu.telemetry.trace.span`:
every timed phase is also a node of the telemetry span tree, so the legacy
log lines and the JSONL/Perfetto trace always agree.
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Iterator, Optional

from photon_ml_tpu.telemetry import trace

logger = logging.getLogger("photon_ml_tpu")


def setup_logging(level: int = logging.INFO, log_file: Optional[str] = None) -> None:
    """Configure the photon_ml_tpu logger tree (PhotonLogger analog).

    Idempotent per TARGET: repeated calls never duplicate a handler, but a
    later call adding a (new) log file still takes effect."""
    import os

    root = logging.getLogger("photon_ml_tpu")
    root.setLevel(level)
    handler: logging.Handler
    if log_file is not None:
        target = os.path.abspath(log_file)
        if any(
            isinstance(h, logging.FileHandler)
            and getattr(h, "baseFilename", None) == target
            for h in root.handlers
        ):
            return
        # hand FileHandler the RESOLVED path: baseFilename is derived from
        # its argument, so a relative log_file plus a later os.chdir would
        # defeat the dedup check above (handler and check must agree)
        handler = logging.FileHandler(target)
    else:
        if any(
            isinstance(h, logging.StreamHandler)
            and not isinstance(h, logging.FileHandler)
            for h in root.handlers
        ):
            return
        handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    root.addHandler(handler)


class Timer:
    """Simple stopwatch (util/Timer.scala analog).

    Monotonic clock: wall-clock steps (NTP slew, DST) must never corrupt a
    phase duration."""

    def __init__(self):
        self._start: Optional[float] = None
        self.seconds: float = 0.0

    def start(self) -> "Timer":
        self._start = time.monotonic()
        return self

    def stop(self) -> float:
        if self._start is None:
            raise RuntimeError("Timer.stop() before start()")
        self.seconds = time.monotonic() - self._start
        self._start = None
        return self.seconds


@contextmanager
def timed(name: str, log: logging.Logger = logger) -> Iterator[Timer]:
    """Log the wall-clock duration of a named phase (Timed.scala analog)
    and record it as a telemetry span of the same name."""
    with trace.span(name):
        t = Timer().start()
        try:
            yield t
        finally:
            t.stop()
            log.info("%s: %.3fs", name, t.seconds)
