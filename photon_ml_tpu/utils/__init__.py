from photon_ml_tpu.utils.timing import Timer, logger, setup_logging, timed  # noqa: F401
