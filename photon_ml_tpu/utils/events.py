"""Event system: a typed pub/sub bus for training lifecycle events.

Reference analog: photon-client event/ (EventEmitter.scala:24-72 —
register/send/clear listener mixin; listeners loaded by class name from the
--event-listeners flag, Driver.scala:110-118) and the event types
PhotonSetupEvent / TrainingStartEvent / TrainingFinishEvent /
PhotonOptimizationLogEvent. Listeners are plain callables here; the
training driver and GameEstimator emit on one shared emitter instance.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Mapping, Optional

logger = logging.getLogger("photon_ml_tpu.events")


@dataclasses.dataclass(frozen=True)
class Event:
    pass


@dataclasses.dataclass(frozen=True)
class SetupEvent(Event):
    """PhotonSetupEvent analog: emitted once with the parsed config."""

    config: Mapping[str, Any]


@dataclasses.dataclass(frozen=True)
class TrainingStartEvent(Event):
    num_rows: int


@dataclasses.dataclass(frozen=True)
class TrainingFinishEvent(Event):
    """End-of-training event; ``metrics_snapshot`` carries the process
    telemetry registry state (``telemetry.snapshot()``) at finish time, so
    listeners see fetch/compile/solve counters without importing telemetry.

    Counters are CUMULATIVE across the process, not per-fit: repeated
    ``fit()`` calls (or ``fit_grid`` combinations) each report the running
    totals — diff consecutive snapshots for per-run deltas."""

    best_metric: Optional[float]
    seconds: float
    metrics_snapshot: Optional[Mapping[str, Any]] = None


@dataclasses.dataclass(frozen=True)
class OptimizationLogEvent(Event):
    """PhotonOptimizationLogEvent analog: one per (CD iteration,
    coordinate) update, carrying that step's telemetry entry."""

    iteration: int
    coordinate: str
    seconds: float
    metrics: Optional[Mapping[str, float]] = None


def load_listener(spec: str) -> Callable[[Event], None]:
    """Import one listener from a dotted-path spec — the --event-listeners
    class-name loading of the reference driver (Driver.scala:110-118).

    ``"pkg.mod:name"`` (or ``"pkg.mod.name"``) must resolve to either a
    callable taking one event, or a zero-arg class whose INSTANCE is the
    listener (classes are instantiated, matching the reference's
    newInstance())."""
    import importlib
    import inspect

    if ":" in spec:
        mod_name, attr = spec.split(":", 1)
    else:
        mod_name, _, attr = spec.rpartition(".")
    if not mod_name or not attr:
        raise ValueError(f"listener spec '{spec}' is not a dotted path")
    try:
        target = getattr(importlib.import_module(mod_name), attr)
        if inspect.isclass(target):
            target = target()
    except (ImportError, AttributeError, TypeError) as e:
        raise ValueError(f"cannot load event listener '{spec}': {e}") from e
    if not callable(target):
        raise ValueError(f"event listener '{spec}' is not callable")
    return target


def load_listeners(specs) -> list[Callable[[Event], None]]:
    """Import every listener named by ``specs`` (sequence of dotted paths)."""
    return [load_listener(s) for s in specs]


class EventEmitter:
    """register/send/clear listener registry (EventEmitter.scala analog).

    A listener raising is logged and skipped — observability must never
    fail training. ``register`` is idempotent (a listener registered twice
    would double-fire on every OptimizationLogEvent) and every send bumps a
    per-event-type telemetry counter (``events.<EventClassName>``)."""

    def __init__(self):
        self._listeners: list[Callable[[Event], None]] = []

    def register(self, listener: Callable[[Event], None]) -> None:
        if listener not in self._listeners:
            self._listeners.append(listener)

    def unregister(self, listener: Callable[[Event], None]) -> None:
        """Remove one listener; unknown listeners are a no-op."""
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def clear(self) -> None:
        self._listeners.clear()

    def send(self, event: Event) -> None:
        from photon_ml_tpu.telemetry.metrics import counter

        counter(f"events.{type(event).__name__}").inc()
        for listener in self._listeners:
            try:
                listener(event)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "event listener %r failed on %s",
                    listener,
                    type(event).__name__,
                )
