"""Crash-durable file writes: write-to-tmp, fsync, rename.

The blessed atomic persistence primitives (tools/check.py lint L008 rejects
raw ``np.savez``/``json.dump``-to-final-path writes in library code outside
this module and the model/checkpoint stores built on it). The contract:
after ``atomic_*`` returns, the destination path holds either the complete
new content or — if the process died mid-write — whatever was there before;
a reader can never observe a truncated file. The fsync before ``os.replace``
matters: without it a crash AFTER the rename can still surface an empty
file on ext4/xfs (rename is metadata-journaled ahead of data).
"""

from __future__ import annotations

import json
import os


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed entry survives a crash (POSIX
    renames are durable only once the parent directory is synced)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse directory fsync; best effort
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_json(path: str, obj, **dump_kwargs) -> None:
    """Serialize ``obj`` as JSON at ``path`` atomically and durably."""
    atomic_write_bytes(
        path, json.dumps(obj, **dump_kwargs).encode("utf-8")
    )


def atomic_write_npz(path: str, **arrays) -> None:
    """Atomic + fsynced npz write so a crash mid-save can never leave a
    truncated array container next to valid metadata."""
    import numpy as np

    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_write_npy(path: str, arr) -> None:
    """Atomic + fsynced single-array .npy write. Streams ``np.save``
    straight into the tmp file — no in-memory serialization, so saving a
    huge table (the mmap index store's hash arrays) costs no extra RAM."""
    import numpy as np

    tmp = path + ".tmp.npy"
    with open(tmp, "wb") as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
