"""Device-resident scoring engine: compile the model once, serve forever.

The batch scorer (``RandomEffectModel.score``) redoes host-side bucket
grouping and re-uploads coefficient tables on every call — fine for one
pass over a dataset, fatal for a request path. :class:`ScoringEngine`
instead:

- uploads the model ONCE at load: fixed-effect weight vectors plus
  per-coordinate random-effect coefficient tables and projections go to
  HBM (after :func:`telemetry.memory.check_headroom` predicts the upload
  fits), and the entity-id -> (bucket, position) lookup stays host-side;
- serves requests through ONE jit-compiled score function whose traces
  are keyed by padded batch-size bucket (powers of two up to
  ``max_batch``); :meth:`warmup` executes every bucket at startup so
  steady state never recompiles. The compiled function is shared via an
  ``lru_cache`` keyed by model STRUCTURE, so hot-swapping to a same-shaped
  model version reuses the existing executable outright;
- scores entities unseen at training time as fixed-effect-only
  (the random-effect contribution is 0), matching
  ``RandomEffectModel.score``'s unseen-entity semantics exactly.

This module is a serving HOT PATH: tools/check.py lint L010 rejects
device->host syncs here (``jax.device_get``, ``float()`` on arrays,
``np.asarray`` on jax arrays) — the one sanctioned fetch is
``telemetry.sync_fetch``.

Request row schema (JSON-safe)::

    {"features": {"<shard>": [[col, value], ...]},   # training feature ids
     "ids": {"<id_name>": "<entity value>"},
     "offset": 0.0}

Features may instead be named — ``[name, term, value]`` or
``{"name": ..., "term": ..., "value": ...}`` — and are then resolved
through the model's persisted ``feature-indexes/`` maps (unknown names
score 0 and count ``serving.unknown_features``, the index-map default
semantics of training).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import time
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.index_map import feature_key
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.parallel import sharding as psharding
from photon_ml_tpu.quality import drift as quality_drift


class BadRequest(ValueError):
    """A score request is malformed (unknown shard schema, feature count
    over ``max_row_nnz``, unresolvable named feature without an index
    map). Servers map this to HTTP 400, never 500."""


def bucket_sizes_for(max_batch: int) -> tuple[int, ...]:
    """Padded batch-size buckets: powers of two up to (and always
    including) ``max_batch`` — each bucket is one compiled trace."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return tuple(sizes)


def _coordinate_terms(coords: tuple, eshard=None):
    """The per-coordinate margin kernel shared by the score and margin
    executables: a traceable ``terms(batch, shards, re_inputs, tables)``
    yielding ``(kind, per-row segment sum)`` for every coordinate spec in
    ``coords`` — ``kind`` is ``"fixed"``/``"re"`` so callers can gate the
    fixed-effect contribution (the fleet-router protocol computes FE on
    exactly one member per row)."""
    re_slots = {}
    for ci, spec in enumerate(coords):
        if spec[0] == "re":
            re_slots[ci] = len(re_slots)

    def _pin(table):
        # keep entity-sharded tables entity-sharded through the trace
        if eshard is None:
            return table
        return jax.lax.with_sharding_constraint(table, eshard)

    def terms(batch, shards, re_inputs, tables):
        for ci, spec in enumerate(coords):
            values, rows, cols = shards[spec[1]]
            if spec[0] == "fixed":
                contrib = values * jnp.take(tables[ci], cols, fill_value=0)
            else:
                row_bucket, row_pos = re_inputs[re_slots[ci]]
                bkt_n = row_bucket[rows]  # padded rows -> row batch-1: -1
                pos_n = row_pos[rows]
                contrib = jnp.zeros_like(values)
                for b_idx, (proj, coef) in enumerate(tables[ci]):
                    proj, coef = _pin(proj), _pin(coef)
                    num_entities, local_dim = proj.shape
                    p = jnp.clip(pos_n, 0, num_entities - 1)
                    if local_dim <= 64:
                        # transposed compare-scan (the K<=64 kernel of
                        # RandomEffectModel.score): each column matches at
                        # most one projection slot, so the masked sum IS
                        # the coefficient lookup
                        w_n = jnp.sum(
                            jnp.where(
                                proj.T[:, p] == cols[None, :],
                                coef.T[:, p],
                                0.0,
                            ),
                            axis=0,
                        )
                    else:
                        proj_rows = proj[p]
                        k = jax.vmap(jnp.searchsorted)(proj_rows, cols)
                        k = jnp.minimum(k, local_dim - 1)
                        hit = (
                            jnp.take_along_axis(
                                proj_rows, k[:, None], axis=1
                            )[:, 0]
                            == cols
                        )
                        w_n = jnp.where(
                            hit,
                            jnp.take_along_axis(
                                coef[p], k[:, None], axis=1
                            )[:, 0],
                            0.0,
                        )
                    contrib = contrib + jnp.where(
                        bkt_n == b_idx, values * w_n, 0.0
                    )
            yield spec[0], jax.ops.segment_sum(
                contrib, rows, num_segments=batch, indices_are_sorted=True
            )

    return terms


@functools.lru_cache(maxsize=32)  # bounded: a long-lived server swapping
# structurally different versions must not accumulate executables forever
def _compiled_score_fn(link: str, coords: tuple, eshard=None):
    """One jitted score function per model STRUCTURE.

    ``coords`` is a static spec per coordinate: ``("fixed", shard_idx)``
    or ``("re", shard_idx, num_buckets)``. Table VALUES arrive as traced
    arguments, so two model versions with the same structure (the common
    hot-swap case: retrained coefficients, same entities/features) share
    one executable and swap with ZERO recompiles. Batch size and table
    shapes are read off the traced arguments — each padded bucket size is
    its own trace inside the one jit cache.

    ``eshard`` (a hashable ``NamedSharding``, or None for the replicated
    single-device engine) pins every random-effect table's entity axis to
    the serving mesh INSIDE the trace: without the constraint the
    compiler is free to "helpfully" replicate a table that only fits
    sharded. With it, the per-row coefficient gathers execute on the
    shard that owns each entity's rows (GSPMD inserts the cross-shard
    combine) and the request path stays free of host syncs — the L013
    gate walks this function like any other.
    """
    terms = _coordinate_terms(coords, eshard)

    def fn(offsets, shards, re_inputs, tables):
        batch = offsets.shape[0]
        total = jnp.zeros((batch,), jnp.float32)
        for _kind, seg in terms(batch, shards, re_inputs, tables):
            total = total + seg
        scores = total + offsets
        if link == "logistic":
            return jax.nn.sigmoid(scores)
        if link == "poisson":
            return jnp.exp(scores)
        return scores

    # instrumented (lint L011): each padded batch-size bucket is one
    # accounted executable — per-bucket compile time + cost surface in
    # healthz/metricsz and the run report's top-executables table.
    # multi_shape: the bucket set IS the design; warmup compiling every
    # bucket must not read as a recompile storm
    return telemetry.instrumented_jit(
        fn, name="serving_score", multi_shape=True
    )


@functools.lru_cache(maxsize=32)
def _compiled_margin_fn(coords: tuple, eshard=None):
    """The fleet-member margin executable: RAW additive margins — no
    link, no offset — with the fixed-effect contribution gated per row by
    a traced 0/1 ``fe_gate`` vector.

    This is the member half of exact fleet folding: the GAME score is a
    SUM of per-coordinate margins, so members return partial sums, the
    router adds them (plus the offset, once) and applies the link.
    Gating FE per row (instead of per batch) keeps one executable per
    bucket whichever member the router designates as a row's FE owner.
    ``offsets`` is accepted for shape/assembly symmetry with the score
    executable and deliberately NOT added."""
    terms = _coordinate_terms(coords, eshard)

    def fn(fe_gate, offsets, shards, re_inputs, tables):
        batch = offsets.shape[0]
        total = jnp.zeros((batch,), jnp.float32)
        for kind, seg in terms(batch, shards, re_inputs, tables):
            total = total + (fe_gate * seg if kind == "fixed" else seg)
        return total

    return telemetry.instrumented_jit(
        fn, name="serving_margin", multi_shape=True
    )


@functools.lru_cache(maxsize=8)
def _row_update_fn(eshard=None):
    """The nearline row-swap executable: scatter re-solved coefficient
    rows into a table, keeping an entity-sharded table pinned to its
    sharding (the scatter indices are replicated, so each shard applies
    only the rows it owns). Non-donating on purpose: the OLD table tuple
    stays valid for any score call still holding it — donation here
    would be the freed-buffer aliasing hazard of PR 10 all over again.

    multi_shape: one signature per (table shape, update-batch size) by
    design; the nearline updater pads update batches to power-of-two
    sizes so steady state re-uses a handful of traces."""

    def fn(table, pos, rows):
        out = table.at[pos].set(rows)
        if eshard is not None:
            out = jax.lax.with_sharding_constraint(out, eshard)
        return out

    return telemetry.instrumented_jit(
        fn, name="serving_row_update", multi_shape=True
    )


def _restore_re_coordinate(
    model: GameModel,
    coord: str,
    ckpt_dir: str,
    mesh=None,
    entity_axis: Optional[str] = None,
) -> GameModel:
    """Replace one random-effect coordinate's coefficient table with the
    newest certified streamed checkpoint, placed DIRECTLY onto the
    serving mesh (``restore_placed``: per-device reads over mmap'd shard
    files — the table never materializes on one host). The
    restore-to-serving path of ROADMAP item 1: train sharded, checkpoint
    sharded, serve sharded, no gather in between."""
    from photon_ml_tpu.data.model_store import ModelLoadError
    from photon_ml_tpu.game.checkpoint import StreamingCheckpointManager

    sub = model.models.get(coord)
    if not isinstance(sub, RandomEffectModel):
        raise ModelLoadError(
            ckpt_dir,
            f"re_checkpoints names coordinate '{coord}', which is not a "
            f"random-effect coordinate of the model "
            f"(has: {sorted(model.models)})",
        )
    if len(sub.buckets) != 1:
        raise ModelLoadError(
            ckpt_dir,
            f"coordinate '{coord}' has {len(sub.buckets)} geometry "
            "buckets; streamed checkpoints hold ONE dense [E, K] table, "
            "so only single-bucket coordinates restore from one",
        )
    manager = StreamingCheckpointManager.open_for_restore(ckpt_dir)
    restore = manager.restore_placed(mesh=mesh, axis=entity_axis)
    if restore is None:
        raise ModelLoadError(
            ckpt_dir,
            "no certified streamed checkpoint to restore the serving "
            f"table for coordinate '{coord}' from",
        )
    bm = sub.buckets[0]
    got = tuple(int(d) for d in restore.coefficients.shape)
    want = tuple(int(d) for d in bm.coefficients.shape)
    if got != want:
        raise ModelLoadError(
            ckpt_dir,
            f"checkpoint table shape {got} does not match coordinate "
            f"'{coord}' table shape {want}",
        )
    return model.with_model(
        coord,
        dataclasses.replace(
            sub,
            buckets=(
                dataclasses.replace(bm, coefficients=restore.coefficients),
            ),
        ),
    )


class ScoringEngine:
    """A :class:`GameModel` compiled into long-lived, device-resident
    scoring form. Structurally immutable after construction — the
    registry hot-swaps by replacing the engine reference while in-flight
    requests finish on the old one. The ONE sanctioned mutation is
    :meth:`apply_re_rows` (nearline personalization): per-entity
    coefficient rows are re-solved online and swapped in by replacing
    the whole device-table tuple atomically under the engine's version
    lock — a reader sees the old tables or the new ones, never a torn
    mix.

    With ``mesh=`` (a mesh carrying a ``model``/``entity`` axis), every
    random-effect coefficient/projection table is placed
    ENTITY-SHARDED over that axis via
    :func:`photon_ml_tpu.parallel.sharding.entity_sharding` — the same
    one placement definition training uses, so a sharded training
    checkpoint restores straight onto the serving mesh
    (``load(..., re_checkpoints=...)``) with no resharding. Fixed-effect
    vectors and request inputs stay replicated; the jitted score
    function pins the tables sharded so per-row gathers run on the
    owning shard.
    """

    def __init__(
        self,
        model: GameModel,
        index_maps: Optional[Mapping] = None,
        max_batch: int = 64,
        max_row_nnz: int = 128,
        version: str = "unversioned",
        mesh=None,
        entity_axis: Optional[str] = None,
        lineage: Optional[dict] = None,
    ):
        if max_row_nnz < 1:
            raise ValueError("max_row_nnz must be >= 1")
        self.model = model
        self.version = version
        # training-ancestry record from the version's metadata (published
        # via publish_version(lineage=...)); surfaced on /healthz so a
        # running model names its warm-start checkpoint and delta
        self.lineage = lineage
        self.max_batch = int(max_batch)
        self.max_row_nnz = int(max_row_nnz)
        self.task = model.task
        self.bucket_sizes = bucket_sizes_for(self.max_batch)
        self.warm = False
        self._link = get_loss(model.task).name
        self._index_maps = dict(index_maps or {})
        self.mesh = mesh
        self.entity_axis = None
        self._eshard = None
        if mesh is not None:
            self.entity_axis = entity_axis or psharding.model_axis(mesh)
            if self.entity_axis is None:
                raise ValueError(
                    f"serving mesh {dict(mesh.shape)} has no model/entity "
                    "axis to shard coefficient tables over"
                )
            self._eshard = psharding.entity_sharding(mesh, self.entity_axis)

        shard_names: list[str] = []
        shard_dims: dict[str, Optional[int]] = {}
        coords: list[tuple] = []
        host_tables: list = []
        re_hosts: list[tuple] = []
        predicted_bytes = 0
        for name, sub in model.models.items():
            if isinstance(sub, FixedEffectModel):
                si = self._shard_slot(shard_names, sub.shard_name)
                shard_dims[sub.shard_name] = int(sub.coefficients.shape[0])
                coords.append(("fixed", si))
                host_tables.append(sub.coefficients)
                predicted_bytes += telemetry.memory.estimate_table_bytes(
                    1, sub.coefficients.shape[0]
                )
            elif isinstance(sub, RandomEffectModel):
                si = self._shard_slot(shard_names, sub.shard_name)
                coords.append(("re", si, len(sub.buckets)))
                host_tables.append(
                    tuple(
                        (bm.projection, bm.coefficients) for bm in sub.buckets
                    )
                )
                for bm in sub.buckets:
                    num_e, local_k = bm.coefficients.shape
                    if (
                        self._eshard is not None
                        and num_e % psharding.axis_size(
                            self.mesh, self.entity_axis
                        )
                    ):
                        # the valid-topology listing of elastic restore,
                        # not a bare modulus: the operator picking a
                        # serving mesh needs the sizes that CAN hold
                        # coordinate `name`'s table
                        raise psharding.entity_axis_mismatch(
                            num_e, self.entity_axis,
                            psharding.axis_size(self.mesh, self.entity_axis),
                            what=(
                                f"shard coordinate '{name}' on the "
                                "serving mesh"
                            ),
                        )
                    # coefficients + int32 projection, both 4-byte
                    predicted_bytes += 2 * telemetry.memory.estimate_table_bytes(
                        num_e, local_k
                    )
                re_hosts.append(
                    (
                        sub.id_name,
                        {str(v): i for i, v in enumerate(sub.vocab.tolist())},
                        np.array(sub.entity_bucket, dtype=np.int32),
                        np.array(sub.entity_pos, dtype=np.int32),
                    )
                )
            else:
                raise TypeError(
                    f"coordinate '{name}': online serving supports fixed and "
                    f"random effects, not {type(sub).__name__}"
                )
        if not coords:
            raise ValueError("GAME model has no sub-models")
        self._shard_names = tuple(shard_names)
        self._coords = tuple(coords)
        self._re_hosts = tuple(re_hosts)
        # RE slot -> position in self._tables (the nearline update path
        # addresses tables by RE slot, aligned with self._re_hosts)
        self._re_coord_indices = tuple(
            ci for ci, spec in enumerate(self._coords) if spec[0] == "re"
        )
        # per-shard feature-space bound for request validation: an
        # out-of-range id would be silently dropped by the clamped device
        # gathers (the silent-wrong-scores hazard). FE coefficients give
        # the exact dim; an index map gives it for RE-only shards; None
        # (no FE, no map) leaves that shard unchecked.
        self._shard_dims = tuple(
            shard_dims.get(s)
            if shard_dims.get(s) is not None
            else (len(self._index_maps[s]) if s in self._index_maps else None)
            for s in self._shard_names
        )

        # predict the upload BEFORE it happens: a model too big for free
        # HBM should warn at load, not OOM the first request. On a mesh
        # the per-device share is predicted/actual table bytes over the
        # entity-axis size — the whole point of sharded serving.
        telemetry.memory.check_headroom(
            predicted_bytes
            if self._eshard is None
            else -(-predicted_bytes
                   // psharding.axis_size(self.mesh, self.entity_axis)),
            label=f"serving model {version}",
        )
        uploaded = []
        for t in host_tables:
            if isinstance(t, tuple):
                # RE tables: entity-sharded over the mesh's model axis
                # when serving sharded; plain upload otherwise. A table
                # restored straight from a sharded checkpoint
                # (load(re_checkpoints=...)) arrives already placed with
                # this exact sharding, so the device_put is a no-op.
                if self._eshard is None:
                    uploaded.append(
                        tuple(
                            (
                                jnp.asarray(proj, jnp.int32),
                                jnp.asarray(coef, jnp.float32),
                            )
                            for proj, coef in t
                        )
                    )
                else:
                    uploaded.append(
                        tuple(
                            (
                                jax.device_put(
                                    jnp.asarray(proj, jnp.int32),
                                    self._eshard,
                                ),
                                jax.device_put(
                                    jnp.asarray(coef, jnp.float32),
                                    self._eshard,
                                ),
                            )
                            for proj, coef in t
                        )
                    )
            elif self._eshard is None:
                uploaded.append(jnp.asarray(t, jnp.float32))
            else:
                # fixed-effect vectors are small: replicate across the mesh
                uploaded.append(
                    jax.device_put(
                        jnp.asarray(t, jnp.float32),
                        psharding.replicated(self.mesh),
                    )
                )
        self._tables = tuple(uploaded)
        self._fn = _compiled_score_fn(self._link, self._coords, self._eshard)
        # the fleet-member margin executable, built on first margin_rows
        # (or warmup(margins=True)); single-process serving never pays
        self._margin_fn = None
        # the VERSION LOCK: apply_re_rows builds + swaps the whole table
        # tuple under it, so concurrent nearline appliers serialize;
        # score_rows deliberately reads self._tables WITHOUT it (one
        # atomic reference read — old tuple or new tuple, never torn)
        self._version_lock = threading.Lock()
        self.nearline_seq = 0
        # per-batch-bucket executable records (telemetry.xla), captured at
        # warmup — the healthz/metricsz compile-state surface
        self._bucket_records: dict[int, object] = {}
        telemetry.gauge("serving.model_bytes").set(predicted_bytes)

    @property
    def index_maps(self) -> dict:
        """The per-shard feature index maps this engine resolves named
        features through (empty when constructed without any) — the maps
        a nearline publish pins next to the updated coefficients."""
        return self._index_maps

    @staticmethod
    def _shard_slot(shard_names: list[str], name: str) -> int:
        if name not in shard_names:
            shard_names.append(name)
        return shard_names.index(name)

    # -- loading -------------------------------------------------------------

    @classmethod
    def load(
        cls,
        model_dir: str,
        max_batch: int = 64,
        max_row_nnz: int = 128,
        version: Optional[str] = None,
        require_feature_indexes: bool = True,
        mesh=None,
        entity_axis: Optional[str] = None,
        re_checkpoints: Optional[Mapping[str, str]] = None,
    ) -> "ScoringEngine":
        """Build an engine from a saved model directory.

        ``feature-indexes/`` is REQUIRED by default: without the training
        feature space pinned next to the coefficients, named features
        cannot be resolved and integer ids cannot be trusted — the
        silent-wrong-scores hazard the batch driver only warned about.

        ``mesh=`` serves the model ENTITY-SHARDED (see the class
        docstring); a random-effect table whose entity count does not
        divide the mesh's entity axis raises
        :class:`~photon_ml_tpu.parallel.sharding.ElasticPlacementError`
        listing the axis sizes that CAN hold it. ``re_checkpoints=``
        maps coordinate name -> streamed-checkpoint directory: that
        coordinate's coefficient table is restored from the sharded
        checkpoint manifest STRAIGHT onto the serving mesh
        (``restore_placed`` — per-device shard reads, no host
        materialization), replacing the table stored in ``model_dir``.
        """
        from photon_ml_tpu.data.model_store import (
            ModelLoadError,
            load_feature_index_maps,
            load_game_model,
        )

        index_maps = load_feature_index_maps(model_dir)
        if index_maps is None and require_feature_indexes:
            raise ModelLoadError(
                os.path.join(model_dir, "feature-indexes"),
                "missing feature-indexes/ — the serving feature space "
                "cannot be pinned to the stored coefficients, so scores "
                "would be silently wrong",
            )
        model = load_game_model(model_dir)
        for coord, ckpt_dir in (re_checkpoints or {}).items():
            model = _restore_re_coordinate(
                model, coord, ckpt_dir, mesh=mesh, entity_axis=entity_axis
            )
        try:
            from photon_ml_tpu.data.model_store import (
                load_game_model_metadata,
            )

            lineage = (
                load_game_model_metadata(model_dir).get("extra") or {}
            ).get("lineage")
        except (OSError, ValueError):
            lineage = None  # metadata already validated by the load above
        return cls(
            model,
            index_maps=index_maps,
            max_batch=max_batch,
            max_row_nnz=max_row_nnz,
            version=version or os.path.basename(os.path.normpath(model_dir)),
            mesh=mesh,
            entity_axis=entity_axis,
            lineage=lineage,
        )

    # -- request assembly ----------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.bucket_sizes:
            if b >= n:
                return b
        return self.max_batch

    def _resolve_feature(self, shard: str, feat):
        """-> (col, value) in the training feature space, or None for a
        named feature the training index never saw (scores 0, like the
        index-map default at training time)."""
        if isinstance(feat, Mapping):
            name, term, value = (
                feat.get("name"),
                feat.get("term", ""),
                feat.get("value"),
            )
            if name is None or value is None:
                raise BadRequest(
                    f"named feature on shard '{shard}' needs 'name' and "
                    f"'value' keys"
                )
        elif isinstance(feat, (list, tuple)) and len(feat) == 2:
            col, value = feat
            if isinstance(col, str):
                name, term = col, ""
            else:
                return int(col), value
        elif isinstance(feat, (list, tuple)) and len(feat) == 3:
            name, term, value = feat
        else:
            raise BadRequest(
                f"feature on shard '{shard}' must be [col, value], "
                f"[name, term, value], or a name/term/value object"
            )
        imap = self._index_maps.get(shard)
        if imap is None:
            raise BadRequest(
                f"named feature on shard '{shard}' but the model has no "
                f"feature index for it — send [col, value] pairs instead"
            )
        col = imap.get(feature_key(str(name), str(term or "")), -1)
        if col < 0:
            telemetry.counter("serving.unknown_features").inc()
            return None
        return int(col), value

    def _assemble(self, rows_batch: Sequence[Mapping], batch: int):
        """Pad ``rows_batch`` into the fixed-shape device inputs of one
        batch-size bucket (host numpy; uploaded at the jit boundary)."""
        per_shard = [([], [], []) for _ in self._shard_names]
        offsets = np.zeros((batch,), np.float32)
        for i, row in enumerate(rows_batch):
            if not isinstance(row, Mapping):
                raise BadRequest(f"row {i} must be an object")
            try:
                offsets[i] = row.get("offset") or 0.0
            except (TypeError, ValueError):
                raise BadRequest(
                    f"row {i}: 'offset' must be a number"
                ) from None
            feats = row.get("features") or {}
            if not isinstance(feats, Mapping):
                raise BadRequest(f"row {i}: 'features' must be an object")
            unknown = set(feats) - set(self._shard_names)
            if unknown:
                # silently dropping a typo'd shard name would score
                # fixed-effect-of-nothing — the silent-wrong-scores hazard
                raise BadRequest(
                    f"row {i}: unknown feature shard(s) {sorted(unknown)}; "
                    f"model has {sorted(self._shard_names)}"
                )
            for s_idx, s_name in enumerate(self._shard_names):
                flist = feats.get(s_name) or ()
                if len(flist) > self.max_row_nnz:
                    raise BadRequest(
                        f"row {i}: {len(flist)} features on shard "
                        f"'{s_name}' exceeds max_row_nnz={self.max_row_nnz}"
                    )
                vals, rws, cls = per_shard[s_idx]
                dim = self._shard_dims[s_idx]
                for feat in flist:
                    resolved = self._resolve_feature(s_name, feat)
                    if resolved is None:
                        continue
                    col = resolved[0]
                    if col < 0 or (dim is not None and col >= dim):
                        raise BadRequest(
                            f"row {i}: feature id {col} is outside shard "
                            f"'{s_name}' (features: "
                            f"{dim if dim is not None else 'unknown'})"
                        )
                    vals.append(resolved[1])
                    rws.append(i)
                    cls.append(col)
        shards = []
        nnz_pad = batch * self.max_row_nnz
        for vals, rws, cls in per_shard:
            v = np.zeros((nnz_pad,), np.float32)
            try:
                v[: len(vals)] = vals
            except (TypeError, ValueError):
                raise BadRequest("feature values must be numbers") from None
            # padding points at the LAST row (keeps rows non-decreasing
            # for indices_are_sorted, same convention as SparseBatch)
            r = np.full((nnz_pad,), batch - 1, np.int32)
            r[: len(rws)] = rws
            c = np.zeros((nnz_pad,), np.int32)
            c[: len(cls)] = cls
            shards.append((v, r, c))
        re_inputs = []
        for id_name, lookup, entity_bucket, entity_pos in self._re_hosts:
            bkt = np.full((batch,), -1, np.int32)
            pos = np.full((batch,), -1, np.int32)
            for i, row in enumerate(rows_batch):
                ids = row.get("ids") or {}
                value = ids.get(id_name)
                if value is None:
                    continue
                code = lookup.get(str(value), -1)
                if code < 0:
                    # unseen entity: fixed-effect-only fallback (scores 0
                    # from this coordinate, RandomEffectModel semantics)
                    telemetry.counter("serving.unseen_entities").inc()
                    continue
                if entity_bucket[code] < 0:
                    # entity the model KNOWS but whose rows live on
                    # another fleet member (a shard-mode slice marks
                    # non-owned codes bucket -1): contributes 0 here —
                    # the router folds the owning member's margin in
                    telemetry.counter("serving.not_owned_entities").inc()
                    continue
                bkt[i] = entity_bucket[code]
                pos[i] = entity_pos[code]
            re_inputs.append((bkt, pos))
        return offsets, tuple(shards), tuple(re_inputs)

    # -- scoring -------------------------------------------------------------

    def score_rows(self, rows: Sequence[Mapping]) -> np.ndarray:
        """Mean predictions (post-link, offset included — the
        ``GameModel.predict_mean`` contract) for ``rows``; chunks
        internally when a request exceeds ``max_batch``."""
        if not rows:
            return np.zeros((0,), np.float32)
        parts = []
        for lo in range(0, len(rows), self.max_batch):
            chunk = rows[lo : lo + self.max_batch]
            t0 = time.monotonic()
            batch = self._bucket_for(len(chunk))
            inputs = self._assemble(chunk, batch)
            preds = self._fn(*inputs, self._tables)
            host = telemetry.sync_fetch(preds, label="serving.scores")
            dt_ms = (time.monotonic() - t0) * 1000.0
            telemetry.histogram("serving.device_ms").observe(dt_ms)
            telemetry.counter("serving.scored_rows").inc(len(chunk))
            # feed the per-version score-distribution sketch (bounded,
            # host-side numpy only — no extra device crossing)
            quality_drift.observe_scores(self.version, host[: len(chunk)])
            parts.append(host[: len(chunk)])
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def margin_rows(
        self,
        rows: Sequence[Mapping],
        include_fixed=None,
    ) -> np.ndarray:
        """RAW additive margins for ``rows`` — pre-link, offset EXCLUDED
        — the fleet-member half of routed scoring. ``include_fixed`` is
        None (fixed effects included for every row) or a per-row boolean
        sequence: the router designates exactly one member per row as its
        FE owner, so the fold stays lossless. Chunks like
        :meth:`score_rows`."""
        if not rows:
            return np.zeros((0,), np.float32)
        mask = None
        if include_fixed is not None:
            # include_fixed is the request's host-side python list,
            # never a device array — no crossing here
            mask = np.asarray(include_fixed, bool)  # photon: noqa[L010]
            if mask.shape != (len(rows),):
                raise BadRequest(
                    f"include_fixed must have one boolean per row "
                    f"({len(rows)}), got shape {tuple(mask.shape)}"
                )
        if self._margin_fn is None:
            self._margin_fn = _compiled_margin_fn(self._coords, self._eshard)
        parts = []
        for lo in range(0, len(rows), self.max_batch):
            chunk = rows[lo : lo + self.max_batch]
            t0 = time.monotonic()
            batch = self._bucket_for(len(chunk))
            inputs = self._assemble(chunk, batch)
            gate = np.ones((batch,), np.float32)
            if mask is not None:
                gate[: len(chunk)] = mask[lo : lo + len(chunk)]
            margins = self._margin_fn(gate, *inputs, self._tables)
            host = telemetry.sync_fetch(margins, label="serving.margins")
            dt_ms = (time.monotonic() - t0) * 1000.0
            telemetry.histogram("serving.device_ms").observe(dt_ms)
            telemetry.counter("serving.margin_rows").inc(len(chunk))
            parts.append(host[: len(chunk)])
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def warmup(self, margins: bool = False) -> "ScoringEngine":
        """Execute every batch-size bucket once so all traces compile at
        load time — after this, steady-state serving never recompiles
        (asserted via the flat ``jit_compiles`` counter in tests).
        ``margins=True`` (fleet members) additionally compiles the margin
        executable for every bucket — the ``fe_gate`` vector is traced,
        so one trace per bucket covers both FE-owner modes."""
        with telemetry.span(
            "serving:warmup", version=self.version,
            buckets=len(self.bucket_sizes),
        ):
            for b in self.bucket_sizes:
                inputs = self._assemble((), b)
                telemetry.sync_fetch(
                    self._fn(*inputs, self._tables), label="serving.warmup"
                )
                rec = self._fn.record_for(*inputs, self._tables)
                if rec is not None:
                    self._bucket_records[b] = rec
                if margins:
                    if self._margin_fn is None:
                        self._margin_fn = _compiled_margin_fn(
                            self._coords, self._eshard
                        )
                    telemetry.sync_fetch(
                        self._margin_fn(
                            np.ones((b,), np.float32), *inputs, self._tables
                        ),
                        label="serving.warmup",
                    )
        self.warm = True
        return self

    def request_attrs(self) -> dict:
        """The serving attributes every request record carries — the
        per-request ``{version, nearline_seq}`` attribution ROADMAP's
        event->served staleness SLO joins on (the request tracer adds
        ``fleet_size`` from the routed payload)."""
        return {
            "version": self.version,
            "nearline_seq": int(self.nearline_seq or 0),
        }

    def compile_summary(self) -> dict[str, dict]:
        """Per-batch-bucket compile state from the executable registry
        (populated at :meth:`warmup`): compile wall seconds plus the XLA
        cost/memory analysis of each bucket's executable. Cost fields are
        None ("unknown") on backends without cost analysis."""
        out: dict[str, dict] = {}
        for b, rec in sorted(self._bucket_records.items()):
            out[str(b)] = {
                "compile_seconds": round(rec.compile_seconds, 6),
                "flops": rec.flops,
                "bytes_accessed": rec.bytes_accessed,
                "temp_bytes": rec.temp_bytes,
                "calls": rec.calls,
            }
        return out

    # -- nearline in-place updates -------------------------------------------

    def re_slot_for(self, id_name: str) -> int:
        """The RE slot index (into :meth:`re_host` / :meth:`re_tables`)
        serving entity ids named ``id_name``."""
        for slot, host in enumerate(self._re_hosts):
            if host[0] == id_name:
                return slot
        raise KeyError(
            f"model has no random-effect coordinate keyed by id "
            f"'{id_name}' (has: {[h[0] for h in self._re_hosts]})"
        )

    def re_host(self, slot: int):
        """(id_name, value->code lookup, entity_bucket, entity_pos) host
        state for RE slot ``slot`` — the entity placement the nearline
        updater resolves events through."""
        return self._re_hosts[slot]

    def re_tables(self, slot: int):
        """The CURRENT ((projection, coefficients), ...) device tables of
        RE slot ``slot`` — a snapshot reference; a concurrent
        :meth:`apply_re_rows` replaces the tuple, never mutates it."""
        return self._tables[self._re_coord_indices[slot]]

    def apply_re_rows(
        self, slot: int, bucket: int, positions, rows,
        real_rows: Optional[int] = None,
    ) -> int:
        """Swap re-solved per-entity coefficient rows into the live
        serving tables — the nearline personalization commit point.

        Builds the updated table with a non-donating scatter executable
        and replaces the WHOLE table tuple in one reference assignment
        under the version lock: a score call dispatched at any moment
        sees either the complete old tables or the complete new ones.
        ``real_rows`` is how many leading lanes are real entities (the
        rest are power-of-two padding duplicates — scattered, but not
        counted as applied rows). Returns the engine's new nearline
        sequence number."""
        ci = self._re_coord_indices[slot]
        pos = jnp.asarray(positions, jnp.int32)
        new_rows = jnp.asarray(rows, jnp.float32)
        update = _row_update_fn(self._eshard)
        with self._version_lock:
            tables = list(self._tables)
            buckets = list(tables[ci])
            proj, coef = buckets[bucket]
            buckets[bucket] = (proj, update(coef, pos, new_rows))
            tables[ci] = tuple(buckets)
            self._tables = tuple(tables)
            self.nearline_seq += 1
            seq = self.nearline_seq
        telemetry.counter("serving.nearline.applied_rows").inc(
            int(pos.shape[0] if real_rows is None else real_rows)
        )
        return seq

    def current_model(self) -> GameModel:
        """The :class:`GameModel` as currently served — base model
        structure with every random-effect bucket's coefficients replaced
        by the LIVE device tables (reflecting nearline row swaps). Used
        by the nearline publish cadence; the arrays stay on device — the
        model store fetches at save time, off the request path."""
        with self._version_lock:
            tables = self._tables
        model = self.model
        re_slot = 0
        for name, sub in model.models.items():
            if not isinstance(sub, RandomEffectModel):
                continue
            ci = self._re_coord_indices[re_slot]
            re_slot += 1
            new_buckets = tuple(
                dataclasses.replace(bm, coefficients=coef)
                for bm, (_proj, coef) in zip(sub.buckets, tables[ci])
            )
            model = model.with_model(
                name, dataclasses.replace(sub, buckets=new_buckets)
            )
        return model
