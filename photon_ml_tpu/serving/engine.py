"""Device-resident scoring engine: compile the model once, serve forever.

The batch scorer (``RandomEffectModel.score``) redoes host-side bucket
grouping and re-uploads coefficient tables on every call — fine for one
pass over a dataset, fatal for a request path. :class:`ScoringEngine`
instead:

- uploads the model ONCE at load: fixed-effect weight vectors plus
  per-coordinate random-effect coefficient tables and projections go to
  HBM (after :func:`telemetry.memory.check_headroom` predicts the upload
  fits), and the entity-id -> (bucket, position) lookup stays host-side;
- serves requests through ONE jit-compiled score function whose traces
  are keyed by padded batch-size bucket (powers of two up to
  ``max_batch``); :meth:`warmup` executes every bucket at startup so
  steady state never recompiles. The compiled function is shared via an
  ``lru_cache`` keyed by model STRUCTURE, so hot-swapping to a same-shaped
  model version reuses the existing executable outright;
- scores entities unseen at training time as fixed-effect-only
  (the random-effect contribution is 0), matching
  ``RandomEffectModel.score``'s unseen-entity semantics exactly.

This module is a serving HOT PATH: tools/check.py lint L010 rejects
device->host syncs here (``jax.device_get``, ``float()`` on arrays,
``np.asarray`` on jax arrays) — the one sanctioned fetch is
``telemetry.sync_fetch``.

Request row schema (JSON-safe)::

    {"features": {"<shard>": [[col, value], ...]},   # training feature ids
     "ids": {"<id_name>": "<entity value>"},
     "offset": 0.0}

Features may instead be named — ``[name, term, value]`` or
``{"name": ..., "term": ..., "value": ...}`` — and are then resolved
through the model's persisted ``feature-indexes/`` maps (unknown names
score 0 and count ``serving.unknown_features``, the index-map default
semantics of training).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.data.index_map import feature_key
from photon_ml_tpu.game.models import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.ops.losses import get_loss


class BadRequest(ValueError):
    """A score request is malformed (unknown shard schema, feature count
    over ``max_row_nnz``, unresolvable named feature without an index
    map). Servers map this to HTTP 400, never 500."""


def bucket_sizes_for(max_batch: int) -> tuple[int, ...]:
    """Padded batch-size buckets: powers of two up to (and always
    including) ``max_batch`` — each bucket is one compiled trace."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return tuple(sizes)


@functools.lru_cache(maxsize=32)  # bounded: a long-lived server swapping
# structurally different versions must not accumulate executables forever
def _compiled_score_fn(link: str, coords: tuple):
    """One jitted score function per model STRUCTURE.

    ``coords`` is a static spec per coordinate: ``("fixed", shard_idx)``
    or ``("re", shard_idx, num_buckets)``. Table VALUES arrive as traced
    arguments, so two model versions with the same structure (the common
    hot-swap case: retrained coefficients, same entities/features) share
    one executable and swap with ZERO recompiles. Batch size and table
    shapes are read off the traced arguments — each padded bucket size is
    its own trace inside the one jit cache.
    """
    re_slots = {}
    for ci, spec in enumerate(coords):
        if spec[0] == "re":
            re_slots[ci] = len(re_slots)

    def fn(offsets, shards, re_inputs, tables):
        batch = offsets.shape[0]
        total = jnp.zeros((batch,), jnp.float32)
        for ci, spec in enumerate(coords):
            values, rows, cols = shards[spec[1]]
            if spec[0] == "fixed":
                contrib = values * jnp.take(tables[ci], cols, fill_value=0)
            else:
                row_bucket, row_pos = re_inputs[re_slots[ci]]
                bkt_n = row_bucket[rows]  # padded rows -> row batch-1: -1
                pos_n = row_pos[rows]
                contrib = jnp.zeros_like(values)
                for b_idx, (proj, coef) in enumerate(tables[ci]):
                    num_entities, local_dim = proj.shape
                    p = jnp.clip(pos_n, 0, num_entities - 1)
                    if local_dim <= 64:
                        # transposed compare-scan (the K<=64 kernel of
                        # RandomEffectModel.score): each column matches at
                        # most one projection slot, so the masked sum IS
                        # the coefficient lookup
                        w_n = jnp.sum(
                            jnp.where(
                                proj.T[:, p] == cols[None, :],
                                coef.T[:, p],
                                0.0,
                            ),
                            axis=0,
                        )
                    else:
                        proj_rows = proj[p]
                        k = jax.vmap(jnp.searchsorted)(proj_rows, cols)
                        k = jnp.minimum(k, local_dim - 1)
                        hit = (
                            jnp.take_along_axis(
                                proj_rows, k[:, None], axis=1
                            )[:, 0]
                            == cols
                        )
                        w_n = jnp.where(
                            hit,
                            jnp.take_along_axis(
                                coef[p], k[:, None], axis=1
                            )[:, 0],
                            0.0,
                        )
                    contrib = contrib + jnp.where(
                        bkt_n == b_idx, values * w_n, 0.0
                    )
            total = total + jax.ops.segment_sum(
                contrib, rows, num_segments=batch, indices_are_sorted=True
            )
        scores = total + offsets
        if link == "logistic":
            return jax.nn.sigmoid(scores)
        if link == "poisson":
            return jnp.exp(scores)
        return scores

    # instrumented (lint L011): each padded batch-size bucket is one
    # accounted executable — per-bucket compile time + cost surface in
    # healthz/metricsz and the run report's top-executables table.
    # multi_shape: the bucket set IS the design; warmup compiling every
    # bucket must not read as a recompile storm
    return telemetry.instrumented_jit(
        fn, name="serving_score", multi_shape=True
    )


class ScoringEngine:
    """A :class:`GameModel` compiled into long-lived, device-resident
    scoring form. Immutable after construction — the registry hot-swaps
    by replacing the engine reference while in-flight requests finish on
    the old one."""

    def __init__(
        self,
        model: GameModel,
        index_maps: Optional[Mapping] = None,
        max_batch: int = 64,
        max_row_nnz: int = 128,
        version: str = "unversioned",
    ):
        if max_row_nnz < 1:
            raise ValueError("max_row_nnz must be >= 1")
        self.model = model
        self.version = version
        self.max_batch = int(max_batch)
        self.max_row_nnz = int(max_row_nnz)
        self.task = model.task
        self.bucket_sizes = bucket_sizes_for(self.max_batch)
        self.warm = False
        self._link = get_loss(model.task).name
        self._index_maps = dict(index_maps or {})

        shard_names: list[str] = []
        shard_dims: dict[str, Optional[int]] = {}
        coords: list[tuple] = []
        host_tables: list = []
        re_hosts: list[tuple] = []
        predicted_bytes = 0
        for name, sub in model.models.items():
            if isinstance(sub, FixedEffectModel):
                si = self._shard_slot(shard_names, sub.shard_name)
                shard_dims[sub.shard_name] = int(sub.coefficients.shape[0])
                coords.append(("fixed", si))
                host_tables.append(sub.coefficients)
                predicted_bytes += telemetry.memory.estimate_table_bytes(
                    1, sub.coefficients.shape[0]
                )
            elif isinstance(sub, RandomEffectModel):
                si = self._shard_slot(shard_names, sub.shard_name)
                coords.append(("re", si, len(sub.buckets)))
                host_tables.append(
                    tuple(
                        (bm.projection, bm.coefficients) for bm in sub.buckets
                    )
                )
                for bm in sub.buckets:
                    num_e, local_k = bm.coefficients.shape
                    # coefficients + int32 projection, both 4-byte
                    predicted_bytes += 2 * telemetry.memory.estimate_table_bytes(
                        num_e, local_k
                    )
                re_hosts.append(
                    (
                        sub.id_name,
                        {str(v): i for i, v in enumerate(sub.vocab.tolist())},
                        np.array(sub.entity_bucket, dtype=np.int32),
                        np.array(sub.entity_pos, dtype=np.int32),
                    )
                )
            else:
                raise TypeError(
                    f"coordinate '{name}': online serving supports fixed and "
                    f"random effects, not {type(sub).__name__}"
                )
        if not coords:
            raise ValueError("GAME model has no sub-models")
        self._shard_names = tuple(shard_names)
        self._coords = tuple(coords)
        self._re_hosts = tuple(re_hosts)
        # per-shard feature-space bound for request validation: an
        # out-of-range id would be silently dropped by the clamped device
        # gathers (the silent-wrong-scores hazard). FE coefficients give
        # the exact dim; an index map gives it for RE-only shards; None
        # (no FE, no map) leaves that shard unchecked.
        self._shard_dims = tuple(
            shard_dims.get(s)
            if shard_dims.get(s) is not None
            else (len(self._index_maps[s]) if s in self._index_maps else None)
            for s in self._shard_names
        )

        # predict the upload BEFORE it happens: a model too big for free
        # HBM should warn at load, not OOM the first request
        telemetry.memory.check_headroom(
            predicted_bytes, label=f"serving model {version}"
        )
        uploaded = []
        for t in host_tables:
            if isinstance(t, tuple):
                uploaded.append(
                    tuple(
                        (
                            jnp.asarray(proj, jnp.int32),
                            jnp.asarray(coef, jnp.float32),
                        )
                        for proj, coef in t
                    )
                )
            else:
                uploaded.append(jnp.asarray(t, jnp.float32))
        self._tables = tuple(uploaded)
        self._fn = _compiled_score_fn(self._link, self._coords)
        # per-batch-bucket executable records (telemetry.xla), captured at
        # warmup — the healthz/metricsz compile-state surface
        self._bucket_records: dict[int, object] = {}
        telemetry.gauge("serving.model_bytes").set(predicted_bytes)

    @staticmethod
    def _shard_slot(shard_names: list[str], name: str) -> int:
        if name not in shard_names:
            shard_names.append(name)
        return shard_names.index(name)

    # -- loading -------------------------------------------------------------

    @classmethod
    def load(
        cls,
        model_dir: str,
        max_batch: int = 64,
        max_row_nnz: int = 128,
        version: Optional[str] = None,
        require_feature_indexes: bool = True,
    ) -> "ScoringEngine":
        """Build an engine from a saved model directory.

        ``feature-indexes/`` is REQUIRED by default: without the training
        feature space pinned next to the coefficients, named features
        cannot be resolved and integer ids cannot be trusted — the
        silent-wrong-scores hazard the batch driver only warned about.
        """
        from photon_ml_tpu.data.model_store import (
            ModelLoadError,
            load_feature_index_maps,
            load_game_model,
        )

        index_maps = load_feature_index_maps(model_dir)
        if index_maps is None and require_feature_indexes:
            raise ModelLoadError(
                os.path.join(model_dir, "feature-indexes"),
                "missing feature-indexes/ — the serving feature space "
                "cannot be pinned to the stored coefficients, so scores "
                "would be silently wrong",
            )
        model = load_game_model(model_dir)
        return cls(
            model,
            index_maps=index_maps,
            max_batch=max_batch,
            max_row_nnz=max_row_nnz,
            version=version or os.path.basename(os.path.normpath(model_dir)),
        )

    # -- request assembly ----------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.bucket_sizes:
            if b >= n:
                return b
        return self.max_batch

    def _resolve_feature(self, shard: str, feat):
        """-> (col, value) in the training feature space, or None for a
        named feature the training index never saw (scores 0, like the
        index-map default at training time)."""
        if isinstance(feat, Mapping):
            name, term, value = (
                feat.get("name"),
                feat.get("term", ""),
                feat.get("value"),
            )
            if name is None or value is None:
                raise BadRequest(
                    f"named feature on shard '{shard}' needs 'name' and "
                    f"'value' keys"
                )
        elif isinstance(feat, (list, tuple)) and len(feat) == 2:
            col, value = feat
            if isinstance(col, str):
                name, term = col, ""
            else:
                return int(col), value
        elif isinstance(feat, (list, tuple)) and len(feat) == 3:
            name, term, value = feat
        else:
            raise BadRequest(
                f"feature on shard '{shard}' must be [col, value], "
                f"[name, term, value], or a name/term/value object"
            )
        imap = self._index_maps.get(shard)
        if imap is None:
            raise BadRequest(
                f"named feature on shard '{shard}' but the model has no "
                f"feature index for it — send [col, value] pairs instead"
            )
        col = imap.get(feature_key(str(name), str(term or "")), -1)
        if col < 0:
            telemetry.counter("serving.unknown_features").inc()
            return None
        return int(col), value

    def _assemble(self, rows_batch: Sequence[Mapping], batch: int):
        """Pad ``rows_batch`` into the fixed-shape device inputs of one
        batch-size bucket (host numpy; uploaded at the jit boundary)."""
        per_shard = [([], [], []) for _ in self._shard_names]
        offsets = np.zeros((batch,), np.float32)
        for i, row in enumerate(rows_batch):
            if not isinstance(row, Mapping):
                raise BadRequest(f"row {i} must be an object")
            try:
                offsets[i] = row.get("offset") or 0.0
            except (TypeError, ValueError):
                raise BadRequest(
                    f"row {i}: 'offset' must be a number"
                ) from None
            feats = row.get("features") or {}
            if not isinstance(feats, Mapping):
                raise BadRequest(f"row {i}: 'features' must be an object")
            unknown = set(feats) - set(self._shard_names)
            if unknown:
                # silently dropping a typo'd shard name would score
                # fixed-effect-of-nothing — the silent-wrong-scores hazard
                raise BadRequest(
                    f"row {i}: unknown feature shard(s) {sorted(unknown)}; "
                    f"model has {sorted(self._shard_names)}"
                )
            for s_idx, s_name in enumerate(self._shard_names):
                flist = feats.get(s_name) or ()
                if len(flist) > self.max_row_nnz:
                    raise BadRequest(
                        f"row {i}: {len(flist)} features on shard "
                        f"'{s_name}' exceeds max_row_nnz={self.max_row_nnz}"
                    )
                vals, rws, cls = per_shard[s_idx]
                dim = self._shard_dims[s_idx]
                for feat in flist:
                    resolved = self._resolve_feature(s_name, feat)
                    if resolved is None:
                        continue
                    col = resolved[0]
                    if col < 0 or (dim is not None and col >= dim):
                        raise BadRequest(
                            f"row {i}: feature id {col} is outside shard "
                            f"'{s_name}' (features: "
                            f"{dim if dim is not None else 'unknown'})"
                        )
                    vals.append(resolved[1])
                    rws.append(i)
                    cls.append(col)
        shards = []
        nnz_pad = batch * self.max_row_nnz
        for vals, rws, cls in per_shard:
            v = np.zeros((nnz_pad,), np.float32)
            try:
                v[: len(vals)] = vals
            except (TypeError, ValueError):
                raise BadRequest("feature values must be numbers") from None
            # padding points at the LAST row (keeps rows non-decreasing
            # for indices_are_sorted, same convention as SparseBatch)
            r = np.full((nnz_pad,), batch - 1, np.int32)
            r[: len(rws)] = rws
            c = np.zeros((nnz_pad,), np.int32)
            c[: len(cls)] = cls
            shards.append((v, r, c))
        re_inputs = []
        for id_name, lookup, entity_bucket, entity_pos in self._re_hosts:
            bkt = np.full((batch,), -1, np.int32)
            pos = np.full((batch,), -1, np.int32)
            for i, row in enumerate(rows_batch):
                ids = row.get("ids") or {}
                value = ids.get(id_name)
                if value is None:
                    continue
                code = lookup.get(str(value), -1)
                if code < 0:
                    # unseen entity: fixed-effect-only fallback (scores 0
                    # from this coordinate, RandomEffectModel semantics)
                    telemetry.counter("serving.unseen_entities").inc()
                    continue
                bkt[i] = entity_bucket[code]
                pos[i] = entity_pos[code]
            re_inputs.append((bkt, pos))
        return offsets, tuple(shards), tuple(re_inputs)

    # -- scoring -------------------------------------------------------------

    def score_rows(self, rows: Sequence[Mapping]) -> np.ndarray:
        """Mean predictions (post-link, offset included — the
        ``GameModel.predict_mean`` contract) for ``rows``; chunks
        internally when a request exceeds ``max_batch``."""
        if not rows:
            return np.zeros((0,), np.float32)
        parts = []
        for lo in range(0, len(rows), self.max_batch):
            chunk = rows[lo : lo + self.max_batch]
            t0 = time.monotonic()
            batch = self._bucket_for(len(chunk))
            inputs = self._assemble(chunk, batch)
            preds = self._fn(*inputs, self._tables)
            host = telemetry.sync_fetch(preds, label="serving.scores")
            dt_ms = (time.monotonic() - t0) * 1000.0
            telemetry.histogram("serving.device_ms").observe(dt_ms)
            telemetry.counter("serving.scored_rows").inc(len(chunk))
            parts.append(host[: len(chunk)])
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def warmup(self) -> "ScoringEngine":
        """Execute every batch-size bucket once so all traces compile at
        load time — after this, steady-state serving never recompiles
        (asserted via the flat ``jit_compiles`` counter in tests)."""
        with telemetry.span(
            "serving:warmup", version=self.version,
            buckets=len(self.bucket_sizes),
        ):
            for b in self.bucket_sizes:
                inputs = self._assemble((), b)
                telemetry.sync_fetch(
                    self._fn(*inputs, self._tables), label="serving.warmup"
                )
                rec = self._fn.record_for(*inputs, self._tables)
                if rec is not None:
                    self._bucket_records[b] = rec
        self.warm = True
        return self

    def compile_summary(self) -> dict[str, dict]:
        """Per-batch-bucket compile state from the executable registry
        (populated at :meth:`warmup`): compile wall seconds plus the XLA
        cost/memory analysis of each bucket's executable. Cost fields are
        None ("unknown") on backends without cost analysis."""
        out: dict[str, dict] = {}
        for b, rec in sorted(self._bucket_records.items()):
            out[str(b)] = {
                "compile_seconds": round(rec.compile_seconds, 6),
                "flops": rec.flops,
                "bytes_accessed": rec.bytes_accessed,
                "temp_bytes": rec.temp_bytes,
                "calls": rec.calls,
            }
        return out
