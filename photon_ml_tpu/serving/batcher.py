"""Adaptive micro-batching: coalesce concurrent score requests into padded
device batches under a latency deadline (the Clipper recipe, NSDI 2017).

One dispatcher thread owns all device work: requests enqueue from any
number of server threads, the dispatcher blocks for the first unit, then
coalesces whatever arrives within ``max_delay_ms`` (or until ``max_batch``
rows), scores the whole batch in one engine call, and slices results back
to each caller's Future. Admission control is by queue depth in ROWS:
when the backlog would exceed ``queue_depth``, the request is shed
immediately with a typed :class:`Overloaded` error (counted as
``serving.shed``) instead of growing the queue — a loaded server degrades
by rejecting, never by stalling every caller.

This module is a serving HOT PATH under tools/check.py lint L010: no
device->host syncs here — the engine's ``telemetry.sync_fetch`` is the one
sanctioned crossing.

Telemetry: ``serving.requests`` / ``serving.shed`` counters;
``serving.queue_ms`` (enqueue -> dispatch), ``serving.total_ms``
(enqueue -> result) and ``serving.batch_size`` (rows per device dispatch)
histograms.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future
from typing import Callable, Mapping, Sequence, Tuple

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.serving.engine import BadRequest
from photon_ml_tpu.telemetry import requests as request_trace

#: scorer contract: flat request rows -> (scores aligned to rows, version)
Scorer = Callable[[Sequence[Mapping]], Tuple[Sequence[float], str]]

# Injection seam on the batched device dispatch: a `raise` rule here is
# delivered to every rider of the batch as a scoring failure (callers see
# the typed error, the dispatcher survives); an `exit` rule is the serving
# process dying mid-request.
_FP_DISPATCH = faults.register_point(
    "serving.dispatch",
    description="micro-batched scoring dispatch (one engine call)",
)
# The continuous-batching dispatch (the async front end's scheduler): same
# delivery semantics as serving.dispatch, distinct seam so chaos runs can
# target the event-loop request path specifically.
_FP_ASYNC_DISPATCH = faults.register_point(
    "serving.async_dispatch",
    description="continuous-batching scoring dispatch (one engine call)",
)


class Overloaded(RuntimeError):
    """Admission control shed this request: the pending queue is at
    capacity. Callers should back off and retry; servers map this to
    HTTP 503."""


class Draining(RuntimeError):
    """The server is draining (SIGTERM graceful stop): admission is
    closed while in-flight batches finish. Servers map this to HTTP 503
    WITH a ``Retry-After`` header — callers should re-resolve and retry
    against a peer, the replacement process, or later."""


class _Unit:
    __slots__ = ("rows", "future", "t_enqueue", "ctx")

    def __init__(self, rows, ctx=None):
        self.rows = rows
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()
        # inbound trace context (X-Photon-Trace); None mints one at
        # dispatch so every unit still lands in the request ring
        self.ctx = ctx


class MicroBatcher:
    """Deadline-bounded request coalescing in front of a scorer."""

    #: injection seam this batcher's dispatch fires (subclasses override)
    _fault_seam = _FP_DISPATCH

    def __init__(
        self,
        scorer: Scorer,
        max_batch: int = 64,
        max_delay_ms: float = 5.0,
        queue_depth: int = 256,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_delay_ms < 0:
            raise ValueError("max_delay_ms must be >= 0")
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        self._scorer = scorer
        self.max_batch = int(max_batch)
        self.max_delay_ms = max_delay_ms
        self.queue_depth = int(queue_depth)
        self._cv = threading.Condition()
        self._queue: collections.deque[_Unit] = collections.deque()
        self._pending_rows = 0
        self._running = False
        self._thread = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MicroBatcher":
        with self._cv:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="micro-batcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop accepting work and DRAIN: queued units are still scored
        before the dispatcher exits (in-flight requests finish)."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    # -- producer side -------------------------------------------------------

    def submit(self, rows: Sequence[Mapping], ctx=None) -> Future:
        """Enqueue one request unit; resolves to
        ``{"scores": <aligned array>, "model_version": <str>}``.
        ``ctx`` tags the unit's request record with the caller's trace
        context."""
        unit = _Unit(list(rows), ctx=ctx)
        if len(unit.rows) > self.queue_depth:
            # shedding this as Overloaded would invite a retry that can
            # NEVER succeed — it is a malformed request, not back-pressure
            raise BadRequest(
                f"request of {len(unit.rows)} rows exceeds the server's "
                f"queue depth ({self.queue_depth}); split it into smaller "
                f"requests"
            )
        with self._cv:
            if not self._running:
                raise RuntimeError("MicroBatcher is not running")
            if self._pending_rows + len(unit.rows) > self.queue_depth:
                telemetry.counter("serving.shed").inc()
                raise Overloaded(
                    f"queue at capacity: {self._pending_rows} rows pending, "
                    f"depth {self.queue_depth}"
                )
            self._queue.append(unit)
            self._pending_rows += len(unit.rows)
            telemetry.counter("serving.requests").inc()
            self._cv.notify_all()
        return unit.future

    # -- dispatcher side -----------------------------------------------------

    def _collect(self) -> list[_Unit]:
        """Block for the first unit, then coalesce until ``max_batch``
        rows are gathered or the delay deadline passes. A single unit
        larger than ``max_batch`` dispatches alone (the engine chunks
        internally)."""
        with self._cv:
            # untimed wait: submit() and stop() both notify under the lock,
            # so an idle dispatcher sleeps instead of polling
            while self._running and not self._queue:
                self._cv.wait()
            if not self._queue:
                return []
            units = [self._queue.popleft()]
            total = len(units[0].rows)
            deadline = time.monotonic() + self.max_delay_ms / 1000.0
            while total < self.max_batch:
                if self._queue:
                    if total + len(self._queue[0].rows) > self.max_batch:
                        break
                    nxt = self._queue.popleft()
                    units.append(nxt)
                    total += len(nxt.rows)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._running:
                    break
                self._cv.wait(timeout=remaining)
            self._pending_rows -= total
            return units

    @staticmethod
    def _deliver(unit: _Unit, result=None, error=None) -> None:
        """set_result/set_exception tolerant of a caller that gave up:
        a timed-out request cancels its future, and InvalidStateError
        must not kill the dispatcher."""
        try:
            if error is not None:
                unit.future.set_exception(error)
            else:
                unit.future.set_result(result)
        except Exception:  # noqa: BLE001 — cancelled/abandoned future
            pass

    def _dispatch(self, units: list[_Unit]) -> None:
        # drop units whose callers timed out and cancelled: scoring work
        # nobody will read amplifies overload instead of shedding it
        units = [u for u in units if not u.future.cancelled()]
        if not units:
            return
        t0 = time.monotonic()
        queue_ms = telemetry.histogram("serving.queue_ms")
        recs: dict[int, object] = {}
        for u in units:
            wait_ms = (t0 - u.t_enqueue) * 1000.0
            queue_ms.observe(wait_ms)
            # every unit becomes a request record (the per-process ring);
            # the record's clock starts at ENQUEUE so queue wait is part
            # of the request, not hidden before it
            rec = request_trace.begin(
                "score",
                ctx=u.ctx,
                role="member",
                t_start=request_trace.trace_time(u.t_enqueue),
                rows=len(u.rows),
            )
            if rec is not None:
                rec.phase(
                    "batcher_wait",
                    wait_ms,
                    ts=request_trace.trace_time(u.t_enqueue),
                )
                recs[id(u)] = rec
        flat = [r for u in units for r in u.rows]
        telemetry.histogram("serving.batch_size").observe(len(flat))
        try:
            faults.fault_point(self._fault_seam)
            scores, version = self._scorer(flat)
        except Exception as e:  # noqa: BLE001 — failure belongs to callers
            if len(units) == 1:
                self._deliver(units[0], error=e)
                request_trace.finish(
                    recs.get(id(units[0])), status="error",
                    error=f"{type(e).__name__}: {e}",
                )
            else:
                # isolate the offender: one malformed co-batched request
                # must not fail the valid ones riding the same batch
                for u in units:
                    try:
                        s, v = self._scorer(u.rows)
                        self._deliver(
                            u, result={"scores": s, "model_version": v}
                        )
                        request_trace.finish(recs.get(id(u)))
                    except Exception as unit_err:  # noqa: BLE001
                        self._deliver(u, error=unit_err)
                        request_trace.finish(
                            recs.get(id(u)), status="error",
                            error=f"{type(unit_err).__name__}: {unit_err}",
                        )
            return
        t1 = time.monotonic()
        dispatch_ms = (t1 - t0) * 1000.0
        dispatch_ts = request_trace.trace_time(t0)
        total_ms = telemetry.histogram("serving.total_ms")
        offset = 0
        for u in units:
            k = len(u.rows)
            self._deliver(
                u,
                result={"scores": scores[offset : offset + k],
                        "model_version": version},
            )
            total_ms.observe((t1 - u.t_enqueue) * 1000.0)
            offset += k
            rec = recs.get(id(u))
            if rec is not None:
                rec.phase("device_dispatch", dispatch_ms, ts=dispatch_ts)
                rec.set_attr(version=version, batch_rows=len(flat))
                request_trace.finish(rec)

    def _loop(self) -> None:
        while True:
            units = self._collect()
            if units:
                self._dispatch(units)
                continue
            with self._cv:
                if not self._running and not self._queue:
                    return


class ContinuousBatcher(MicroBatcher):
    """Continuous batching: the device is never idle while work is queued.

    :class:`MicroBatcher` holds the first request of every batch hostage
    to the ``max_delay_ms`` deadline hoping co-riders arrive — the right
    trade for a mostly-idle server, the wrong one under sustained load,
    where the deadline only ADDS latency: while one batch runs on the
    device, the next has already formed in the queue. This scheduler
    instead dispatches IMMEDIATELY with whatever is queued (up to
    ``max_batch`` rows): requests arriving while a batch is in flight are
    admitted into the next bucket the moment device capacity frees —
    batch size grows naturally with offered load (1 at idle, ``max_batch``
    at saturation), and no request ever waits on a timer.

    ``max_delay_ms`` is accepted for signature compatibility and ignored.
    Admission control (queue depth in rows -> typed :class:`Overloaded`),
    oversized-request rejection (:class:`BadRequest`), cancelled-future
    dropping, and co-rider error isolation are all inherited unchanged —
    one semantics, two scheduling policies.
    """

    _fault_seam = _FP_ASYNC_DISPATCH

    def _collect(self) -> list[_Unit]:
        """Block until at least one unit is queued, then take as many
        whole units as fit in ``max_batch`` rows WITHOUT waiting for
        more. A single unit larger than ``max_batch`` dispatches alone
        (the engine chunks internally)."""
        with self._cv:
            while self._running and not self._queue:
                self._cv.wait()
            if not self._queue:
                return []
            units = [self._queue.popleft()]
            total = len(units[0].rows)
            while (
                self._queue
                and total + len(self._queue[0].rows) <= self.max_batch
            ):
                nxt = self._queue.popleft()
                units.append(nxt)
                total += len(nxt.rows)
            self._pending_rows -= total
            return units
