"""Event-loop serving front end: one asyncio loop instead of one thread
per connection.

The stdlib :class:`~http.server.ThreadingHTTPServer` front end spends a
thread (stack, GIL wakeups, scheduler churn) per in-flight connection —
fine for tens of callers, the wrong shape for the sustained-load regime
the SLO bench drives (thousands of open keep-alive connections feeding a
device that scores them 64 rows at a time). :class:`AsyncScoringServer`
serves the same endpoints from ONE event loop:

- connections are asyncio streams; request parsing and response writes
  never block the loop;
- ``POST /v1/score`` enqueues into the shared
  :class:`~photon_ml_tpu.serving.batcher.ContinuousBatcher` (or
  ``MicroBatcher``) and ``await``s the wrapped batcher future — the
  device dispatch stays on the batcher's dispatcher thread, the loop is
  free to accept/parse/answer while batches run;
- ``GET /healthz`` / ``GET /metricsz`` are answered DIRECTLY on the loop
  from telemetry registries — they never queue behind scoring, so the
  health surface stays responsive while the engine is mid-warmup,
  mid-swap, or saturated (asserted by test);
- ``POST /v1/update`` feeds nearline personalization events to an
  attached :class:`~photon_ml_tpu.serving.nearline.NearlineUpdater`.

Error semantics are identical to the threading front end: Overloaded ->
503, BadRequest -> 400, timeout -> 504 (the future is cancelled so the
dispatcher drops the dead unit), anything else -> 500 without killing the
server. HTTP/1.1 keep-alive is supported; malformed requests close the
connection.

This module is a serving HOT PATH (tools/check.py L010/L013): no
device->host syncs — scores arrive host-side from the engine's one
sanctioned ``telemetry.sync_fetch``.
"""

from __future__ import annotations

import asyncio
import functools
import json
import logging
import threading
from typing import Optional

from photon_ml_tpu.serving.batcher import Draining, Overloaded
from photon_ml_tpu.serving.engine import BadRequest
from photon_ml_tpu.serving.server import (
    DRAIN_RETRY_AFTER_S,
    ScoringService,
    _json_scores,
)
from photon_ml_tpu.telemetry import requests as request_trace

logger = logging.getLogger("photon_ml_tpu.serving.aio")

_MAX_HEADER_LINES = 128
_MAX_BODY_BYTES = 64 * 1024 * 1024


class AsyncScoringServer:
    """Asyncio HTTP front end with the same lifecycle surface as
    :class:`~photon_ml_tpu.serving.server.ScoringServer` (``start()`` /
    ``stop()`` / ``.port``), so drivers and tests swap front ends with
    one flag. The loop runs on a dedicated background thread; the caller
    keeps a plain blocking API."""

    def __init__(
        self,
        service: ScoringService,
        host: str = "127.0.0.1",
        port: int = 8080,
    ):
        self.service = service
        self._host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop_event: Optional[asyncio.Event] = None
        self._ready = threading.Event()
        self._lock = threading.Lock()
        self._startup_error: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "AsyncScoringServer":
        self.service.start()
        self._ready.clear()
        with self._lock:
            self._startup_error = None
        self._thread = threading.Thread(
            target=self._run, name="scoring-aio", daemon=True
        )
        self._thread.start()
        self._ready.wait(timeout=30)
        with self._lock:
            if self._startup_error is not None:
                raise self._startup_error
        if self.port is None:
            raise RuntimeError("async scoring server failed to start")
        return self

    def stop(self) -> None:
        loop, stop_event = self._loop, self._stop_event
        if loop is not None and stop_event is not None:
            loop.call_soon_threadsafe(stop_event.set)
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        self.service.stop()

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:  # noqa: BLE001 — surfaced to start()
            with self._lock:
                self._startup_error = e
            self._ready.set()

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        server = await asyncio.start_server(
            self._handle, self._host, self._requested_port
        )
        self.port = server.sockets[0].getsockname()[1]
        self._ready.set()
        try:
            async with server:
                await self._stop_event.wait()
        finally:
            self._loop = None
            self._stop_event = None

    # -- connection handling -------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                request = await self._read_request(reader)
                if request is None:
                    break
                method, path, headers, body = request
                code, obj, extra = await self._route(
                    method, path, body, headers
                )
                await self._reply(writer, code, obj, extra)
                if headers.get("connection", "").lower() == "close":
                    break
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            ValueError,
        ):
            pass  # client went away / sent garbage: drop the connection
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    @staticmethod
    async def _read_request(reader: asyncio.StreamReader):
        """Parse one HTTP/1.1 request; None at a clean EOF between
        requests (keep-alive close)."""
        request_line = await reader.readline()
        if not request_line or request_line in (b"\r\n", b"\n"):
            return None
        parts = request_line.decode("latin-1").split()
        if len(parts) < 2:
            raise ValueError("malformed request line")
        method, path = parts[0].upper(), parts[1]
        headers: dict[str, str] = {}
        for _ in range(_MAX_HEADER_LINES):
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            key, sep, value = line.decode("latin-1").partition(":")
            if sep:
                headers[key.strip().lower()] = value.strip()
        else:
            raise ValueError("too many header lines")
        length = int(headers.get("content-length") or 0)
        if length < 0 or length > _MAX_BODY_BYTES:
            raise ValueError(f"bad content-length {length}")
        body = await reader.readexactly(length) if length else b""
        return method, path, headers, body

    async def _reply(
        self,
        writer: asyncio.StreamWriter,
        code: int,
        obj,
        extra_headers: Optional[dict] = None,
    ) -> None:
        body = json.dumps(obj, default=float).encode("utf-8")
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  409: "Conflict", 503: "Service Unavailable",
                  504: "Gateway Timeout",
                  500: "Internal Server Error"}.get(code, "OK")
        extras = "".join(
            f"{name}: {value}\r\n"
            for name, value in (extra_headers or {}).items()
        )
        writer.write(
            (
                f"HTTP/1.1 {code} {reason}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"{extras}"
                "\r\n"
            ).encode("latin-1")
            + body
        )
        await writer.drain()

    # -- routing -------------------------------------------------------------

    _POST_PATHS = (
        "/v1/score",
        "/v1/update",
        "/v1/margins",
        "/v1/admin/stage",
        "/v1/admin/commit",
    )

    async def _route(
        self,
        method: str,
        path: str,
        body: bytes,
        headers: Optional[dict] = None,
    ):
        """Returns ``(code, obj, extra_headers_or_None)``."""
        if method == "GET":
            # answered inline on the loop — NEVER behind the batcher, so
            # health/metrics stay responsive however loaded scoring is
            if path == "/healthz":
                return 200, self.service.health(), None
            if path == "/metricsz":
                return 200, self.service.metrics(), None
            return 404, {"error": f"unknown path {path}"}, None
        if method != "POST" or path not in self._POST_PATHS:
            return 404, {"error": f"unknown path {path}"}, None
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            return 400, {"error": "bad_request",
                         "detail": "body is not valid JSON"}, None
        # _read_request lowercases header names; a malformed trace header
        # parses to None and the request proceeds untraced
        ctx = request_trace.parse_header(
            (headers or {}).get(request_trace.TRACE_HEADER.lower())
        )
        loop = asyncio.get_running_loop()
        try:
            if path == "/v1/update":
                return 200, self.service.update_request(payload), None
            if path == "/v1/margins":
                # device work runs off-loop: the margin fold is a blocking
                # engine call, and the loop must keep accepting traffic
                result = await loop.run_in_executor(
                    None,
                    functools.partial(
                        self.service.margin_request, payload, ctx=ctx
                    ),
                )
                return 200, result, None
            if path.startswith("/v1/admin/"):
                op = path.rsplit("/", 1)[1]
                # stage loads+warms a whole shard engine — seconds of
                # blocking work that must not stall the event loop
                result = await loop.run_in_executor(
                    None, self.service.admin_request, op, payload
                )
                return 200, result, None
            return 200, await self._score(payload, ctx), None
        except Draining as e:
            return (
                503,
                {"error": "draining", "detail": str(e)},
                {"Retry-After": str(DRAIN_RETRY_AFTER_S)},
            )
        except Overloaded as e:
            return 503, {"error": "overloaded", "detail": str(e)}, None
        except BadRequest as e:
            return 400, {"error": "bad_request", "detail": str(e)}, None
        except KeyError as e:
            # a version pin the member cannot honor (mid-swap window):
            # the router sheds this member for the request, never blends
            return 409, {"error": "version_unavailable",
                         "detail": str(e)}, None
        except asyncio.TimeoutError:
            return 504, {"error": "timeout"}, None
        except Exception as e:  # noqa: BLE001 — a request must not kill the loop
            logger.exception("async score request failed")
            return 500, {"error": "internal", "detail": str(e)}, None

    async def _score(self, payload, ctx=None) -> dict:
        """Submit to the shared batcher and await the wrapped future —
        the loop stays free while the batch runs on the device."""
        future = self.service.submit_rows(payload, ctx=ctx)
        try:
            result = await asyncio.wait_for(
                asyncio.wrap_future(future),
                timeout=self.service.request_timeout_s,
            )
        except asyncio.TimeoutError:
            # same contract as the blocking path: cancel so the
            # dispatcher drops the unit instead of scoring dead work
            future.cancel()
            raise
        return _json_scores(result)
