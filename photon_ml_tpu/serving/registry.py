"""Hot-swappable model registry: watch a versioned models directory, swap
to the newest valid version in the background, never downgrade, never
serve a partial write.

Layout (one directory per published version)::

    <registry_dir>/
      v-00000001/
        model-metadata.json         written LAST (completeness certificate)
        feature-indexes/<shard>/    REQUIRED: the pinned training feature
                                    space (versions without it are refused
                                    outright — the silent-wrong-scores
                                    hazard of rebuilding indices at serve
                                    time)
        fixed-effect/... random-effect/...
      v-00000002/
      .tmp-v-00000003/              in-flight publish (ignored by scans)

Atomicity follows ``game/checkpoint.py``: :func:`publish_version`
assembles a ``.tmp-v-*`` sibling (index maps first, then the model store
save, whose metadata lands last) and ``os.rename``s it into place, so a
scanner never observes a partial version. :meth:`ModelRegistry.refresh`
walks versions NEWEST-first, skips corrupt/partial/unloadable ones with a
warning + ``serving.skipped_versions`` counter (exactly the checkpoint
restore fallback), builds + warms the engine OFF the request path, and
only then swaps the engine reference — in-flight requests finish on the
old engine, which the swap never mutates.

Telemetry: ``serving.model_swaps`` counter, ``serving.model_version``
gauge, ``serving.skipped_versions`` and ``serving.version_retries``
counters (the latter = transient-IO load retries; see
:meth:`ModelRegistry._load_version` for the transient/deterministic
failure split).
"""

from __future__ import annotations

import logging
import os
import re
import shutil
import threading
from typing import Mapping, Optional

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.utils.atomic import fsync_dir

logger = logging.getLogger("photon_ml_tpu.serving.registry")

# Injection seams: the background poll tick and the version load itself.
# An `io` rule at the load point is exactly the transient flaky-read shape
# the bounded retry absorbs (InjectedIOError IS an OSError).
_FP_REGISTRY_POLL = faults.register_point(
    "serving.registry.poll",
    description="background registry poll tick (refresh entry)",
)
_FP_REGISTRY_LOAD = faults.register_point(
    "serving.registry.load",
    description="one version's engine load (io action = transient read)",
)

_VERSION_RE = re.compile(r"^v-(\d{8})$")
_METADATA_FILE = "model-metadata.json"


def version_dirname(version: int) -> str:
    return f"v-{version:08d}"


def scan_versions(directory: str) -> list[tuple[int, str]]:
    """(version, path) for every published version, oldest first; tmp
    dirs and foreign names are ignored."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    out = []
    for name in names:
        m = _VERSION_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def champion_quality(directory: str) -> tuple[Optional[str], Optional[dict]]:
    """(version dirname, quality block) of the newest published version
    carrying recorded quality stats — the gate's champion. Versions
    without a quality block (pre-gate publishes, ungated nearline
    snapshots) are skipped, not treated as champions: a gate can only
    compare against error bars that were actually recorded."""
    from photon_ml_tpu.data.model_store import load_game_model_metadata

    for v, path in reversed(scan_versions(directory)):
        try:
            meta = load_game_model_metadata(path) or {}
        except (OSError, ValueError):
            continue
        quality = (meta.get("extra") or {}).get("quality")
        if quality:
            return version_dirname(v), quality
    return None, None


def _assemble_version(
    directory: str, name: str, model, index_maps: Mapping, extra_metadata
) -> str:
    """Assemble a complete version directory under a ``.tmp-`` sibling
    and rename it to ``name`` — the atomic-publish protocol shared by
    accepted and quarantined versions."""
    from photon_ml_tpu.data.index_map import IndexMap
    from photon_ml_tpu.data.model_store import save_game_model

    final = os.path.join(directory, name)
    tmp = os.path.join(directory, ".tmp-" + name)
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    for shard, imap in index_maps.items():
        if not isinstance(imap, IndexMap):
            imap = IndexMap(list(imap))
        imap.save(os.path.join(tmp, "feature-indexes", shard))
    # model-metadata.json lands last inside tmp (save_game_model order)
    save_game_model(model, tmp, extra_metadata=extra_metadata)
    os.rename(tmp, final)
    fsync_dir(directory)
    return final


def publish_version(
    directory: str,
    model,
    index_maps: Mapping,
    version: Optional[int] = None,
    extra_metadata: Optional[dict] = None,
    lineage: Optional[dict] = None,
    quality: Optional[dict] = None,
    gate_override: bool = False,
) -> str:
    """Atomically publish ``model`` as the next registry version.

    ``index_maps`` (shard name -> IndexMap or sequence of feature names)
    is REQUIRED: the registry refuses versions without a pinned feature
    space. The version directory is assembled in a ``.tmp-v-*`` sibling
    and renamed into place — watchers see the complete version or nothing.

    ``lineage`` (optional): a JSON-safe training-ancestry record
    (``base_version``, ``warm_start_checkpoint``, delta digest — see
    ``incremental.publish.lineage_record``) stored under the metadata
    ``"lineage"`` key; the loaded engine carries it and ``/healthz``
    serves it, so a running version is traceable to the checkpoint and
    delta that produced it.

    ``quality`` (optional) arms the champion/challenger gate: a JSON
    block with the candidate's :class:`photon_ml_tpu.quality.gate
    .QualityStats` fields (plus any bootstrap summaries). The candidate
    is compared against the newest published version with recorded
    stats; a candidate that regresses beyond the champion's bootstrap
    CI is assembled under ``quarantined-v-*`` (invisible to version
    scans, evidence preserved) and :class:`QualityGateRefused` is
    raised. The decision — publish, quarantine, or ``gate_override``
    bypass — is recorded in the metadata quality block AND in lineage
    (``quality_gate``), so ``/healthz`` serves it. ``quality=None``
    publishes ungated (back-compat; the nearline snapshot path).
    """
    if not index_maps:
        raise ValueError(
            "index_maps is required: a served version must pin the training "
            "feature space next to its coefficients"
        )
    decision = None
    if quality is not None:
        from photon_ml_tpu.quality.gate import (
            FP_PUBLISH_GATE,
            QualityGateRefused,
            QualityStats,
            decide_gate,
        )

        champ_version, champ_quality = champion_quality(directory)
        # the seam sits AFTER candidate stats and champion lookup but
        # BEFORE any write: a hard kill here must leave the registry
        # exactly as it was (tools/chaos.py --quality)
        faults.fault_point(FP_PUBLISH_GATE)
        decision = decide_gate(
            QualityStats.from_json(quality),
            champ_quality,
            champ_version,
            override=gate_override,
        )
        telemetry.counter(f"quality.gate_{decision.decision}").inc()
        extra_metadata = dict(extra_metadata or {})
        extra_metadata["quality"] = {
            **dict(quality), "gate": decision.to_json(),
        }
        if lineage is not None:
            lineage = dict(lineage)
            lineage["quality_gate"] = decision.to_json()
    if lineage is not None:
        extra_metadata = dict(extra_metadata or {})
        extra_metadata["lineage"] = dict(lineage)
    os.makedirs(directory, exist_ok=True)
    if version is None:
        existing = scan_versions(directory)
        version = existing[-1][0] + 1 if existing else 1
    final = os.path.join(directory, version_dirname(version))
    if os.path.exists(final):
        raise FileExistsError(f"version already published: {final}")
    if decision is not None and decision.decision == "quarantined":
        # park the refused candidate under a name version scans ignore:
        # the evidence (model + stats + decision) survives for offline
        # diagnosis, but no server will ever load it; repeated refusals
        # of the same slot keep the latest evidence
        stale = os.path.join(
            directory, "quarantined-" + version_dirname(version)
        )
        if os.path.exists(stale):
            shutil.rmtree(stale)
        qpath = _assemble_version(
            directory,
            "quarantined-" + version_dirname(version),
            model,
            index_maps,
            extra_metadata,
        )
        logger.warning(
            "quality gate quarantined candidate version %d: %s",
            version,
            decision.reason,
        )
        raise QualityGateRefused(decision, quarantine_path=qpath)
    return _assemble_version(
        directory, version_dirname(version), model, index_maps,
        extra_metadata,
    )


class ModelRegistry:
    """Background-refreshed source of the current :class:`ScoringEngine`."""

    def __init__(
        self,
        directory: str,
        max_batch: int = 64,
        max_row_nnz: int = 128,
        poll_interval: float = 2.0,
        warm: bool = True,
        load_retries: int = 2,
        retry_backoff_s: float = 0.1,
        mesh=None,
        entity_axis: Optional[str] = None,
    ):
        self.directory = directory
        self.max_batch = max_batch
        self.max_row_nnz = max_row_nnz
        self.poll_interval = poll_interval
        self.warm = warm
        # serve every loaded version ENTITY-SHARDED over this mesh (the
        # engine's mesh= path); hot swaps re-place the new version's
        # tables with the same sharding, so a swap never degrades a
        # sharded deployment to replicated
        self.mesh = mesh
        self.entity_axis = entity_axis
        # transient-IO retry budget per version load (a half-synced NFS
        # dir, a flaky read): retries back off retry_backoff_s * 2**k and
        # count serving.version_retries
        self.load_retries = load_retries
        self.retry_backoff_s = retry_backoff_s
        self._engine: Optional[ScoringEngine] = None
        self._version = -1
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (path -> mtime) of versions that failed DETERMINISTIC validation
        # (bad metadata, unservable model type): a persistently corrupt
        # newest version is skipped silently on later polls instead of
        # re-reading/re-warning every interval; retried when it changes.
        # Transient IO errors are deliberately NOT recorded here — one
        # flaky read must not mark a good version skipped forever.
        self._skipped: dict[str, float] = {}

    @property
    def engine(self) -> ScoringEngine:
        with self._lock:
            if self._engine is None:
                raise RuntimeError(
                    f"no valid model version loaded from {self.directory}"
                )
            return self._engine

    @property
    def current_version(self) -> Optional[str]:
        with self._lock:
            return self._engine.version if self._engine is not None else None

    # -- refresh -------------------------------------------------------------

    def refresh(self) -> bool:
        """Load the newest valid version newer than the current one.

        Walks newest-first and falls back past corrupt/partial/unloadable
        versions (missing metadata or feature-indexes, truncated npz,
        unsupported sub-model types) — the checkpoint-restore fallback.
        Returns True when a swap happened."""
        with self._lock:
            current = self._version
        for version, path in reversed(scan_versions(self.directory)):
            if version <= current:
                return False
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = -1.0
            if self._skipped.get(path) == mtime:
                continue  # known-bad and unchanged since the last attempt
            engine = self._load_version(version, path, mtime)
            if engine is None:
                continue
            with self._lock:
                self._skipped.pop(path, None)
                if version <= self._version:  # raced with another refresh
                    return False
                old = self._engine
                self._engine = engine
                self._version = version
            telemetry.counter("serving.model_swaps").inc()
            telemetry.gauge("serving.model_version").set(version)
            logger.info(
                "serving model version %s%s", engine.version,
                f" (replacing {old.version})" if old is not None else "",
            )
            return True
        return False

    def _load_version(
        self, version: int, path: str, mtime: float
    ) -> Optional[ScoringEngine]:
        """Load + warm one version, or None when it must be skipped.

        Failure handling distinguishes the two shapes a read can fail:

        - **transient IO** (``OSError``: a half-synced network dir, a
          flaky read) — retried up to ``load_retries`` times with
          exponential backoff (``serving.version_retries``); if it STILL
          fails, the version is skipped for this refresh only — the next
          poll retries from scratch, because one bad read must not pin a
          good version as skipped-by-mtime forever.
        - **deterministic validation** (``ValueError``/``TypeError``/
          ``KeyError``: corrupt metadata, unservable model type) — pinned
          in ``_skipped`` by mtime so later polls stop re-reading it
          until the directory changes.
        """
        last_transient: Optional[OSError] = None
        for attempt in range(self.load_retries + 1):
            try:
                faults.fault_point(_FP_REGISTRY_LOAD)
                engine = ScoringEngine.load(
                    path,
                    max_batch=self.max_batch,
                    max_row_nnz=self.max_row_nnz,
                    version=version_dirname(version),
                    mesh=self.mesh,
                    entity_axis=self.entity_axis,
                )
                if self.warm:
                    engine.warmup()
                return engine
            except OSError as e:
                last_transient = e
                if attempt < self.load_retries:
                    delay = self.retry_backoff_s * (2 ** attempt)
                    telemetry.counter("serving.version_retries").inc()
                    logger.warning(
                        "transient error loading model version %s "
                        "(attempt %d/%d, retrying in %.2fs): %s", path,
                        attempt + 1, self.load_retries + 1, delay, e,
                    )
                    if self._stop.wait(delay):
                        return None  # shutting down mid-backoff
            except (ValueError, TypeError, KeyError) as e:
                # ModelLoadError is a ValueError; TypeError an unservable
                # model. _skipped is shared with concurrent refresh()
                # callers (start() on the main thread vs the poll loop),
                # so its writes take the lock like every other registry
                # mutation (lint L015)
                with self._lock:
                    self._skipped[path] = mtime
                telemetry.counter("serving.skipped_versions").inc()
                logger.warning(
                    "skipping unusable model version %s: %s", path, e
                )
                return None
        telemetry.counter("serving.skipped_versions").inc()
        logger.warning(
            "model version %s still unreadable after %d attempt(s) — "
            "skipped for THIS refresh, retried next poll: %s", path,
            self.load_retries + 1, last_transient,
        )
        return None

    # -- background watcher --------------------------------------------------

    def start(self) -> "ModelRegistry":
        """Load the newest valid version NOW (raising if none exists) and
        start the background poll thread."""
        self.refresh()
        with self._lock:
            if self._engine is None:
                raise RuntimeError(
                    f"no valid model version under {self.directory}"
                )
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._poll_loop, name="model-registry", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                faults.fault_point(_FP_REGISTRY_POLL)
                self.refresh()
            except Exception:  # noqa: BLE001 — the watcher must survive
                logger.exception("model registry refresh failed")
