"""Serving front ends: a stdlib threading HTTP server and a stdio JSONL
mode (so tests and tier-1 CI drive the full request schema without
sockets).

Endpoints:

- ``POST /v1/score`` — body ``{"rows": [<row>, ...]}`` (see
  :mod:`photon_ml_tpu.serving.engine` for the row schema); responds
  ``{"scores": [...], "model_version": "v-..."}``. Requests flow through
  the :class:`MicroBatcher`, so concurrent callers share device batches.
  Overload -> 503 ``{"error": "overloaded"}``; malformed rows -> 400.
- ``GET /healthz`` — ``{"status", "model_version", "warm", "buckets"}``.
- ``GET /metricsz`` — the full telemetry ``snapshot()``.

The stdio mode reads one JSON object per stdin line (``{"rows": [...]}``
scores; ``{"op": "health"}`` / ``{"op": "metrics"}`` introspect) and
writes one JSON response line to stdout; it scores directly on the engine
(no batcher threads) so a driver loop is deterministic.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Mapping, Optional

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import requests as request_trace
from photon_ml_tpu.serving.batcher import (
    ContinuousBatcher,
    Draining,
    MicroBatcher,
    Overloaded,
)
from photon_ml_tpu.serving.engine import BadRequest, ScoringEngine

#: the Retry-After hint (seconds) on draining 503s — long enough for a
#: drain + relaunch, short enough that a router's next probe finds the
#: replacement
DRAIN_RETRY_AFTER_S = 2

logger = logging.getLogger("photon_ml_tpu.serving.server")


def _engine_of(source) -> ScoringEngine:
    """Accept a bare engine or anything with an ``.engine`` property
    (the ModelRegistry), so one front end serves both static and
    hot-swapped deployments."""
    return source.engine if hasattr(source, "engine") else source


def _metrics_payload() -> dict:
    """The ``/metricsz`` body: the metrics snapshot plus the executable
    registry (per-bucket/per-hot-path compile time + cost analysis)."""
    payload = dict(telemetry.snapshot())
    payload["xla_executables"] = telemetry.XLA_REGISTRY.snapshot()
    return payload


def _json_scores(result: Mapping) -> dict:
    """Shape one batcher result for the wire (shared by the threading
    and asyncio front ends)."""
    return {
        # host-side already: the batcher future resolves to a numpy
        # slice the engine fetched through sync_fetch — float() here
        # is JSON shaping of host scalars, not a device crossing
        "scores": [round(float(s), 8) for s in result["scores"]],  # photon: noqa[L013]
        "model_version": result["model_version"],
    }


class ScoringService:
    """Engine-or-registry + batcher glue shared by the threading HTTP,
    asyncio HTTP, and stdio front ends.

    The batcher's scorer resolves the CURRENT engine at dispatch time, so
    a registry swap takes effect on the next batch while the batch already
    in flight finishes on the engine reference it grabbed.

    ``batcher="continuous"`` swaps the fixed-deadline
    :class:`MicroBatcher` for the :class:`ContinuousBatcher` (admit rows
    into the next in-flight bucket as device capacity frees — the async
    front end's default scheduler). :meth:`health` and
    :meth:`metrics` never touch the batcher or its locks: a wedged or
    saturated scoring path must not take the health surface down with it
    (asserted by a responsiveness test)."""

    # class-level defaults so hand-assembled instances (tests build
    # wedged services via ``__new__`` to inject custom scorers) admit
    # requests and skip the commit hook without tripping on attributes
    # __init__ would have set
    _draining = False
    on_commit = None

    def __init__(
        self,
        source,
        max_batch: int = 64,
        max_delay_ms: float = 5.0,
        queue_depth: int = 256,
        request_timeout_s: float = 30.0,
        batcher: str = "deadline",
    ):
        self._source = source
        self.request_timeout_s = request_timeout_s
        if batcher not in ("deadline", "continuous"):
            raise ValueError(
                f"batcher must be 'deadline' or 'continuous', got {batcher!r}"
            )
        batcher_cls = (
            ContinuousBatcher if batcher == "continuous" else MicroBatcher
        )
        self._batcher = batcher_cls(
            self._score,
            max_batch=max_batch,
            max_delay_ms=max_delay_ms,
            queue_depth=queue_depth,
        )
        self._updater = None
        self._draining = False
        # fleet-member hook: called after a successful /v1/admin/commit
        # with (key, payload) so the owner re-announces at the new size
        self.on_commit = None

    def _score(self, rows):
        engine = _engine_of(self._source)
        return engine.score_rows(rows), engine.version

    def start(self) -> "ScoringService":
        self._batcher.start()
        if self._updater is not None:
            self._updater.start()
        return self

    def stop(self) -> None:
        self._batcher.stop()
        if self._updater is not None:
            self._updater.stop()

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """The graceful-stop half of the training ``GracefulStop``
        contract, serving-side: close admission FIRST (new requests get
        :class:`Draining` -> 503 + ``Retry-After``), then drain —
        ``batcher.stop()`` joins the dispatcher only after every
        already-admitted unit has been scored and delivered. Idempotent;
        safe from a signal-handling thread."""
        self._draining = True
        telemetry.counter("serving.drains").inc()
        self._batcher.stop()
        if self._updater is not None:
            self._updater.stop()

    # -- nearline ------------------------------------------------------------

    def attach_nearline(self, updater) -> "ScoringService":
        """Attach a :class:`~photon_ml_tpu.serving.nearline
        .NearlineUpdater`; both front ends then accept ``POST
        /v1/update`` events, and the updater's lifecycle follows the
        service's."""
        self._updater = updater
        return self

    def update_request(self, payload: Mapping) -> dict:
        """Handle one ``/v1/update`` body: ``{"events": [...]}`` (see
        serving/nearline.py for the event schema)."""
        if self._draining:
            raise Draining("server is draining; retry elsewhere")
        if self._updater is None:
            raise BadRequest(
                "nearline updates are not enabled on this server"
            )
        events = (
            payload.get("events") if isinstance(payload, Mapping) else None
        )
        if not isinstance(events, list):
            raise BadRequest('request body must be {"events": [...]}')
        accepted = self._updater.submit(events)
        return {"accepted": accepted}

    # -- scoring -------------------------------------------------------------

    def submit_rows(self, payload: Mapping, ctx=None):
        """Validate one ``/v1/score`` body and enqueue it; the batcher
        Future (resolves to ``{"scores", "model_version"}``). Shared by
        the blocking (:meth:`score_request`) and asyncio front ends.
        ``ctx`` is the inbound trace context (``X-Photon-Trace``); the
        batcher carries it through queue wait and dispatch."""
        if self._draining:
            raise Draining("server is draining; retry elsewhere")
        rows = payload.get("rows") if isinstance(payload, Mapping) else None
        if not isinstance(rows, list):
            raise BadRequest('request body must be {"rows": [...]}')
        return self._batcher.submit(rows, ctx=ctx)

    # -- fleet-member endpoints ----------------------------------------------

    def margin_request(self, payload: Mapping, ctx=None) -> dict:
        """One ``/v1/margins`` body — the router's fan-out unit:
        ``{"rows": [...], "include_fixed": [bool, ...]?, "fleet_size":
        N?, "version": "v-..."?}``. Scores DIRECTLY on the resolved
        engine (router batches upstream; re-coalescing here would add a
        deadline per member). Full-precision margins: the router's fold
        is exact, so no wire rounding.

        ``ctx`` is the router's propagated trace context: the member-side
        record (engine-dispatch phase + ``{version, nearline_seq,
        fleet_size}``) carries its ids, so the fleet report joins this
        hop under the router's tree."""
        rec = request_trace.begin("margins", ctx=ctx, role="member")
        try:
            return self._margin_request(payload, rec)
        except Exception as e:
            request_trace.finish(
                rec, status="error", error=f"{type(e).__name__}: {e}"
            )
            raise

    def _margin_request(self, payload: Mapping, rec) -> dict:
        if self._draining:
            raise Draining("server is draining; retry elsewhere")
        if not isinstance(payload, Mapping):
            raise BadRequest('request body must be {"rows": [...]}')
        rows = payload.get("rows")
        if not isinstance(rows, list):
            raise BadRequest('request body must be {"rows": [...]}')
        engine = self._resolve_engine(payload)
        include_fixed = payload.get("include_fixed")
        if include_fixed is not None and not isinstance(include_fixed, list):
            raise BadRequest("include_fixed must be a list of booleans")
        telemetry.counter("serving.requests").inc()
        t0 = time.monotonic()
        margins = engine.margin_rows(rows, include_fixed)
        if rec is not None:
            rec.phase(
                "engine_dispatch",
                (time.monotonic() - t0) * 1000.0,
                ts=request_trace.trace_time(t0),
            )
            attrs = (
                engine.request_attrs()
                if hasattr(engine, "request_attrs")
                else {"version": engine.version}
            )
            fleet_size = payload.get("fleet_size")
            if fleet_size is not None:
                attrs["fleet_size"] = fleet_size
            rec.set_attr(rows=len(rows), **attrs)
        request_trace.finish(rec)
        return {
            # host numpy from the engine's sync_fetch; float() is JSON
            # shaping, not a device crossing
            "margins": [float(m) for m in margins],
            "model_version": engine.version,
        }

    def admin_request(self, op: str, payload: Mapping) -> dict:
        """``/v1/admin/stage`` / ``/v1/admin/commit`` — the fleet
        resize/hot-swap barrier on a shard member. Stage loads + warms a
        ``(fleet_size, version)`` slice while the current one serves;
        commit flips to a staged key (and re-announces via
        ``on_commit``). Only meaningful when the source is a
        :class:`~photon_ml_tpu.serving.shard.ShardMemberSource`."""
        src = self._source
        if not (hasattr(src, "stage") and hasattr(src, "commit")):
            raise BadRequest(
                "this server is not a shard-owning fleet member"
            )
        if not isinstance(payload, Mapping):
            raise BadRequest("admin body must be a JSON object")
        try:
            fleet_size = int(payload["fleet_size"])
        except (KeyError, TypeError, ValueError):
            raise BadRequest(
                'admin body must carry an integer "fleet_size"'
            ) from None
        if op == "stage":
            key = src.stage(fleet_size, payload.get("version"))
            return {"staged": {"fleet_size": key[0], "version": key[1]}}
        version = payload.get("version")
        if not version:
            raise BadRequest('commit requires an explicit "version"')
        key = src.commit(fleet_size, str(version))
        if self.on_commit is not None:
            self.on_commit(key, payload)
        return {"committed": {"fleet_size": key[0], "version": key[1]}}

    def _resolve_engine(self, payload: Mapping):
        """The engine a margin request is pinned to: a shard member
        resolves ``(fleet_size, version)`` through its staged set
        (KeyError -> HTTP 409, the mixed-swap-window signal); everything
        else serves its current engine."""
        src = self._source
        if hasattr(src, "resolve"):
            return src.resolve(
                payload.get("fleet_size"), payload.get("version")
            )
        return _engine_of(src)

    def score_request(self, payload: Mapping, ctx=None) -> dict:
        future = self.submit_rows(payload, ctx=ctx)
        try:
            result = future.result(timeout=self.request_timeout_s)
        except FutureTimeout:
            # nobody will read this result: cancel so the dispatcher drops
            # the unit instead of scoring dead work under overload
            future.cancel()
            raise
        return _json_scores(result)

    def metrics(self) -> dict:
        """The ``/metricsz`` body — reads telemetry registries only,
        never the batcher (stays responsive mid-warmup / mid-swap)."""
        return _metrics_payload()

    def health(self) -> dict:
        try:
            engine = _engine_of(self._source)
        except RuntimeError as e:
            return {"status": "loading", "model_version": None,
                    "warm": False, "detail": str(e)}
        state = {
            "status": "draining" if self._draining else "serving",
            "model_version": engine.version,
            "warm": engine.warm,
            "buckets": list(engine.bucket_sizes),
            "task": engine.task,
        }
        if getattr(engine, "entity_axis", None) is not None:
            # entity-sharded deployment: which axis the RE tables span
            state["entity_axis"] = engine.entity_axis
        if getattr(engine, "nearline_seq", 0):
            state["nearline_seq"] = engine.nearline_seq
        if getattr(engine, "lineage", None):
            # training ancestry of the served version (incremental
            # retrains: base checkpoint + delta digest, registry lineage)
            state["lineage"] = engine.lineage
        if engine.warm:
            # per-batch-bucket compile time + cost from the executable
            # registry (telemetry.xla) — which bucket executables exist,
            # what each cost to compile, and their per-call FLOPs
            state["compile"] = engine.compile_summary()
        return state


class _Handler(BaseHTTPRequestHandler):
    server_version = "photon-serve/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: requests go to telemetry
        logger.debug(fmt, *args)

    def _reply(self, code: int, obj, headers: Optional[dict] = None) -> None:
        body = json.dumps(obj, default=float).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        service: ScoringService = self.server.service  # type: ignore[attr-defined]
        if self.path == "/healthz":
            self._reply(200, service.health())
        elif self.path == "/metricsz":
            self._reply(200, _metrics_payload())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    _POST_PATHS = (
        "/v1/score", "/v1/update", "/v1/margins",
        "/v1/admin/stage", "/v1/admin/commit",
    )

    def do_POST(self):  # noqa: N802
        service: ScoringService = self.server.service  # type: ignore[attr-defined]
        if self.path not in self._POST_PATHS:
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
        except ValueError:
            self._reply(400, {"error": "bad_request",
                              "detail": "body is not valid JSON"})
            return
        # the inbound trace context (router fan-out propagation); a
        # malformed header parses to None and the request proceeds
        ctx = request_trace.parse_header(
            self.headers.get(request_trace.TRACE_HEADER)
        )
        try:
            if self.path == "/v1/update":
                self._reply(200, service.update_request(payload))
            elif self.path == "/v1/margins":
                self._reply(200, service.margin_request(payload, ctx=ctx))
            elif self.path.startswith("/v1/admin/"):
                op = self.path.rsplit("/", 1)[1]
                self._reply(200, service.admin_request(op, payload))
            else:
                self._reply(200, service.score_request(payload, ctx=ctx))
        except Draining as e:
            self._reply(
                503, {"error": "draining", "detail": str(e)},
                headers={"Retry-After": str(DRAIN_RETRY_AFTER_S)},
            )
        except Overloaded as e:
            self._reply(503, {"error": "overloaded", "detail": str(e)})
        except BadRequest as e:
            self._reply(400, {"error": "bad_request", "detail": str(e)})
        except KeyError as e:
            # a margin request pinned to a (fleet_size, version) this
            # member does not hold — the mixed-swap window; the router
            # sheds this member for the request instead of blending
            self._reply(409, {"error": "version_unavailable",
                              "detail": str(e)})
        except FutureTimeout:
            self._reply(504, {"error": "timeout"})
        except Exception as e:  # noqa: BLE001 — a request must not kill the server
            logger.exception("score request failed")
            self._reply(500, {"error": "internal", "detail": str(e)})


class ScoringServer:
    """``ThreadingHTTPServer`` wrapper owning the service lifecycle."""

    def __init__(self, service: ScoringService, host: str = "127.0.0.1",
                 port: int = 8080):
        self.service = service
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.service = service  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "ScoringServer":
        self.service.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="scoring-http", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.service.stop()


def serve_stdio(source, inp, out) -> int:
    """JSONL request/response loop over text streams (no sockets, no
    batcher threads — deterministic for CI drivers). Returns 0 at EOF."""
    for line in inp:
        line = line.strip()
        if not line:
            continue
        try:
            request = json.loads(line)
        except ValueError as e:
            out.write(json.dumps({"error": f"bad JSON: {e}"}) + "\n")
            out.flush()
            continue
        try:
            op = request.get("op") if isinstance(request, Mapping) else None
            if op == "health":
                engine = _engine_of(source)
                response = {
                    "status": "serving",
                    "model_version": engine.version,
                    "warm": engine.warm,
                    "buckets": list(engine.bucket_sizes),
                }
                if engine.warm:
                    response["compile"] = engine.compile_summary()
            elif op == "metrics":
                response = _metrics_payload()
            else:
                rows = (
                    request.get("rows")
                    if isinstance(request, Mapping) else None
                )
                if not isinstance(rows, list):
                    raise BadRequest(
                        'each line must be {"rows": [...]} or {"op": ...}'
                    )
                engine = _engine_of(source)
                telemetry.counter("serving.requests").inc()
                scores = engine.score_rows(rows)
                response = {
                    "scores": [round(float(s), 8) for s in scores],
                    "model_version": engine.version,
                }
        except (BadRequest, ValueError, RuntimeError) as e:
            response = {"error": str(e)}
        out.write(json.dumps(response) + "\n")
        out.flush()
    return 0
