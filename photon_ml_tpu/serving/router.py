"""The serving-fleet routing front end: split each request's entity
lookups across shard-owning members, fold the partial margins exactly,
degrade — never fail — on partial fleet loss.

The GAME score is a SUM of per-coordinate margins, so routed scoring is
lossless: every entity's rows live on exactly ONE member (contiguous
code blocks, ``parallel.sharding.owner_of_row``), each owning member
returns its partial margin, one designated member per row adds the
fixed-effect margin (``include_fixed`` — FE vectors are replicated so
ANY member can), and the router folds partials in f64, adds the offset
once, and applies the link host-side. No jax on this path: the router
is pure numpy + stdlib HTTP, so a routing tier needs no accelerator.

Degraded mode: an unreachable member's entities fall back to
fixed-effect-only — the established unseen-entity semantics — counted
per affected row as ``serving.degraded_scores``. A row's FE margin
retries on any alive member, so partial fleet loss sheds ACCURACY
(bounded, observable) but never availability while one member lives.

Fleet discovery is file-based: each member atomically writes
``member-<i>.json`` into the announce directory when its slice is warm.
The router adopts the highest ``epoch`` whose member set is COMPLETE
(all of ``0..fleet_size-1`` ready) and swaps its ownership view
atomically (``serving.resize_swap``) — a live resize is: new members
announce at the next epoch, the view flips once, old members drain.
Requests are pinned to the view's registry version, so a mid-swap
member either serves the pinned version (staged or committed) or is
treated as unavailable for that request — mixed-version windows can
shed, never blend coefficients from two versions in one score.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_ml_tpu import faults, telemetry
from photon_ml_tpu.parallel.sharding import owner_of_row
from photon_ml_tpu.telemetry import requests as request_trace
from photon_ml_tpu.utils.atomic import atomic_write_json

_FP_ROUTE_FANOUT = faults.register_point(
    "serving.route_fanout",
    distributed=True,
    description=(
        "one member's margin fan-out call from the router — io action = "
        "the member unreachable for that batch (degraded, never failed)"
    ),
)
_FP_RESIZE_SWAP = faults.register_point(
    "serving.resize_swap",
    distributed=True,
    description=(
        "the router's atomic ownership-map swap at a fleet resize / "
        "epoch flip — a failed swap keeps the old map serving"
    ),
)

#: link functions the router applies host-side after the fold — the
#: numpy mirror of the engine's post-link (``get_loss(task).name``)
_LINKS = {
    "logistic": lambda s: 1.0 / (1.0 + np.exp(-s)),
    "poisson": np.exp,
}


class FleetUnavailable(RuntimeError):
    """No fleet member could serve any part of a request — total fleet
    loss (or no complete epoch announced yet). Partial loss never raises
    this; it degrades."""


class _MemberUnavailable(RuntimeError):
    """One member failed a fan-out call past its retry budget."""


# ---------------------------------------------------------------------------
# announce files: how members and router find each other
# ---------------------------------------------------------------------------


def announce_path(announce_dir: str, member: int) -> str:
    return os.path.join(announce_dir, f"member-{int(member)}.json")


def write_announce(announce_dir: str, payload: Mapping) -> str:
    """Atomically publish one member's announce record (the member calls
    this AFTER its slice is warm — announcing is the readiness barrier).
    Required keys: member, fleet_size, epoch, url, version."""
    os.makedirs(announce_dir, exist_ok=True)
    path = announce_path(announce_dir, int(payload["member"]))
    atomic_write_json(path, dict(payload), indent=2, sort_keys=True)
    return path


def scan_announce(announce_dir: str) -> list[dict]:
    """Every parseable announce record in ``announce_dir`` — a member
    killed mid-write leaves a torn file, which reads as absent."""
    out = []
    try:
        names = os.listdir(announce_dir)
    except FileNotFoundError:
        return out
    for name in sorted(names):
        if not (name.startswith("member-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(announce_dir, name)) as fh:
                rec = json.load(fh)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and "member" in rec:
            out.append(rec)
    return out


def fleet_lookups_from_version_dir(version_dir: str):
    """``(task, link, {id_name: {value: code}})`` read numpy+json-only
    from a published registry version — the router's share of the model:
    entity vocabularies (for ownership) and the task link, no
    coefficients."""
    with open(os.path.join(version_dir, "model-metadata.json")) as fh:
        meta = json.load(fh)
    from photon_ml_tpu.ops.losses import get_loss

    task = meta["task"]
    link = get_loss(task).name
    lookups: dict[str, dict] = {}
    for name, spec in (meta.get("coordinates") or {}).items():
        if spec.get("type") != "random_effect":
            continue
        with np.load(
            os.path.join(version_dir, "random-effect", name, "model.npz")
        ) as z:
            vocab = z["vocab"]
        id_name = spec["id_name"]
        table = {str(v): i for i, v in enumerate(vocab.tolist())}
        if id_name in lookups and lookups[id_name] != table:
            raise ValueError(
                f"coordinates disagree on the '{id_name}' vocabulary — "
                "the router cannot derive one ownership map"
            )
        lookups[id_name] = table
    return task, link, lookups


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FleetView:
    """One immutable ownership snapshot: requests read whichever view
    was current when they started — a resize swaps the reference, never
    mutates a view in place."""

    epoch: int
    fleet_size: int
    version: str
    endpoints: tuple  # member index -> base url


class FleetRouter:
    """Engine-shaped fleet scorer: ``score_rows(rows)`` like
    :class:`~photon_ml_tpu.serving.engine.ScoringEngine`, so the whole
    existing front-end stack (service, batchers, HTTP/asyncio servers)
    serves a fleet by swapping in a router where an engine went.

    ``lookups`` maps ``id_name -> {entity value: training code}`` (the
    ownership inputs; see :func:`fleet_lookups_from_version_dir`).
    ``link`` is the post-fold link function name (engine parity)."""

    def __init__(
        self,
        announce_dir: str,
        lookups: Mapping[str, Mapping[str, int]],
        task: str = "logistic",
        link: Optional[str] = None,
        member_timeout_s: float = 5.0,
        retries: int = 1,
        backoff_s: float = 0.05,
        refresh_interval_s: float = 0.5,
        cooldown_s: float = 1.0,
        max_batch: int = 1024,
        sample_every: int = 0,
    ):
        self.announce_dir = announce_dir
        self._lookups = {
            name: dict(table) for name, table in dict(lookups).items()
        }
        self._num_entities = {
            name: len(table) for name, table in self._lookups.items()
        }
        self.task = task
        # the post-fold link defaults to the task name (the engine's
        # get_loss(task).name for the canonical task spellings); unknown
        # names fold to identity, matching the engine's else-branch
        self._link = task if link is None else link
        self.member_timeout_s = float(member_timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.refresh_interval_s = float(refresh_interval_s)
        self.cooldown_s = float(cooldown_s)
        # engine-shaped surface (health/metrics/front ends)
        self.max_batch = int(max_batch)
        self.max_row_nnz = None
        self.bucket_sizes = (int(max_batch),)
        self.warm = True
        self.entity_axis = None
        self.nearline_seq = 0
        self.lineage = None
        # mark every Nth routed batch explicitly sampled (its full trace
        # persists on router AND members via header propagation); 0 = off
        self.sample_every = int(sample_every)
        self._req_seq = itertools.count(1)
        self._view: Optional[FleetView] = None
        self._view_lock = threading.Lock()
        self._down_until: dict[int, float] = {}
        self._next_refresh = 0.0
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="fleet-router"
        )

    # -- fleet view ----------------------------------------------------------

    @property
    def version(self) -> str:
        view = self._view
        return view.version if view is not None else "fleet-unannounced"

    @property
    def view(self) -> Optional[FleetView]:
        return self._view

    def compile_summary(self) -> dict:
        return {}

    def refresh(self) -> Optional[FleetView]:
        """Re-scan the announce directory; adopt the newest COMPLETE
        epoch (atomic ownership swap through ``serving.resize_swap``
        when the epoch/fleet size changes). Safe to call from any
        thread; also called lazily from the request path on a cadence."""
        records = scan_announce(self.announce_dir)
        by_epoch: dict[tuple[int, int], dict[int, dict]] = {}
        for rec in records:
            try:
                key = (int(rec.get("epoch", 0)), int(rec["fleet_size"]))
                member = int(rec["member"])
            except (TypeError, ValueError, KeyError):
                continue
            if rec.get("ready", True) and "url" in rec:
                by_epoch.setdefault(key, {})[member] = rec
        for (epoch, fleet_size), members in sorted(
            by_epoch.items(), reverse=True
        ):
            if set(members) != set(range(fleet_size)):
                continue  # incomplete epoch: keep serving the old view
            version = members[0].get("version", "unversioned")
            view = FleetView(
                epoch=epoch,
                fleet_size=fleet_size,
                version=str(version),
                endpoints=tuple(
                    str(members[i]["url"]) for i in range(fleet_size)
                ),
            )
            return self._adopt(view)
        return self._view

    def _adopt(self, view: FleetView) -> Optional[FleetView]:
        with self._view_lock:
            old = self._view
            if old == view:
                return old
            if old is None or (old.epoch, old.fleet_size) != (
                view.epoch, view.fleet_size,
            ):
                try:
                    # the swap seam: an injected failure here must leave
                    # the OLD ownership map serving untouched
                    faults.fault_point(_FP_RESIZE_SWAP)
                except (faults.InjectedFault, faults.InjectedIOError):
                    telemetry.counter("serving.resize_swap_failures").inc()
                    return old
                telemetry.counter("serving.resize_swaps").inc()
            self._view = view  # the atomic ownership swap
            self._down_until.clear()
            return view

    def _current_view(self) -> FleetView:
        now = time.monotonic()
        if now >= self._next_refresh or self._view is None:
            self._next_refresh = now + self.refresh_interval_s
            self.refresh()
        view = self._view
        if view is None:
            raise FleetUnavailable(
                f"no complete serving-fleet epoch announced under "
                f"{self.announce_dir}"
            )
        return view

    def members_status(self) -> dict[int, dict]:
        """Per-member router's-eye liveness for the status surface:
        cooldown/degraded state plus the fan-out RTT summary (the
        ``serving.fanout_rtt_ms.m<i>`` histogram) — what the supervisor
        publishes into ``/statusz``."""
        view = self._view
        if view is None:
            return {}
        now = time.monotonic()
        out: dict[int, dict] = {}
        hists = telemetry.snapshot().get("histograms", {})
        for m in range(view.fleet_size):
            until = self._down_until.get(m, 0.0)
            entry: dict = {
                "url": view.endpoints[m],
                "cooling_down": until > now,
                "cooldown_remaining_s": round(max(0.0, until - now), 3),
                # cooldown IS the router's degraded signal: rows owned by
                # a cooling member shed to FE-only until it recovers
                "degraded": until > now,
            }
            rtt = hists.get(f"serving.fanout_rtt_ms.m{m}")
            if rtt:
                entry["fanout_rtt_ms"] = rtt
            out[m] = entry
        return out

    # -- request path --------------------------------------------------------

    def score_rows(
        self,
        rows: Sequence[Mapping],
        ctx: Optional[request_trace.TraceContext] = None,
    ) -> np.ndarray:
        """Mean predictions for ``rows`` — the
        ``ScoringEngine.score_rows`` contract, served by the fleet.

        The router is the MINTING end of request tracing: when no
        inbound ``ctx`` arrives it creates one per routed batch and
        propagates it to every member over ``X-Photon-Trace``, so the
        member-side spans join this call's record by ``trace_id``."""
        if not rows:
            return np.zeros((0,), np.float32)
        if ctx is None:
            sampled = (
                self.sample_every > 0
                and next(self._req_seq) % self.sample_every == 0
            )
            ctx = request_trace.make_context(sampled=sampled)
        rec = request_trace.begin(
            "route", ctx=ctx, role="router", rows=len(rows)
        )
        try:
            view = self._current_view()
        except FleetUnavailable as e:
            request_trace.finish(rec, status="error", error=str(e))
            raise
        if rec is not None:
            rec.set_attr(
                fleet_size=view.fleet_size,
                version=view.version,
                epoch=view.epoch,
            )
        try:
            scores = self._score_routed(rows, view, ctx, rec)
        except FleetUnavailable as e:
            request_trace.finish(rec, status="error", error=str(e))
            raise
        request_trace.finish(rec)
        return scores

    def _score_routed(
        self,
        rows: Sequence[Mapping],
        view: FleetView,
        ctx: Optional[request_trace.TraceContext],
        rec,
    ) -> np.ndarray:
        n, fleet = len(rows), view.fleet_size
        offsets = np.zeros((n,), np.float64)
        # plan: row -> owning members (one per entity) + one FE owner
        member_rows: dict[int, list[int]] = {}
        member_fe: dict[int, list[bool]] = {}
        fe_owner = np.empty((n,), np.int64)
        for i, row in enumerate(rows):
            try:
                offsets[i] = float(row.get("offset") or 0.0)
            except (TypeError, ValueError, AttributeError):
                offsets[i] = 0.0  # the member rejects the malformed row
            ids = row.get("ids") if isinstance(row, Mapping) else None
            owners = set()
            for id_name, table in self._lookups.items():
                value = (ids or {}).get(id_name)
                if value is None:
                    continue
                code = table.get(str(value))
                if code is None:
                    continue  # unseen entity: FE-only everywhere
                owners.add(
                    owner_of_row(self._num_entities[id_name], code, fleet)
                )
            fe_owner[i] = min(owners) if owners else i % fleet
            for m in owners | {int(fe_owner[i])}:
                member_rows.setdefault(m, []).append(i)
                # plain bool: this list is json-serialized onto the wire
                member_fe.setdefault(m, []).append(bool(m == fe_owner[i]))
        t_fanout = time.monotonic()
        futures = {
            m: self._pool.submit(
                self._call_member,
                view,
                m,
                [self._sub_row(rows[i]) for i in idxs],
                member_fe[m],
                ctx,
                rec,
            )
            for m, idxs in member_rows.items()
        }
        totals = np.zeros((n,), np.float64)
        degraded = np.zeros((n,), bool)
        fe_orphans: list[int] = []
        failed: set[int] = set()
        for m, fut in futures.items():
            idxs = member_rows[m]
            try:
                margins = fut.result()
                totals[idxs] += np.asarray(margins, np.float64)
            except _MemberUnavailable:
                failed.add(m)
                telemetry.counter("serving.member_failures").inc()
                for i, had_fe in zip(idxs, member_fe[m]):
                    if had_fe:
                        fe_orphans.append(i)
                    # only LOST ENTITY margins are accuracy shed — a
                    # losslessly-retried FE designate is not degraded
                    if self._row_had_entities(rows[i], m, fleet):
                        degraded[i] = True
        if rec is not None:
            rec.phase(
                "fanout",
                (time.monotonic() - t_fanout) * 1000.0,
                ts=request_trace.trace_time(t_fanout),
            )
        t_fold = time.monotonic()
        if fe_orphans:
            totals[fe_orphans] += self._fe_fallback(
                view, [rows[i] for i in fe_orphans], failed, ctx, rec
            )
        shed = int(np.count_nonzero(degraded))
        if shed:
            telemetry.counter("serving.degraded_scores").inc(shed)
        telemetry.counter("serving.routed_rows").inc(n)
        scores = totals + offsets
        link_fn = _LINKS.get(self._link)
        if link_fn is not None:
            scores = link_fn(scores)
        if rec is not None:
            rec.phase(
                "fold",
                (time.monotonic() - t_fold) * 1000.0,
                ts=request_trace.trace_time(t_fold),
            )
            rec.set_attr(
                degraded=bool(shed),
                members=sorted(member_rows),
                failed_members=sorted(failed),
            )
        return np.asarray(scores, np.float32)

    @staticmethod
    def _sub_row(row) -> dict:
        """A member-bound copy of ``row``: the offset stays host-side
        (added once, after the fold)."""
        if not isinstance(row, Mapping):
            return {"features": {}}
        return {k: v for k, v in row.items() if k != "offset"}

    def _row_had_entities(self, row, member: int, fleet: int) -> bool:
        """Did ``member`` own any of ``row``'s entities (vs being only
        its FE designate)? Distinguishes real accuracy shed from a
        losslessly-retried FE margin."""
        ids = row.get("ids") if isinstance(row, Mapping) else None
        if not ids:
            return False
        for id_name, table in self._lookups.items():
            value = ids.get(id_name)
            if value is None:
                continue
            code = table.get(str(value))
            if code is None:
                continue
            if owner_of_row(self._num_entities[id_name], code, fleet) == member:
                return True
        return False

    def _fe_fallback(
        self,
        view: FleetView,
        rows: Sequence[Mapping],
        failed: set,
        ctx: Optional[request_trace.TraceContext] = None,
        rec=None,
    ) -> np.ndarray:
        """Fixed-effect margins for rows whose FE designate died,
        retried on any alive member (FE vectors are replicated; ids are
        STRIPPED so no member double-counts entity margins it already
        returned). Total fleet loss is the one unservable case."""
        stripped = [
            {k: v for k, v in self._sub_row(r).items() if k != "ids"}
            for r in rows
        ]
        last_err: Optional[Exception] = None
        for m in range(view.fleet_size):
            if m in failed:
                continue
            try:
                margins = self._call_member(
                    view, m, stripped, [True] * len(stripped), ctx, rec
                )
                return np.asarray(margins, np.float64)
            except _MemberUnavailable as e:
                failed.add(m)
                telemetry.counter("serving.member_failures").inc()
                last_err = e
        raise FleetUnavailable(
            f"every member of fleet epoch {view.epoch} is unreachable"
        ) from last_err

    def _call_member(
        self,
        view: FleetView,
        member: int,
        sub_rows: list,
        include_fixed: list,
        ctx: Optional[request_trace.TraceContext] = None,
        rec=None,
    ) -> list:
        """One member's margin batch, with bounded retry/backoff and a
        down-cooldown so a dead member costs one timeout per cooldown
        window, not per request. Each attempt's RTT lands in the
        per-member ``serving.fanout_rtt_ms.m<i>`` histogram; the call's
        total wall time becomes a ``member<i>_rtt`` phase of ``rec``
        (appended from the pool thread — list append is GIL-atomic)."""
        now = time.monotonic()
        if self._down_until.get(member, 0.0) > now:
            raise _MemberUnavailable(f"member {member} cooling down")
        try:
            faults.fault_point(_FP_ROUTE_FANOUT)
        except (faults.InjectedFault, faults.InjectedIOError) as e:
            # the seam's contract: an injected fan-out failure IS a
            # member unreachable for this batch — degraded, never failed
            self._down_until[member] = time.monotonic() + self.cooldown_s
            raise _MemberUnavailable(
                f"member {member} fan-out fault: {e}"
            ) from e
        headers = {"Content-Type": "application/json"}
        if ctx is not None:
            headers[request_trace.TRACE_HEADER] = ctx.to_header()
        body = json.dumps({
            "rows": sub_rows,
            "include_fixed": include_fixed,
            "fleet_size": view.fleet_size,
            "version": view.version,
        }).encode()
        url = view.endpoints[member] + "/v1/margins"
        rtt_hist = telemetry.histogram(f"serving.fanout_rtt_ms.m{member}")
        t_call = time.monotonic()

        def _rtt_phase() -> None:
            if rec is not None:
                rec.phase(
                    f"member{member}_rtt",
                    (time.monotonic() - t_call) * 1000.0,
                    ts=request_trace.trace_time(t_call),
                )

        last_err: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            t_attempt = time.monotonic()
            try:
                req = urllib.request.Request(
                    url, data=body, headers=headers,
                )
                with urllib.request.urlopen(
                    req, timeout=self.member_timeout_s
                ) as resp:
                    payload = json.loads(resp.read())
                rtt_hist.observe((time.monotonic() - t_attempt) * 1000.0)
                self._down_until.pop(member, None)
                margins = payload["margins"]
                if len(margins) != len(sub_rows):
                    raise _MemberUnavailable(
                        f"member {member} returned {len(margins)} margins "
                        f"for {len(sub_rows)} rows"
                    )
                _rtt_phase()
                return margins
            except urllib.error.HTTPError as e:
                # 409: the member holds no engine for our pinned
                # (fleet_size, version) — a mixed-swap window; shed this
                # member for the request rather than blend versions
                rtt_hist.observe((time.monotonic() - t_attempt) * 1000.0)
                last_err = e
                if e.code == 409:
                    break
            except (OSError, ValueError, KeyError) as e:
                # a timeout's RTT is as real as a success's — without it
                # the histogram hides exactly the calls that hurt
                rtt_hist.observe((time.monotonic() - t_attempt) * 1000.0)
                last_err = e
            if attempt < self.retries:
                time.sleep(self.backoff_s * (2 ** attempt))
        self._down_until[member] = time.monotonic() + self.cooldown_s
        _rtt_phase()
        raise _MemberUnavailable(
            f"member {member} at {url}: {last_err}"
        ) from last_err

    def close(self):
        self._pool.shutdown(wait=False)
